"""Scheduler ComponentConfig: KubeSchedulerConfiguration-shaped setup.

Mirrors pkg/scheduler/apis/config/types.go:37-98 (KubeSchedulerConfiguration
+ KubeSchedulerProfile :100-138, Plugins enable/disable :176-232) and the
defaulting in apis/config/v1/default_plugins.go:30, reduced to the knobs
this framework actually consumes:

- per-profile scheduler name, plugin enable/disable by extension-point-free
  name (our plugin objects carry all their extension points), plugin
  weights (MultiPoint weights, default_plugins.go:93), and the scoring
  strategy (NodeResourcesFitArgs.ScoringStrategy, types_pluginargs.go).
- queue tuning: podInitialBackoffSeconds / podMaxBackoffSeconds
  (types.go:80-87) and percentageOfNodesToScore (types.go:62).
- TPU additions under the same roof: device batch size and the padded batch
  dims — these replace the reference's Parallelism knob (types.go:58),
  because on this architecture the device program IS the parallelism.

`load(path)` / `from_dict` accept the YAML/dict form; `validate()` mirrors
apis/config/validation/validation.go (duplicate profiles, unknown plugin
names, non-positive backoffs); `build_profiles()` turns the config into the
Scheduler's Profile list.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.types import DEFAULT_SCHEDULER_NAME


@dataclass
class PluginSet:
    """types.go:176 Plugins — enabled adds to defaults, disabled removes
    ('*' disables all defaults first)."""

    enabled: list[str] = field(default_factory=list)
    disabled: list[str] = field(default_factory=list)


@dataclass
class KubeSchedulerProfile:
    """types.go:100 KubeSchedulerProfile."""

    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    plugins: PluginSet = field(default_factory=PluginSet)
    # plugin name → weight (MultiPoint weight, default_plugins.go:93)
    plugin_weights: dict[str, int] = field(default_factory=dict)
    # NodeResourcesFit scoring strategy: LeastAllocated | MostAllocated
    # (shorthand for pluginArgs.NodeResourcesFit.scoringStrategy)
    scoring_strategy: str = "LeastAllocated"
    # typed per-plugin args (types_pluginargs.go analog): plugin name →
    # camelCase arg dict, decoded by _decode_plugin_args into the plugin's
    # own Args dataclass and handed to its factory
    plugin_args: dict[str, dict] = field(default_factory=dict)


@dataclass
class KubeSchedulerConfiguration:
    """types.go:37 KubeSchedulerConfiguration (consumed subset)."""

    profiles: list[KubeSchedulerProfile] = field(
        default_factory=lambda: [KubeSchedulerProfile()])
    percentage_of_nodes_to_score: int = 100          # types.go:62
    pod_initial_backoff_seconds: float = 1.0         # types.go:80
    pod_max_backoff_seconds: float = 10.0            # types.go:84
    # TPU batch shape (replaces Parallelism, types.go:58)
    batch_size: int = 512
    # API-call retry policy (client-go wait.Backoff analog): attempt
    # budget per call INCLUDING the first try, and the base backoff that
    # doubles per retry (with jitter) in the dispatcher
    api_retry_max_attempts: int = 5
    api_retry_base_seconds: float = 0.02
    # persistent XLA compilation cache directory: warm-start passes skip
    # the 20-40s per-executable compiles entirely (empty string = off)
    compilation_cache_dir: str = "~/.cache/ktpu-xla"
    # jax.profiler trace output directory: when set,
    # Scheduler.profile_session() brackets work with an XLA-level profiler
    # trace under the host spans (empty string = off)
    profiler_trace_dir: str = ""
    # continuous host profiler sampling rate (perf/profiler.py), consulted
    # only when the ContinuousHostProfiling gate is on; 0 disables the
    # sampler even with the gate on
    host_profiler_hz: float = 200.0
    # shadow-oracle audit (obs/audit.py, `ShadowOracleAudit` gate):
    # fraction of drains sampled into the hash-chained replay ledger and
    # re-executed through the host oracle on the background worker.
    # 1.0 = every drain (chaos soaks); the default keeps the audit's
    # host-oracle replay cost off the steady-state throughput envelope.
    shadow_audit_sample_rate: float = 1.0 / 64.0
    # cap on serially re-executed pods per sampled drain: the host
    # oracle replays the drain PREFIX up to this length (the serial
    # greedy's first K decisions depend only on prior state), bounding
    # the background Python cost per sample; 0 = no cap. Reason-histogram
    # diffs only run on fully-replayed (untruncated) drains.
    shadow_audit_max_replay_pods: int = 64
    # directory for standalone replay records (one pickle per audited
    # drain, re-runnable via tools/audit_replay.py); "" = in-memory only
    shadow_audit_dir: str = ""
    # directory for incident evidence bundles (obs/incident.py,
    # `IncidentForensics` gate): the watchdog writes one bounded JSON
    # bundle per trigger edge, verifiable offline by
    # tools/incident_dump.py; "" = last bundle kept in memory only
    incident_dir: str = ""
    # telemetry timeline (obs/timeline.py, `TelemetryTimeline` gate):
    # ring depth in seconds, and the JSON-lines export sink — each
    # per-second bucket is appended as it rotates out of "current"
    # ("" = in-memory ring only)
    timeline_horizon_seconds: int = 900
    timeline_export_path: str = ""
    # SLO burn-rate objectives (obs/slo.py): sli name → {"objective":
    # fraction, "thresholdSeconds": latency bound, "maxBurn": {window:
    # rate}} overriding the defaults; unknown sli names are rejected
    slo_objectives: dict = field(default_factory=dict)
    # names of out-of-tree plugins registered in the caller's Registry
    # (accepted by validation; resolved by build_profiles' registry)
    extra_plugins: tuple = ()
    # feature gate overrides (--feature-gates flag / featureGates field)
    feature_gates: dict[str, bool] = field(default_factory=dict)

    # -- validation (apis/config/validation/validation.go) -------------------

    def validate(self) -> None:
        if not self.profiles:
            raise ValueError("at least one profile is required")
        names = [p.scheduler_name for p in self.profiles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate profile schedulerName in {names}")
        if self.pod_initial_backoff_seconds <= 0:
            raise ValueError("podInitialBackoffSeconds must be > 0")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            raise ValueError(
                "podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
        if not 0 < self.percentage_of_nodes_to_score <= 100:
            raise ValueError("percentageOfNodesToScore must be in (0, 100]")
        if self.batch_size <= 0:
            raise ValueError("batchSize must be > 0")
        if self.api_retry_max_attempts < 1:
            raise ValueError("apiRetryMaxAttempts must be >= 1")
        if self.api_retry_base_seconds <= 0:
            raise ValueError("apiRetryBaseSeconds must be > 0")
        if self.host_profiler_hz < 0 or self.host_profiler_hz > 10000:
            raise ValueError("hostProfilerHz must be in [0, 10000]")
        if not 0.0 <= self.shadow_audit_sample_rate <= 1.0:
            raise ValueError("shadowAuditSampleRate must be in [0, 1]")
        if self.shadow_audit_max_replay_pods < 0:
            raise ValueError("shadowAuditMaxReplayPods must be >= 0")
        if self.timeline_horizon_seconds < 1:
            raise ValueError("timelineHorizonSeconds must be >= 1")
        from ..obs.slo import validate_objectives
        validate_objectives(self.slo_objectives)  # raises on unknown sli
        known = set(_default_plugin_names()) | set(self.extra_plugins)
        for p in self.profiles:
            for n in p.plugins.enabled + p.plugins.disabled:
                if n not in known and n != "*":
                    raise ValueError(f"unknown plugin {n!r} in profile "
                                     f"{p.scheduler_name!r} (known: "
                                     f"{sorted(known)})")
            if p.scoring_strategy not in ("LeastAllocated", "MostAllocated"):
                raise ValueError(
                    f"unknown scoringStrategy {p.scoring_strategy!r}")
            for name in p.plugin_args:
                if name not in known:
                    raise ValueError(
                        f"pluginArgs for unknown plugin {name!r} in "
                        f"profile {p.scheduler_name!r}")
                _decode_plugin_args(name, p.plugin_args[name])  # validates
        from .features import default_gate
        default_gate(self.feature_gates)  # raises on unknown gate names

    # -- round trip ----------------------------------------------------------

    API_VERSION = "kubescheduler.config.k8s.io/v1"
    KIND = "KubeSchedulerConfiguration"

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.API_VERSION,
            "kind": self.KIND,
            "profiles": [{
                "schedulerName": p.scheduler_name,
                "plugins": {"enabled": list(p.plugins.enabled),
                            "disabled": list(p.plugins.disabled)},
                "pluginWeights": dict(p.plugin_weights),
                "scoringStrategy": p.scoring_strategy,
            } for p in self.profiles],
            "percentageOfNodesToScore": self.percentage_of_nodes_to_score,
            "podInitialBackoffSeconds": self.pod_initial_backoff_seconds,
            "podMaxBackoffSeconds": self.pod_max_backoff_seconds,
            "batchSize": self.batch_size,
            "apiRetryMaxAttempts": self.api_retry_max_attempts,
            "apiRetryBaseSeconds": self.api_retry_base_seconds,
            "compilationCacheDir": self.compilation_cache_dir,
            "profilerTraceDir": self.profiler_trace_dir,
            "hostProfilerHz": self.host_profiler_hz,
            "shadowAuditSampleRate": self.shadow_audit_sample_rate,
            "shadowAuditMaxReplayPods": self.shadow_audit_max_replay_pods,
            "shadowAuditDir": self.shadow_audit_dir,
            "incidentDir": self.incident_dir,
            "timelineHorizonSeconds": self.timeline_horizon_seconds,
            "timelineExportPath": self.timeline_export_path,
            "sloObjectives": dict(self.slo_objectives),
            "extraPlugins": list(self.extra_plugins),
            "featureGates": dict(self.feature_gates),
        }

    @staticmethod
    def from_dict(d: dict) -> "KubeSchedulerConfiguration":
        # versioned-scheme envelope (apis/config/scheme): tolerate its
        # absence (internal form), reject a WRONG group/version — the
        # failure mode strict decoding exists for
        api_version = d.get("apiVersion")
        if api_version is not None and api_version != \
                KubeSchedulerConfiguration.API_VERSION:
            raise ValueError(
                f"unsupported apiVersion {api_version!r} (want "
                f"{KubeSchedulerConfiguration.API_VERSION!r})")
        kind = d.get("kind")
        if kind is not None and kind != KubeSchedulerConfiguration.KIND:
            raise ValueError(f"unsupported kind {kind!r}")
        profiles = [
            KubeSchedulerProfile(
                scheduler_name=pd.get("schedulerName",
                                      DEFAULT_SCHEDULER_NAME),
                plugins=PluginSet(
                    enabled=list(pd.get("plugins", {}).get("enabled", [])),
                    disabled=list(pd.get("plugins", {}).get("disabled", []))),
                plugin_weights=dict(pd.get("pluginWeights", {})),
                scoring_strategy=pd.get("scoringStrategy", "LeastAllocated"),
                plugin_args={k: dict(v) for k, v in
                             pd.get("pluginArgs", {}).items()})
            for pd in d.get("profiles", [{}])
        ] or [KubeSchedulerProfile()]
        return KubeSchedulerConfiguration(
            profiles=profiles,
            percentage_of_nodes_to_score=d.get("percentageOfNodesToScore",
                                               100),
            pod_initial_backoff_seconds=d.get("podInitialBackoffSeconds",
                                              1.0),
            pod_max_backoff_seconds=d.get("podMaxBackoffSeconds", 10.0),
            batch_size=d.get("batchSize", 512),
            api_retry_max_attempts=d.get("apiRetryMaxAttempts", 5),
            api_retry_base_seconds=d.get("apiRetryBaseSeconds", 0.02),
            compilation_cache_dir=d.get("compilationCacheDir",
                                        "~/.cache/ktpu-xla"),
            profiler_trace_dir=d.get("profilerTraceDir", ""),
            host_profiler_hz=d.get("hostProfilerHz", 200.0),
            shadow_audit_sample_rate=d.get("shadowAuditSampleRate",
                                           1.0 / 64.0),
            shadow_audit_max_replay_pods=d.get("shadowAuditMaxReplayPods",
                                               64),
            shadow_audit_dir=d.get("shadowAuditDir", ""),
            incident_dir=d.get("incidentDir", ""),
            timeline_horizon_seconds=d.get("timelineHorizonSeconds", 900),
            timeline_export_path=d.get("timelineExportPath", ""),
            slo_objectives=dict(d.get("sloObjectives", {})),
            extra_plugins=tuple(d.get("extraPlugins", ())),
            feature_gates=dict(d.get("featureGates", {})))


def load(path: str) -> KubeSchedulerConfiguration:
    """Load + validate a YAML KubeSchedulerConfiguration."""
    import yaml
    with open(path) as f:
        cfg = KubeSchedulerConfiguration.from_dict(yaml.safe_load(f) or {})
    cfg.validate()
    return cfg


_cc_applied = False


def apply_compilation_cache(path: str | None = None) -> bool:
    """Enable jax's persistent compilation cache (once per process).

    The scheduler mints a handful of big executables (scan buckets,
    uniform L/K/J variants, wave kernels) whose XLA compiles dominate
    cold-start — PreemptionChurn's warm pass alone was ~41s of compiles.
    The on-disk cache survives process restarts, so every pass after the
    first machine-wide warm-up starts hot. `path` defaults to the
    `compilation_cache_dir` knob's default (~/.cache/ktpu-xla); empty
    string or "off" disables. Returns True when the cache is active."""
    global _cc_applied
    if _cc_applied:
        return True
    import os
    if path is None:
        path = os.environ.get("KTPU_XLA_CACHE_DIR", "~/.cache/ktpu-xla")
    if not path or path == "off":
        return False
    try:
        import jax
        full = os.path.expanduser(path)
        os.makedirs(full, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", full)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        # cache is an optimization — never fail scheduler construction
        return False
    _cc_applied = True
    return True


def _default_plugin_names() -> list[str]:
    from ..scheduler import default_plugins
    return [p.name() for p in default_plugins()] + ["DefaultPreemption"]


def _decode_plugin_args(name: str, d: dict):
    """camelCase arg dict → the plugin's typed Args dataclass
    (apis/config/types_pluginargs.go + scheme decoding analog). Raises on
    unknown plugin-arg keys — silent typos in scheduler config are the
    classic production foot-gun the reference's strict decoding catches."""
    def pick(allowed: dict):
        unknown = set(d) - set(allowed)
        if unknown:
            raise ValueError(f"unknown {name}Args fields {sorted(unknown)}")
        return {py: d[yaml] for yaml, py in allowed.items() if yaml in d}

    if name == "NodeResourcesFit":
        from ..plugins.noderesources import FitArgs, ResourceSpec
        kw = pick({"scoringStrategy": "scoring_strategy",
                   "resources": "resources",
                   "ignoredResources": "ignored_resources"})
        if "scoring_strategy" in kw and kw["scoring_strategy"] not in (
                "LeastAllocated", "MostAllocated"):
            raise ValueError(
                f"unknown scoringStrategy {kw['scoring_strategy']!r}")
        if "resources" in kw:
            kw["resources"] = tuple(
                ResourceSpec(r["name"], r.get("weight", 1))
                for r in kw["resources"])
        if "ignored_resources" in kw:
            kw["ignored_resources"] = frozenset(kw["ignored_resources"])
        return FitArgs(**kw)
    if name == "NodeResourcesBalancedAllocation":
        from ..plugins.noderesources import (BalancedAllocationArgs,
                                             ResourceSpec)
        kw = pick({"resources": "resources"})
        if "resources" in kw:
            kw["resources"] = tuple(
                ResourceSpec(r["name"], r.get("weight", 1))
                for r in kw["resources"])
        return BalancedAllocationArgs(**kw)
    if name == "PodTopologySpread":
        from ..api.types import TopologySpreadConstraint
        from ..plugins.podtopologyspread import PodTopologySpreadArgs
        kw = pick({"defaultingType": "defaulting_type",
                   "defaultConstraints": "default_constraints"})
        if kw.get("defaulting_type") not in (None, "List", "System"):
            raise ValueError(
                f"unknown defaultingType {kw['defaulting_type']!r}")
        if "default_constraints" in kw:
            kw["default_constraints"] = tuple(
                TopologySpreadConstraint(
                    max_skew=c.get("maxSkew", 1),
                    topology_key=c["topologyKey"],
                    when_unsatisfiable=c.get("whenUnsatisfiable",
                                             "DoNotSchedule"))
                for c in kw["default_constraints"])
        return PodTopologySpreadArgs(**kw)
    if name == "InterPodAffinity":
        from ..plugins.interpodaffinity import InterPodAffinityArgs
        kw = pick({"hardPodAffinityWeight": "hard_pod_affinity_weight",
                   "ignorePreferredTermsOfExistingPods":
                       "ignore_preferred_terms_of_existing_pods"})
        return InterPodAffinityArgs(**kw)
    if name == "GangScheduling":
        kw = pick({"schedulingTimeoutSeconds": "scheduling_timeout_seconds"})
        if kw.get("scheduling_timeout_seconds", 1) <= 0:
            raise ValueError("schedulingTimeoutSeconds must be > 0")
        return kw
    raise ValueError(f"plugin {name!r} does not accept args")


def default_registry(client=None):
    """Registry of plugin factories (runtime/registry.go NewInTreeRegistry
    analog): every in-tree plugin by name. Out-of-tree plugins register
    additional factories and become enable-able through the config.

    Factories construct a FRESH instance per call: plugin objects carry
    per-scheduler handles (gang Handle, volume reserved-PV sets), so
    sharing one instance across profiles or Scheduler instances would
    cross their state. Each registered factory builds exactly ONE plugin —
    one throwaway instantiation per plugin here learns the names (name()
    is an instance method), after which lookups are O(1) instead of the
    former build-the-whole-default-list-per-lookup O(n²)."""
    from ..framework.runtime import Registry
    from ..scheduler import default_plugin_factories
    reg = Registry()
    for factory in default_plugin_factories(client):
        reg.register(factory().name(), factory)
    return reg


def build_profiles(cfg: KubeSchedulerConfiguration, client=None,
                   registry=None):
    """Config → the Scheduler's Profile list (profile.NewMap analog,
    profile/profile.go:46): defaults ± enable/disable through the plugin
    registry, weights applied, ScoreConfig strategy set per profile."""
    from ..framework.runtime import Framework
    from ..ops.program import ScoreConfig
    from ..scheduler import DEFAULT_WEIGHTS, Profile, default_plugins

    registry = registry or default_registry(client)
    from .features import default_gate
    gate = default_gate(cfg.feature_gates)
    # feature-gated default plugins (v1/default_plugins.go:60-71 pattern:
    # a gate adds/removes its plugin from the default set)
    gated_off = {name for name, feature in (
        ("GangScheduling", "GenericWorkload"),
        ("NodeDeclaredFeatures", "NodeDeclaredFeatures"),
        ("DynamicResources", "DynamicResourceAllocation"),
    ) if not gate.enabled(feature)}
    out = []
    for p in cfg.profiles:
        plugins = [pl for pl in default_plugins(client)
                   if pl.name() not in gated_off]
        if "*" in p.plugins.disabled:
            plugins = []
        else:
            plugins = [pl for pl in plugins
                       if pl.name() not in p.plugins.disabled]
        have = {pl.name() for pl in plugins}
        for name in p.plugins.enabled:
            if name in have:
                continue
            factory = registry.factories.get(name)
            if factory is None:
                # validation vouched for the name (possibly via
                # extra_plugins) — silently running without it would be a
                # config lie
                raise ValueError(
                    f"plugin {name!r} enabled by profile "
                    f"{p.scheduler_name!r} has no registered factory")
            plugins.append(factory())
        # typed per-plugin args: rebuild the named plugin with its Args
        strategy = p.scoring_strategy
        for pname, argdict in p.plugin_args.items():
            decoded = _decode_plugin_args(pname, argdict)
            for idx, pl in enumerate(plugins):
                if pl.name() != pname:
                    continue
                if pname == "NodeResourcesFit":
                    from ..plugins.noderesources import Fit, FitArgs
                    if "scoringStrategy" not in argdict:
                        # args without a strategy key must not silently
                        # reset the profile-level scoringStrategy
                        decoded = FitArgs(
                            scoring_strategy=strategy,
                            resources=decoded.resources,
                            ignored_resources=decoded.ignored_resources)
                    plugins[idx] = Fit(decoded)
                    strategy = decoded.scoring_strategy
                elif pname == "NodeResourcesBalancedAllocation":
                    from ..plugins.noderesources import BalancedAllocation
                    plugins[idx] = BalancedAllocation(decoded)
                elif pname == "PodTopologySpread":
                    from ..plugins.podtopologyspread import PodTopologySpread
                    plugins[idx] = PodTopologySpread(decoded)
                elif pname == "InterPodAffinity":
                    from ..plugins.interpodaffinity import InterPodAffinity
                    old = plugins[idx]
                    plugins[idx] = InterPodAffinity(
                        decoded, ns_lister=getattr(old, "ns_lister", None))
                elif pname == "GangScheduling":
                    for k, v in decoded.items():
                        setattr(pl, k, v)
                break
        weights = dict(DEFAULT_WEIGHTS)
        weights.update(p.plugin_weights)
        fwk = Framework(p.scheduler_name, plugins, weights=weights)
        score_cfg = ScoreConfig(
            strategy=strategy,
            w_taint=weights.get("TaintToleration", 3),
            w_node_affinity=weights.get("NodeAffinity", 2),
            w_spread=weights.get("PodTopologySpread", 2),
            w_ipa=weights.get("InterPodAffinity", 2),
            w_fit=weights.get("NodeResourcesFit", 1),
            w_balanced=weights.get("NodeResourcesBalancedAllocation", 1),
            w_image=weights.get("ImageLocality", 1))
        out.append(Profile(name=p.scheduler_name, framework=fwk,
                           score_config=score_cfg,
                           disabled_plugins=tuple(p.plugins.disabled)))
    return out
