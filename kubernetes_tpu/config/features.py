"""Feature gates: component-base/featuregate + pkg/features/kube_features.go.

A FeatureGate is a registry of known features with per-feature defaults and
maturity stages; a config (or test) overrides specific gates by name, and
unknown names are rejected exactly like featuregate.Set. The scheduler
consults the gate at wiring time — the same pattern the reference uses to
introduce OpportunisticBatching (kube_features.go:686), the async API
dispatcher (SchedulerAsyncAPICalls, :891) and the Workload API
(GenericWorkload, :338).

GA features cannot be disabled (featuregate.go's locked-to-default
behavior for GA+locked gates) — mirrored here for the gates whose off
state no longer exists in this architecture.
"""

from __future__ import annotations

from dataclasses import dataclass


ALPHA = "Alpha"
BETA = "Beta"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    """featuregate.FeatureSpec: default + prerelease stage + lock."""

    default: bool
    stage: str = BETA
    lock_to_default: bool = False


# the known gate set (kube_features.go analogs + TPU-backend gates)
DEFAULT_FEATURES: dict[str, FeatureSpec] = {
    # KEP-5598 signature batching → here: the closed-form uniform fast
    # path over same-signature runs (kube_features.go:686)
    "OpportunisticBatching": FeatureSpec(True, BETA),
    # async API call pipeline (kube_features.go:891); off = every drain
    # commits synchronously before the next dispatch
    "SchedulerAsyncAPICalls": FeatureSpec(True, BETA),
    # Workload / gang scheduling API (kube_features.go:338)
    "GenericWorkload": FeatureSpec(True, ALPHA),
    # whole-gang all-or-nothing assignment as one device dispatch
    # (ops/gang.py run_gang): once PreEnqueue quorum is met, the gang is
    # solved atomically — accept commits without Reserve/Permit churn,
    # reject unwinds on device. Off = gangs ride the per-pod path with
    # the reference's Permit-barrier dance (members park holding assumed
    # resources until quorum or timeout).
    "GangDevicePlacement": FeatureSpec(True, BETA),
    # queueing hints consulted on requeue (SchedulerQueueingHint)
    "SchedulerQueueingHints": FeatureSpec(True, BETA),
    # nodedeclaredfeatures plugin
    "NodeDeclaredFeatures": FeatureSpec(True, ALPHA),
    # dynamicresources plugin (structured parameters)
    "DynamicResourceAllocation": FeatureSpec(True, BETA),
    # batched device preemption dry-run (SURVEY §7 step 8): the Evaluator's
    # per-candidate-node host sweep becomes one gathered kernel; off =
    # the host loop (still PreFilter-hoisted) for every preemption
    "BatchedPreemptionDryRun": FeatureSpec(True, BETA),
    # speculative wave placement for group (spread / inter-pod affinity)
    # drains: conflict-checked parallel placement on device with exact
    # serial-order parity (ops/program.py run_wave); off = the host
    # greedy / per-pod scan paths for every group drain
    "SpeculativeWavePlacement": FeatureSpec(True, BETA),
    # mask-derived FailedScheduling diagnosis (ops/program.py diagnose_row):
    # per-plugin rejected-node counts reduced from the device filter masks;
    # off = the host-oracle filter replay per failed signature
    "DeviceMaskDiagnosis": FeatureSpec(True, BETA),
    # always-on sampling host profiler (perf/profiler.py): a background
    # thread samples the host-loop stack at hostProfilerHz, attributing
    # cost per drain phase + signature-cardinality bucket; served at
    # /debug/hostprofile. Off = no sampler thread, no attribution.
    "ContinuousHostProfiling": FeatureSpec(True, BETA),
    # runtime sanitizer rails (analysis/rails.py): transfer guard on the
    # drain path (implicit host↔device transfers raise), per-kernel
    # retrace budgets, donation-after-use poisoning on non-donating
    # backends, NaN/inf score probes. For tests, soaks and staging —
    # not the production hot path.
    "SanitizerRails": FeatureSpec(False, ALPHA),
    # columnar ingest & commit engine (kubernetes_tpu/ingest/): the
    # batched assume/bind path (CommitEngine) + the bulk bind-echo
    # confirm. Off = the serial per-pod _fast_commit / per-pod informer
    # fan-out — the parity oracle tests/test_ingest.py compares against.
    "ColumnarIngest": FeatureSpec(True, BETA),
    # shadow-oracle audit (kubernetes_tpu/obs/audit.py): a background
    # sampler captures a deterministic replay record per sampled drain
    # into a hash-chained ledger, re-executes it through the host oracle
    # off the hot path, and diffs assignments + FailedScheduling reason
    # histograms (oracle_divergence_total). The production-time half of
    # the bind-parity contract the fuzz suites verify offline — the
    # precondition for learned score columns (ROADMAP item 5) whose
    # correctness cannot be fuzzed ahead of time.
    "ShadowOracleAudit": FeatureSpec(True, BETA),
    # active/standby HA (kubernetes_tpu/ha/): lease-based leader election
    # with generation fencing tokens on every dispatched write, plus the
    # ledger-warmed hot spare (StandbyScheduler tails the drain ledger +
    # watch stream and takes over via a warm resync). Off = the
    # single-instance fallback matrix documented in the README: electors
    # still work (server.py back-compat) but writes go unfenced and a
    # standby runs cold — takeover degrades to a full LIST + tensorize +
    # JIT warm-up.
    "ActiveStandbyHA": FeatureSpec(True, ALPHA),
    # pod-journey tracing (obs/journey.py): the columnar lifecycle ring
    # behind /debug/pod and the scheduler_e2e_segment_seconds families.
    # Off = no transition recording; the first-enqueue SLI clock is NOT
    # gated (the e2e bugfix holds regardless).
    "PodJourneyTracing": FeatureSpec(True, BETA),
    # on-device cluster analytics (ops/program.py cluster_probe): one
    # reduction over the resident carry per drain → utilization
    # percentiles, fragmentation/stranded indices, topology-domain
    # imbalance (/debug/cluster, scheduler_cluster_* gauges, flight
    # recorder, timeline).
    "ClusterStateProbe": FeatureSpec(True, BETA),
    # per-second telemetry timeline ring (obs/timeline.py):
    # /debug/timeline + the config-gated JSON-lines exporter
    # (timeline_export_path) + bench --timeline-dir.
    "TelemetryTimeline": FeatureSpec(True, BETA),
    # streaming drain pipeline (kubernetes_tpu/pipeline.py): the 3-stage
    # ingest / device / commit overlap driver — a background ingest stage
    # builds + dispatches the next drain while the device executes the
    # current one and a commit worker drains the _PendingDrain queue off
    # the critical path, with depth-capped backpressure between stages.
    # Off = StreamingPipeline refuses to start; callers fall back to the
    # lock-step schedule_pending() loop (same assignments, no overlap).
    "StreamingDrainPipeline": FeatureSpec(True, ALPHA),
    # kernel observatory (perf/observatory.py): per-dispatch device-time
    # attribution — run-wall histograms keyed (kernel, plan/shape,
    # backend), the per-drain device lane in the flight recorder and
    # Chrome trace, the sharded-lane profile, /debug/kernels and the
    # scheduler_kernel_*/scheduler_shard_* metric families. Process-
    # global like the compile ledger it extends.
    "KernelObservatory": FeatureSpec(True, BETA),
    # fleet observatory (obs/federation.py + obs/stitch.py): telemetry
    # federation over N sharded instances — shard/role-labeled fleet
    # exposition, ONE federated SLO burn per SLI (standbys excluded),
    # capacity-weighted fleet cluster probe (/debug/fleet) — and the
    # cross-shard journey stitcher behind the manager's /debug/pod.
    "FleetObservatory": FeatureSpec(True, ALPHA),
    # incident forensics (obs/incident.py): the watchdog over federated
    # SLO / divergence / fenced-write / pipeline-stall signals that
    # captures bounded evidence bundles to incidentDir, offline
    # verifiable by tools/incident_dump.py.
    "IncidentForensics": FeatureSpec(True, ALPHA),
    # critical-path observatory (perf/critical_path.py + costmodel.py):
    # per-drain bottleneck verdicts over {host_build, device_compute,
    # device_comms, commit, backpressure, idle} stamped on the flight
    # record and aggregated as scheduler_critical_path_seconds /
    # scheduler_bottleneck_drains_total; the device cost model
    # (cost_analysis flops/bytes, achieved-vs-modeled fraction per
    # kernel variant); /debug/criticalpath and the bench headroom block.
    "CriticalPathObservatory": FeatureSpec(True, BETA),
}


class FeatureGate:
    """featuregate.MutableFeatureGate (reduced): known map + overrides."""

    def __init__(self, known: dict[str, FeatureSpec] | None = None):
        self._known = dict(known if known is not None else DEFAULT_FEATURES)
        self._overrides: dict[str, bool] = {}

    def add(self, name: str, spec: FeatureSpec) -> None:
        """Register an out-of-tree feature (featuregate.Add)."""
        self._known[name] = spec

    def enabled(self, name: str) -> bool:
        if name in self._overrides:
            return self._overrides[name]
        spec = self._known.get(name)
        if spec is None:
            raise KeyError(f"unknown feature gate {name!r}")
        return spec.default

    def set(self, name: str, value: bool) -> None:
        spec = self._known.get(name)
        if spec is None:
            raise ValueError(
                f"unknown feature gate {name!r} (known: "
                f"{sorted(self._known)})")
        if spec.lock_to_default and value != spec.default:
            raise ValueError(
                f"feature gate {name!r} is {spec.stage} and locked to "
                f"{spec.default}")
        self._overrides[name] = value

    def set_from_map(self, overrides: dict[str, bool]) -> None:
        for name, value in overrides.items():
            self.set(name, bool(value))

    def known(self) -> dict[str, FeatureSpec]:
        return dict(self._known)


def default_gate(overrides: dict[str, bool] | None = None) -> FeatureGate:
    gate = FeatureGate()
    if overrides:
        gate.set_from_map(overrides)
    return gate
