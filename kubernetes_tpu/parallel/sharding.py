"""Node-axis sharding: the scheduler's long axis distributed over a device mesh.

The reference scales its node axis with adaptive sampling + √n-chunked
parallel iteration (SURVEY §2.6); the TPU design shards the node axis of the
tensorized cluster state over a `jax.sharding.Mesh` instead. Every filter and
score kernel in ops/program.py is row-independent over nodes, so the per-pod
evaluation runs unchanged on each shard; only the argmax and the carry update
need cross-device communication:

  local masked-score → local argmax → `lax.pmax` of the best score →
  `lax.pmin` of the global index among shards holding that score (this
  reproduces the single-device "first max index" tie-break exactly) →
  each shard applies the placement only if the winning row is local.

Two scalar collectives per pod step, riding ICI — plus, when group kernels
(PodTopologySpread / InterPodAffinity, ops/groups.py) are active:
  - `pmin` for the global minimum match count across domains,
  - a psum'd domain-flag vector for the global distinct-domain count,
  - pmax/pmin scalars for the score normalizations, and
  - a psum broadcast of the chosen node's topology values so every shard can
    apply the same-topology-value count update to its local slice.

The assignments stream is replicated; the carry stays sharded.
`run_batch_sharded` therefore returns bit-identical assignments to
`ops.program.run_batch` (asserted in tests/test_sharding.py) while holding
1/D of the node state per device — the "long-context" scaling story of
SURVEY §5.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.groups import GroupCarry, GroupsDev, group_update
from ..ops.program import (MAX_SCORE, Carry, PodRow, PodTableDev, PodXs,
                           ScoreConfig, SigCache, _apply_assignment,
                           _eval_pod, _fit_scores, _gather_row, _row_refresh,
                           _uniform_matrix, _WaveState, balanced_allocation,
                           default_normalize, fit_mask, least_allocated,
                           ports_mask)
from ..state.tensorize import NodeArrays

NODE_AXIS = "nodes"

if hasattr(jax, "shard_map"):
    def _shard_map(f, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:
    # older jax (< 0.5): same semantics under jax.experimental, with the
    # replication check spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, mesh, in_specs, out_specs):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

_INT_MAX = jnp.iinfo(jnp.int32).max

# the signature-cache sig is a replicated scalar; every other carry leaf is
# sharded along the node axis
_CACHE_SPEC = SigCache(sig=P(), static_mask=P(NODE_AXIS), taint_raw=P(NODE_AXIS),
                       s_img=P(NODE_AXIS),
                       na_raw=P(NODE_AXIS), fit_ok=P(NODE_AXIS),
                       s_fit=P(NODE_AXIS), s_bal=P(NODE_AXIS))

# group tensors: node axis is the LAST dim of the node-indexed arrays; the
# per-row scalars and pairwise match matrices are replicated
_GD_NODE_FIELDS = ("spr_f_tv", "spr_f_elig", "spr_f_dom", "spr_s_tv",
                   "spr_s_elig", "spr_s_keys_ok", "spr_s_dom", "ipa_ra_tv",
                   "ipa_ra_dom", "ipa_raa_tv", "ipa_raa_dom", "ipa_stc_tv",
                   "ipa_stc_dom", "ipa_stp_tv", "ipa_stp_dom")
_GC_NODE_FIELDS = ("spr_f_cnt", "spr_s_cnt", "ipa_veto", "ipa_a_cnt",
                   "ipa_aa_cnt", "ipa_score")


def _last_axis_spec(tree, node_fields):
    def spec(name, arr):
        if name in node_fields:
            return P(*([None] * (np_ndim(arr) - 1) + [NODE_AXIS]))
        return P()
    return type(tree)(**{name: spec(name, getattr(tree, name))
                         for name in tree._fields})


def np_ndim(x) -> int:
    return getattr(x, "ndim", 0)


def _carry_spec(carry: Carry) -> Carry:
    groups_spec = None
    if carry.groups is not None:
        groups_spec = _last_axis_spec(carry.groups, _GC_NODE_FIELDS)
    return Carry(used=P(NODE_AXIS), nonzero_used=P(NODE_AXIS),
                 npods=P(NODE_AXIS), ports=P(NODE_AXIS), cache=_CACHE_SPEC,
                 groups=groups_spec)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the node axis."""
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    import numpy as np
    return Mesh(np.array(devices), (NODE_AXIS,))


def _sharded_step(cfg: ScoreConfig, axis: str, n_global: int,
                  na_l: NodeArrays, table: PodTableDev,
                  groups: GroupsDev | None, offset: jnp.ndarray, fam,
                  c: Carry, x: PodXs):
    """One pod placement on a node shard. Collectives: pmax + pmin (plus the
    global normalization maxes inside _eval_pod and the group-kernel
    collectives described in the module docstring)."""
    n_local = na_l.cap.shape[0]
    pod = _gather_row(table, x)
    mask, score, parts = _eval_pod(cfg, na_l, c, pod, axis=axis,
                                   groups=groups, tidx=x.tidx,
                                   n_global=n_global, fam=fam)
    masked = jnp.where(mask, score, -1)
    lbest = jnp.argmax(masked).astype(jnp.int32)
    lscore = masked[lbest]
    gscore = lax.pmax(lscore, axis)
    # global "first max index" tie-break == single-device argmax semantics
    cand = jnp.where(lscore == gscore, offset + lbest, _INT_MAX)
    gbest = lax.pmin(cand, axis)
    assigned = (gscore >= 0) & pod.valid
    lidx = gbest - offset
    in_shard = (lidx >= 0) & (lidx < n_local)
    lidx_safe = jnp.clip(lidx, 0, n_local - 1).astype(jnp.int32)
    gate = assigned & in_shard
    c2 = _apply_assignment(c, pod, lidx_safe, gate)
    c2 = c2._replace(cache=_row_refresh(cfg, na_l, c2, pod, lidx_safe,
                                        gate, parts))
    if groups is not None:
        def pick(arr):
            # chosen node's value, broadcast from the owning shard
            local = arr[..., lidx_safe]
            return lax.psum(jnp.where(in_shard, local,
                                      jnp.zeros_like(local)), axis)

        is_chosen = in_shard & (jnp.arange(n_local, dtype=jnp.int32)
                                == lidx_safe)
        # gate here is GLOBAL placement (counts update on every shard's
        # local slice via topology-value sharing)
        c2 = c2._replace(groups=group_update(groups, c2.groups, x.tidx,
                                             pick, is_chosen, assigned,
                                             fam=fam))
    return c2, jnp.where(assigned, gbest, -1)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "fam"))
def _run_batch_sharded_jit(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                           carry: Carry, pods: PodXs, table: PodTableDev,
                           groups: GroupsDev | None = None, fam=None):
    """`ops.program.run_batch` with the node axis sharded over `mesh`.

    N (the padded node count) must be divisible by the mesh size; the
    pow-of-two padding of ClusterState guarantees this for pow-of-two
    meshes. Returns (final sharded carry, replicated assignments[B]).
    """
    n_global = na.cap.shape[0]
    node_sharded_na = NodeArrays(*(P(NODE_AXIS) for _ in na))
    node_sharded_carry = _carry_spec(carry)
    # optional leaves (nom_idx=None — overlays are single-device-only)
    # keep their None spec: a P() over a None leaf breaks tree matching
    replicated_pods = PodXs(*(P() if x is not None else None for x in pods))
    replicated_table = PodTableDev(*(P() for _ in table))
    groups_spec = (_last_axis_spec(groups, _GD_NODE_FIELDS)
                   if groups is not None else None)

    def local(na_l: NodeArrays, carry_l: Carry, pods_r: PodXs,
              table_r: PodTableDev, groups_l):
        n_local = na_l.cap.shape[0]
        offset = (lax.axis_index(NODE_AXIS) * n_local).astype(jnp.int32)
        step = functools.partial(_sharded_step, cfg, NODE_AXIS, n_global,
                                 na_l, table_r, groups_l, offset, fam)
        return lax.scan(step, carry_l, pods_r)

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(node_sharded_na, node_sharded_carry, replicated_pods,
                  replicated_table, groups_spec),
        out_specs=(node_sharded_carry, P()))
    return fn(na, carry, pods, table, groups)


def run_batch_sharded(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                      carry: Carry, pods: PodXs, table: PodTableDev,
                      groups: GroupsDev | None = None, fam=None):
    """Ledger-instrumented entry for `_run_batch_sharded_jit` (compile
    ledger: perf/ledger.py — the sharded program's compiles are the
    expensive ones, one executable per mesh shape). Host-side per-pod
    inputs are explicitly staged like every single-device entry, so the
    mesh path runs under the sanitizer rails' ambient transfer guard
    too (ISSUE 10 satellite: run_batch_sharded was the only JIT entry
    outside the rails/ledger coverage)."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    pods, table = RAILS.stage((pods, table))
    return LEDGER.measured_call("run_batch_sharded", _run_batch_sharded_jit,
                                cfg, mesh, na, carry, pods, table, groups,
                                fam)


@functools.partial(jax.jit, static_argnames=("cfg", "fam"))
def _lane_probe_jit(cfg: ScoreConfig, na_l: NodeArrays, carry_l: Carry,
                    pods: PodXs, table: PodTableDev, fam=None):
    """One lane's LOCAL compute, collectives elided: the same per-pod
    eval/argmax/carry-update scan `_sharded_step` runs on each shard,
    minus the pmax/pmin exchange. Timing this per lane against the full
    sharded program's blocked wall is what decomposes the mesh gap into
    compute vs comms (ROADMAP item 1): the slowest lane bounds the
    compute share, the remainder is collectives + dispatch."""
    n_local = na_l.cap.shape[0]

    def step(c, x):
        pod = _gather_row(table, x)
        mask, score, parts = _eval_pod(cfg, na_l, c, pod, axis=None,
                                       groups=None, tidx=x.tidx,
                                       n_global=n_local, fam=fam)
        masked = jnp.where(mask, score, -1)
        best = jnp.argmax(masked).astype(jnp.int32)
        gate = (masked[best] >= 0) & pod.valid
        c2 = _apply_assignment(c, pod, best, gate)
        c2 = c2._replace(cache=_row_refresh(cfg, na_l, c2, pod, best,
                                            gate, parts))
        return c2, jnp.where(gate, best, -1)

    return lax.scan(step, carry_l, pods)


def _lane_carry(host_carry: Carry, sl: slice) -> Carry:
    """Slice the node axis of a host copy of the carry (groups must be
    None — the lane probe is group-free)."""
    cache = host_carry.cache
    cache_l = type(cache)(
        sig=cache.sig,
        **{f: getattr(cache, f)[sl] for f in cache._fields if f != "sig"})
    return Carry(used=host_carry.used[sl],
                 nonzero_used=host_carry.nonzero_used[sl],
                 npods=host_carry.npods[sl],
                 ports=host_carry.ports[sl], cache=cache_l, groups=None)


# lane-imbalance verdict threshold (ISSUE 20): peak/mean lane time
# beyond this means a straggler lane binds the collective barrier
IMBALANCE_BOUND_RATIO = 1.5


def profile_shard_lanes(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                        carry: Carry, pods: PodXs, table: PodTableDev,
                        groups: GroupsDev | None = None, fam=None) -> dict:
    """Sharded-lane profile (ISSUE 14): per-device local-compute seconds,
    time imbalance, and an all-reduce/comms share estimate for
    `run_batch_sharded` — the decomposition ROADMAP item 1 needs before
    porting the single-device toolchain onto the mesh.

    Measurement harness, NOT hot path: re-dispatches the (non-donating)
    sharded program on the given inputs with a blocking fence for the
    total wall, then times each lane's node slice through the group-free
    local scan (`_lane_probe_jit` — one executable for all lanes, they
    share a shape). `commsShare` attributes everything the slowest lane
    does not explain to collectives + dispatch; `imbalanceRatio` is
    max/mean over lanes. Transfers use the explicit device_get/device_put
    escapes so the sanitizer rails' guard stays honest. When group
    kernels are active only the total is measured (the local scan has no
    group-collective twin) and `skipped` says why."""
    import time as _t

    import numpy as np

    n_dev = int(mesh.devices.size)

    def run_full():
        out = _run_batch_sharded_jit(cfg, mesh, na, carry, pods, table,
                                     groups, fam)
        jax.block_until_ready(out)

    run_full()    # warm — a no-op re-dispatch when the drain already ran
    t0 = _t.perf_counter()
    run_full()
    total = _t.perf_counter() - t0
    prof = {"nDevices": n_dev, "totalSeconds": round(total, 6),
            "laneSeconds": [], "imbalanceRatio": 0.0, "commsShare": 0.0,
            "pods": int(np.asarray(jax.device_get(pods.valid)).shape[0])}
    if groups is not None or carry.groups is not None:
        prof["skipped"] = "group kernels active: lane probe is group-free"
        return prof

    host = jax.tree_util.tree_map(
        np.asarray, jax.device_get((na, carry, pods, table)))
    host_na, host_carry, host_pods, host_table = host
    n_nodes = int(host_na.cap.shape[0])
    nl = n_nodes // n_dev
    prof["nodesPerLane"] = nl
    pods_d, table_d = jax.device_put((host_pods, host_table))
    lane_in = []
    for d in range(n_dev):
        sl = slice(d * nl, (d + 1) * nl)
        na_l = NodeArrays(*(np.ascontiguousarray(x[sl]) for x in host_na))
        lane_in.append(jax.device_put((na_l, _lane_carry(host_carry, sl))))
    # warm the (single, shared-shape) lane executable outside the timings
    jax.block_until_ready(
        _lane_probe_jit(cfg, lane_in[0][0], lane_in[0][1], pods_d, table_d,
                        fam=fam))
    lanes = []
    for na_l, carry_l in lane_in:
        t0 = _t.perf_counter()
        jax.block_until_ready(
            _lane_probe_jit(cfg, na_l, carry_l, pods_d, table_d, fam=fam))
        lanes.append(_t.perf_counter() - t0)
    mean = sum(lanes) / len(lanes)
    peak = max(lanes)
    prof["laneSeconds"] = [round(s, 6) for s in lanes]
    prof["imbalanceRatio"] = round(peak / mean, 4) if mean > 0 else 0.0
    prof["commsShare"] = (round(max(0.0, 1.0 - peak / total), 4)
                          if total > 0 else 0.0)
    # lane verdict (ISSUE 20): classify what binds the sharded dispatch,
    # with the same comms threshold the device cost model uses — so the
    # lane profile, the kernel cost rows and the per-drain critical-path
    # chain all call the same dispatch "comms_bound" at the same share
    from ..perf.costmodel import COMMS_BOUND_SHARE
    prof["laneShares"] = ([round(s / total, 4) for s in lanes]
                          if total > 0 else [0.0] * len(lanes))
    if prof["commsShare"] > COMMS_BOUND_SHARE:
        prof["verdict"] = "comms_bound"
    elif prof["imbalanceRatio"] > IMBALANCE_BOUND_RATIO:
        # one straggler lane holds the collective barrier: the fix is
        # rebalancing the node slices, not shrinking the collectives
        prof["verdict"] = "imbalance_bound"
    else:
        prof["verdict"] = "compute_bound"
    return prof


def _note_shard_upload(phase: str, tree) -> None:
    """Attribute a mesh placement's H2D bytes to its drain phase — the
    same `scheduler_h2d_bytes_total{phase}` surface the single-device
    uploads report through (perf/ledger.py)."""
    from ..perf.ledger import GLOBAL as LEDGER
    LEDGER.note_h2d_tree(phase, tree)


def shard_node_arrays(mesh: Mesh, na: NodeArrays) -> NodeArrays:
    """Place the staging arrays onto the mesh, node axis split."""
    spec = NamedSharding(mesh, P(NODE_AXIS))
    out = NodeArrays(*(jax.device_put(jnp.asarray(x), spec) for x in na))
    _note_shard_upload("host_snapshot", out)
    return out


def shard_groups(mesh: Mesh, gd: GroupsDev) -> GroupsDev:
    """Place group static tensors: node-indexed arrays split, rest replicated."""
    out = {}
    for name in gd._fields:
        arr = jnp.asarray(getattr(gd, name))
        if name in _GD_NODE_FIELDS:
            spec = NamedSharding(mesh, P(*([None] * (arr.ndim - 1) + [NODE_AXIS])))
        else:
            spec = NamedSharding(mesh, P())
        out[name] = jax.device_put(arr, spec)
    gd = GroupsDev(**out)
    _note_shard_upload("host_group_seed", gd)
    return gd


def shard_group_carry(mesh: Mesh, gc: GroupCarry) -> GroupCarry:
    out = {}
    for name in gc._fields:
        arr = jnp.asarray(getattr(gc, name))
        if name in _GC_NODE_FIELDS:
            spec = NamedSharding(mesh, P(*([None] * (arr.ndim - 1) + [NODE_AXIS])))
        else:
            spec = NamedSharding(mesh, P())
        out[name] = jax.device_put(arr, spec)
    gc = GroupCarry(**out)
    _note_shard_upload("host_group_seed", gc)
    return gc


# ---------------------------------------------------------------------------
# ISSUE 16: the drain toolchain on the mesh. Four entries port the
# single-device fast paths onto the node-sharded mesh with exact bind
# parity (tests/test_sharded_parity.py):
#
#   run_uniform_sharded   closed-form top-L runs — ONE dispatch and ~O(1)
#                         collectives per span instead of 2 scalar
#                         collectives per pod (the BENCH_r09 20× gap was
#                         per-pod pmax/pmin latency, not bandwidth)
#   run_plan_sharded      the DrainCompiler's wavescan program with the
#                         group counters as psum/all-reduces over the axis
#   run_gang_sharded      both gang tiers (closed-form + scan) with the
#                         all-or-nothing verdict replicated
#   scatter_rows_sharded  dirty-row upload onto the resident mesh copy —
#                         the PR-9 columnar-ingest win for mesh drains
#
# Exactness of the sharded uniform merge: each shard evaluates its local
# top-K_loc candidates (K_loc = min(K, n_local); every member of the
# global top-K ranks inside its own shard's top-K_loc, so the union of
# local candidate sets contains the global candidate set), keys its
# [K_loc, J] score matrix with GLOBAL entry ids (node id · J + j), takes a
# local top-L_loc, and all-gathers (key, node) pairs for a replicated
# merge top-L. Keys are globally unique, so the merged top-L equals the
# single-device top-L of the full matrix whenever the run_uniform
# exactness preconditions hold — and when they fail, the replicated
# mono/norm/depth flags (pmin-reduced, conservative in the safe
# direction) send both paths to the identical-output scan.


def _uniform_local_core(cfg: ScoreConfig, n_global: int, L: int, K: int,
                        J: int, na_l: NodeArrays, carry_l: Carry, x: PodXs,
                        table: PodTableDev, n_actual):
    """SPMD body of the sharded closed-form run (shared with the gang
    uniform tier). `na_l`/`carry_l` are one node shard; `x`/`table`/
    `n_actual` replicated. Returns (local carry', replicated assignments
    i32[L], replicated exact/depth flags)."""
    n_local = na_l.cap.shape[0]
    n_dev = n_global // n_local
    K_loc = min(K, n_local)
    offset = (lax.axis_index(NODE_AXIS) * n_local).astype(jnp.int32)
    pod = _gather_row(table, x)
    feasible0, total0, parts = _eval_pod(cfg, na_l, carry_l, pod,
                                         axis=NODE_AXIS, n_global=n_global)
    masked0 = jnp.where(feasible0, total0, jnp.int64(-1))
    _, cand = lax.top_k(masked0.astype(jnp.int32), K_loc)
    cand = cand.astype(jnp.int32)

    # static per-node score components — globally normalized (axis), so
    # the matrix values match the single-device keys bit for bit
    s_taint = default_normalize(parts.taint_raw, feasible0, reverse=True,
                                axis=NODE_AXIS)
    s_na = default_normalize(parts.na_raw, feasible0, reverse=False,
                             axis=NODE_AXIS)
    static_add = (cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
                  + cfg.w_image * parts.s_img)[cand]
    static_m = parts.static_mask[cand]
    norm_ok = (lax.pmax(jnp.max(jnp.where(feasible0, parts.taint_raw, 0)),
                        NODE_AXIS) == 0) & (
        lax.pmax(jnp.max(jnp.where(feasible0, parts.na_raw, 0)),
                 NODE_AXIS) == 0)

    fit_kj, s_fit_kj, s_bal_kj = _uniform_matrix(
        cfg, na_l, carry_l.used, carry_l.npods, carry_l.used,
        carry_l.nonzero_used, cand, pod, J)
    score_kj = (cfg.w_fit * s_fit_kj + cfg.w_balanced * s_bal_kj
                + static_add[:, None])
    masked_kj = jnp.where(static_m[:, None] & fit_kj, score_kj,
                          jnp.int64(-1))
    # checked over a SUPERSET of the single-device candidates — may only
    # be more conservative, and a False flag routes to the exact scan
    mono_ok = lax.pmin(
        jnp.all(masked_kj[:, 1:] <= masked_kj[:, :-1]).astype(jnp.int32),
        NODE_AXIS) == 1

    score_max = MAX_SCORE * (cfg.w_fit + cfg.w_balanced + cfg.w_taint
                             + cfg.w_node_affinity + cfg.w_image)
    M = n_global * J
    key_dt = jnp.int32 if (score_max + 2) * M < 2 ** 31 else jnp.int64
    gcand = offset + cand
    ent_id = (gcand[:, None].astype(key_dt) * J
              + jnp.arange(J, dtype=key_dt)[None, :])
    flat_key = (masked_kj.astype(key_dt) * key_dt(M)
                - ent_id).reshape(K_loc * J)
    L_loc = min(L, K_loc * J)
    lvals, li = lax.top_k(flat_key, L_loc)
    node_l = gcand[(li // J).astype(jnp.int32)]
    g_vals = lax.all_gather(lvals, NODE_AXIS).reshape(n_dev * L_loc)
    g_node = lax.all_gather(node_l, NODE_AXIS).reshape(n_dev * L_loc)
    if g_vals.shape[0] < L:
        # defensive: the scheduler's shapes keep D·L_loc ≥ L; pad with
        # strictly-infeasible keys if a caller hands a thinner lattice
        pad = L - g_vals.shape[0]
        g_vals = jnp.concatenate(
            [g_vals, jnp.full((pad,), -key_dt(M) - 1, key_dt)])
        g_node = jnp.concatenate([g_node, jnp.full((pad,), -1, jnp.int32)])
    top_vals, top_i = lax.top_k(g_vals, L)
    node_of = g_node[top_i]
    sel_ok = (top_vals > -key_dt(M)) & (jnp.arange(L) < n_actual)
    assignments = jnp.where(sel_ok, node_of, -1).astype(jnp.int32)

    lid = assignments - offset
    in_shard = sel_ok & (lid >= 0) & (lid < n_local)
    lid_safe = jnp.clip(lid, 0, n_local - 1)
    counts_local = jnp.zeros((n_local,), jnp.int64).at[lid_safe].add(
        in_shard.astype(jnp.int64))
    counts = counts_local[cand]
    depth_ok = lax.pmin(jnp.all(counts < J).astype(jnp.int32),
                        NODE_AXIS) == 1
    used = carry_l.used.at[cand].add(counts[:, None] * pod.req[None, :])
    nonzero = carry_l.nonzero_used.at[cand].add(
        counts[:, None] * pod.nonzero_req[None, :])
    npods = carry_l.npods.at[cand].add(counts.astype(carry_l.npods.dtype))

    # cache refresh at the local candidates: entry j=counts IS the
    # next-pod evaluation; untouched candidates write their pre-existing
    # value (fit_kj[k, 0] == parts at count 0), so the refreshed cache is
    # bit-identical to the single-device refresh
    ar = jnp.arange(K_loc)
    cnt_i = jnp.minimum(counts, J - 1).astype(jnp.int32)
    new_cache = SigCache(
        sig=pod.sig,
        static_mask=parts.static_mask, taint_raw=parts.taint_raw,
        na_raw=parts.na_raw, s_img=parts.s_img,
        fit_ok=parts.fit_ok.at[cand].set(fit_kj[ar, cnt_i]),
        s_fit=parts.s_fit.at[cand].set(s_fit_kj[ar, cnt_i]),
        s_bal=parts.s_bal.at[cand].set(s_bal_kj[ar, cnt_i]))
    new_carry = carry_l._replace(used=used, nonzero_used=nonzero,
                                 npods=npods, cache=new_cache)
    return new_carry, assignments, mono_ok & norm_ok, depth_ok


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "L", "K", "J"))
def _run_uniform_sharded_jit(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                             carry: Carry, x: PodXs, table: PodTableDev,
                             n_actual, L: int, K: int, J: int):
    n_global = na.cap.shape[0]
    node_na = NodeArrays(*(P(NODE_AXIS) for _ in na))
    carry_spec = _carry_spec(carry)
    x_spec = PodXs(*(P() if v is not None else None for v in x))
    table_spec = PodTableDev(*(P() for _ in table))

    def local(na_l, carry_l, x_r, table_r, n_act):
        return _uniform_local_core(cfg, n_global, L, K, J, na_l, carry_l,
                                   x_r, table_r, n_act)

    fn = _shard_map(local, mesh,
                    in_specs=(node_na, carry_spec, x_spec, table_spec, P()),
                    out_specs=(carry_spec, P(), P(), P()))
    new_carry, assignments, ok, depth_ok = fn(na, carry, x, table, n_actual)
    packed = jnp.concatenate([
        assignments, jnp.stack([ok, depth_ok]).astype(jnp.int32)])
    return new_carry, packed


def run_uniform_sharded(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                        carry: Carry, x: PodXs, table: PodTableDev,
                        n_actual, L: int, K: int, J: int):
    """`ops.program.run_uniform` on the mesh: the whole same-signature run
    is one dispatch with ~six collectives TOTAL (eval normalizations, the
    flag pmins, one all-gather merge) instead of two scalar collectives
    per pod — the flagship of the BENCH_r09 → r10 sharded-throughput fix.
    Packed layout identical to run_uniform ([assignments(L); exact;
    depth]); never donates — the scheduler keeps the input carry to
    replay failed exactness preconditions on the sharded scan."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    x, table, n_actual = RAILS.stage((x, table, n_actual))
    return LEDGER.measured_call("run_uniform_sharded",
                                _run_uniform_sharded_jit, cfg, mesh, na,
                                carry, x, table, n_actual, L, K, J)


# ---------------------------------------------------------------------------
# the DrainCompiler's plan program on the mesh


def _plan_local(cfg: ScoreConfig, n_global: int, fam, norm_live: bool,
                has_groups: bool, has_ports: bool, na_l: NodeArrays,
                carry_l: Carry, xs, table: PodTableDev, wt, gd_l, statics_l):
    """SPMD body of `run_plan_sharded` — `ops.program._run_wave_scan_impl`
    with the node axis local: per-signature surfaces and group counters
    hold one shard, the per-step argmax is the pmax/pmin global
    tie-break, and every "chosen node's row" read becomes an
    owner-broadcast psum. Serial order, conflict detection and the
    epilogue fold are unchanged, so assignments are bit-identical to the
    single-device plan program."""
    from ..ops.groups import GroupView, group_mask_view, group_scores_view
    from ..ops.groups import wave_fold

    gc = carry_l.groups
    S = wt.shape[0]
    n_local = na_l.cap.shape[0]
    offset = (lax.axis_index(NODE_AXIS) * n_local).astype(jnp.int32)
    garange = offset + jnp.arange(n_local, dtype=jnp.int32)
    fields = {name: getattr(table, name)[wt] for name in PodTableDev._fields}
    rows = PodRow(valid=jnp.ones((S,), bool),
                  sig=jnp.ones((S,), jnp.int32), **fields)
    static_mask, taint_raw, na_raw, s_img = statics_l

    def fit_one(pod: PodRow):
        fit_ok = fit_mask(na_l.cap, carry_l.used, carry_l.npods,
                          na_l.allowed_pods, pod.req)
        s_fit, s_bal = _fit_scores(cfg, na_l, carry_l, pod)
        return fit_ok, s_fit, s_bal

    fit0, sfit0, sbal0 = jax.vmap(fit_one)(rows)

    if has_groups:
        f_act = gd_l.spr_f_active[wt]
        f_skew = gd_l.spr_f_max_skew[wt]
        f_self = gd_l.spr_f_self[wt]
        f_minz = gc.spr_f_min_zero[wt]
        f_tv = gd_l.spr_f_tv[wt]
        f_elig = gd_l.spr_f_elig[wt]
        s_act = gd_l.spr_s_active[wt]
        s_skew = gd_l.spr_s_max_skew[wt]
        s_ishost = gd_l.spr_s_is_host[wt]
        s_tv = gd_l.spr_s_tv[wt]
        s_elig = gd_l.spr_s_elig[wt]
        s_keys = gd_l.spr_s_keys_ok[wt]
        s_dom = gd_l.spr_s_dom[wt]
        ra_act = gd_l.ipa_ra_active[wt]
        ra_tv = gd_l.ipa_ra_tv[wt]
        raa_act = gd_l.ipa_raa_active[wt]
        raa_tv = gd_l.ipa_raa_tv[wt]
        self_all = gd_l.ipa_self_all[wt]
        stc_tv = gd_l.ipa_stc_tv[wt]
        stp_tv = gd_l.ipa_stp_tv[wt]
        m_f = gd_l.m_spr_f[wt][:, wt]
        m_s = gd_l.m_spr_s[wt][:, wt]
        m_a = gd_l.m_ipa_a[wt][:, wt]
        m_aa = gd_l.m_ipa_aa[wt][:, wt]
        m_ex = gd_l.m_ipa_exist[wt][:, wt]
        w_c = gd_l.w_stc[wt][:, wt]
        w_p = gd_l.w_stp[wt][:, wt]

    st0 = _WaveState(
        used=carry_l.used, nonzero_used=carry_l.nonzero_used,
        npods=carry_l.npods,
        fit_ok=fit0, s_fit=sfit0, s_bal=sbal0,
        f_cnt=gc.spr_f_cnt[wt] if has_groups else None,
        s_cnt=gc.spr_s_cnt[wt] if has_groups else None,
        veto=gc.ipa_veto[wt] if has_groups else None,
        a_cnt=gc.ipa_a_cnt[wt] if has_groups else None,
        a_total=gc.ipa_a_total[wt] if has_groups else None,
        aa_cnt=gc.ipa_aa_cnt[wt] if has_groups else None,
        iscore=gc.ipa_score[wt] if has_groups else None,
        cnt_sn=jnp.zeros((S, n_local), jnp.int32) if has_groups else None,
        clean=jnp.bool_(True), n_conf=jnp.int32(0), prefix=jnp.int32(0),
        ports=carry_l.ports if has_ports else None)

    def own(v, in_shard):
        # the chosen node's value, broadcast from the owning shard
        z = jnp.where(in_shard, v, jnp.zeros_like(v))
        if z.dtype == jnp.bool_:
            return lax.psum(z.astype(jnp.int32), NODE_AXIS).astype(bool)
        return lax.psum(z, NODE_AXIS)

    def _eval(stx: _WaveState, w):
        feasible = static_mask[w] & stx.fit_ok[w]
        if has_ports:
            feasible &= ports_mask(stx.ports, rows.port_ids[w])
        if has_groups:
            view = GroupView(
                f_act=f_act[w], f_skew=f_skew[w], f_self=f_self[w],
                f_minz=f_minz[w], f_tv=f_tv[w], f_elig=f_elig[w],
                f_cnt=stx.f_cnt[w],
                s_act=s_act[w], s_skew=s_skew[w], s_is_host=s_ishost[w],
                s_tv=s_tv[w], s_keys_ok=s_keys[w], s_dom=s_dom[w],
                s_cnt=stx.s_cnt[w],
                ra_act=ra_act[w], ra_tv=ra_tv[w], raa_act=raa_act[w],
                raa_tv=raa_tv[w], self_all=self_all[w],
                veto=stx.veto[w], a_cnt=stx.a_cnt[w],
                a_total=stx.a_total[w],
                aa_cnt=stx.aa_cnt[w], iscore=stx.iscore[w])
            feasible &= group_mask_view(view, fam, axis=NODE_AXIS)
        if norm_live:
            s_taint = default_normalize(taint_raw[w], feasible,
                                        reverse=True, axis=NODE_AXIS)
            s_na = default_normalize(na_raw[w], feasible, reverse=False,
                                     axis=NODE_AXIS)
            tn = cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
        else:
            tn = cfg.w_taint * MAX_SCORE
        total = (cfg.w_fit * stx.s_fit[w] + cfg.w_balanced * stx.s_bal[w]
                 + tn + cfg.w_image * s_img[w])
        if has_groups:
            total = total + group_scores_view(cfg.w_spread, cfg.w_ipa, view,
                                              feasible, fam, axis=NODE_AXIS,
                                              n_global=n_global)
        return feasible, total

    def _argmax_global(masked):
        lbest = jnp.argmax(masked).astype(jnp.int32)
        lscore = masked[lbest]
        gscore = lax.pmax(lscore, NODE_AXIS)
        cand = jnp.where(lscore == gscore, offset + lbest, _INT_MAX)
        return lax.pmin(cand, NODE_AXIS), gscore

    def spec_one(s):
        feas, tot = _eval(st0, s)
        best, gscore = _argmax_global(jnp.where(feas, tot, -1))
        return jnp.where(gscore >= 0, best, jnp.int32(-1))

    spec_y = jax.vmap(spec_one)(jnp.arange(S, dtype=jnp.int32))

    cols = jnp.array(cfg.score_cols, jnp.int32)
    nzm = jnp.array(cfg.col_nonzero)
    slots = jnp.array(cfg.nonzero_slot, jnp.int32)

    def step(stx: _WaveState, x):
        w = x.widx
        feasible, total = _eval(stx, w)
        best, gscore = _argmax_global(jnp.where(feasible, total, -1))
        assigned = (gscore >= 0) & x.valid
        g_i = assigned.astype(jnp.int32)
        lid = best - offset
        in_shard = (lid >= 0) & (lid < n_local)
        lid_safe = jnp.clip(lid, 0, n_local - 1).astype(jnp.int32)
        onehot = (garange == best) & assigned
        req_w = rows.req[w]
        used = stx.used + jnp.where(onehot[:, None], req_w[None, :], 0)
        nzu = stx.nonzero_used + jnp.where(onehot[:, None],
                                           rows.nonzero_req[w][None, :], 0)
        npods = stx.npods + onehot.astype(stx.npods.dtype)

        gate_w = assigned & in_shard
        cap_row = own(na_l.cap[lid_safe], in_shard)
        used_row = own(used[lid_safe], in_shard)
        nz_row = own(nzu[lid_safe], in_shard)
        npods_b = own(npods[lid_safe], in_shard)
        allowed_b = own(na_l.allowed_pods[lid_safe], in_shard)

        def refresh_one(row_s: PodRow):
            fit_b = ((npods_b + 1 <= allowed_b)
                     & jnp.all((row_s.req == 0)
                               | (used_row + row_s.req <= cap_row)))
            cap_r = cap_row[cols][None, :]
            used_nz_r = nz_row[slots] + row_s.nonzero_req[slots]
            used_pl_r = used_row[cols] + row_s.req[cols]
            used_cols_r = jnp.where(nzm, used_nz_r, used_pl_r)[None, :]
            s_fit_b = least_allocated(cfg, cap_r, used_cols_r)[0]
            s_bal_b = jnp.where(row_s.skip_balanced, 0,
                                balanced_allocation(cap_r,
                                                    used_pl_r[None, :])[0])
            return fit_b, s_fit_b, s_bal_b

        fit_b, sfit_b, sbal_b = jax.vmap(refresh_one)(rows)

        def put_col(arr, new):
            return arr.at[:, lid_safe].set(jnp.where(gate_w, new,
                                                     arr[:, lid_safe]))

        fit_ok = put_col(stx.fit_ok, fit_b)
        s_fit = put_col(stx.s_fit, sfit_b)
        s_bal = put_col(stx.s_bal, sbal_b)

        f_cnt, s_cnt = stx.f_cnt, stx.s_cnt
        veto, a_cnt, a_total = stx.veto, stx.a_cnt, stx.a_total
        aa_cnt, iscore = stx.aa_cnt, stx.iscore
        if has_groups and fam.spr_f:
            tvb_f = own(f_tv[:, :, lid_safe], in_shard)       # [S, SC]
            eligb_f = own(f_elig[:, :, lid_safe], in_shard)
            inc_f = ((m_f[w] & eligb_f)[:, :, None]
                     & (f_tv == tvb_f[:, :, None])
                     & (tvb_f[:, :, None] != 0))
            f_cnt = stx.f_cnt + g_i * inc_f.astype(jnp.int32)
        if has_groups and fam.spr_s:
            tvb_s = own(s_tv[:, :, lid_safe], in_shard)
            eligb_s = own(s_elig[:, :, lid_safe], in_shard)
            is_b = ((garange == best) & assigned)[None, None, :]
            share_s = jnp.where(s_ishost[:, :, None], is_b,
                                (s_tv == tvb_s[:, :, None])
                                & (tvb_s[:, :, None] != 0))
            gate_c = jnp.where(s_ishost, m_s[w], m_s[w] & eligb_s)
            s_cnt = stx.s_cnt + g_i * (
                gate_c[:, :, None] & share_s).astype(jnp.int32)
        if has_groups and fam.ipa_anti:
            tvb_p_anti = own(raa_tv[w, :, lid_safe], in_shard)  # [TAA]
            share_anti = ((raa_tv[w] == tvb_p_anti[:, None])
                          & (tvb_p_anti[:, None] != 0))
            delta_veto = jnp.sum(m_ex[w][:, :, None] & share_anti[None],
                                 axis=1).astype(jnp.int32)
            veto = stx.veto + g_i * delta_veto
            tvb_aa = own(raa_tv[:, :, lid_safe], in_shard)
            share_aa = ((raa_tv == tvb_aa[:, :, None])
                        & (tvb_aa[:, :, None] != 0))
            inc_aa = m_aa[w][:, :, None] & share_aa
            aa_cnt = stx.aa_cnt + g_i * inc_aa.astype(jnp.int32)
        if has_groups and fam.ipa_req:
            tvb_a = own(ra_tv[:, :, lid_safe], in_shard)
            share_a = ((ra_tv == tvb_a[:, :, None])
                       & (tvb_a[:, :, None] != 0))
            inc_a = ((m_a[w][:, None] & ra_act)[:, :, None] & share_a)
            a_cnt = stx.a_cnt + g_i * inc_a.astype(jnp.int32)
            a_total = stx.a_total + (
                g_i * m_a[w]
                * jnp.sum(ra_act & (tvb_a != 0), axis=1)).astype(jnp.int64)
        if has_groups and fam.ipa_score:
            tvb_c = own(stc_tv[:, :, lid_safe], in_shard)
            share_c = ((stc_tv == tvb_c[:, :, None])
                       & (tvb_c[:, :, None] != 0))
            d_cons = jnp.sum(w_c[w][:, :, None] * share_c, axis=1)
            tvb_p = own(stp_tv[w, :, lid_safe], in_shard)
            share_p = ((stp_tv[w] == tvb_p[:, None])
                       & (tvb_p[:, None] != 0))
            d_plcd = jnp.sum(w_p[w][:, :, None] * share_p[None], axis=1)
            iscore = stx.iscore + assigned.astype(jnp.int64) * (
                d_cons + d_plcd)

        cnt_sn = (stx.cnt_sn.at[w, lid_safe].add(
            jnp.where(in_shard, g_i, 0)) if has_groups else None)
        ports2 = stx.ports
        if has_ports:
            prow = stx.ports[lid_safe]
            free = prow == 0
            rank = jnp.cumsum(free) - 1
            pp = rows.port_ids[w]
            nport = pp.shape[0]
            incoming = jnp.where((rank >= 0) & (rank < nport) & free,
                                 pp[jnp.clip(rank, 0, nport - 1)], 0)
            new_prow = jnp.where(free, incoming, prow)
            ports2 = stx.ports.at[lid_safe].set(
                jnp.where(gate_w & jnp.any(pp != 0), new_prow, prow))
        y = jnp.where(assigned, best, jnp.int32(-1))
        conflict = x.valid & (y != spec_y[w])
        prefix = stx.prefix + (stx.clean & x.valid
                               & ~conflict).astype(jnp.int32)
        return _WaveState(
            used=used, nonzero_used=nzu, npods=npods,
            fit_ok=fit_ok, s_fit=s_fit, s_bal=s_bal,
            f_cnt=f_cnt, s_cnt=s_cnt, veto=veto, a_cnt=a_cnt,
            a_total=a_total, aa_cnt=aa_cnt, iscore=iscore,
            cnt_sn=cnt_sn, clean=stx.clean & ~conflict,
            n_conf=stx.n_conf + conflict.astype(jnp.int32),
            prefix=prefix, ports=ports2), y

    stf, ys = lax.scan(step, st0, xs)

    new_gc = (wave_fold(gd_l, gc, wt, stf.cnt_sn, fam=fam, axis=NODE_AXIS,
                        n_seg=n_global) if has_groups else carry_l.groups)
    new_carry = Carry(used=stf.used, nonzero_used=stf.nonzero_used,
                      npods=stf.npods,
                      ports=stf.ports if has_ports else carry_l.ports,
                      cache=carry_l.cache._replace(sig=jnp.int32(0)),
                      groups=new_gc)
    packed = jnp.concatenate(
        [ys, jnp.stack([stf.n_conf, stf.prefix])]).astype(jnp.int32)
    return new_carry, packed


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "fam",
                                             "norm_live", "has_groups",
                                             "has_ports"))
def _run_plan_sharded_jit(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                          carry: Carry, xs, table: PodTableDev, wt, gd,
                          statics, fam, norm_live: bool, has_groups: bool,
                          has_ports: bool):
    n_global = na.cap.shape[0]
    node_na = NodeArrays(*(P(NODE_AXIS) for _ in na))
    carry_spec = _carry_spec(carry)
    xs_spec = type(xs)(*(P() for _ in xs._fields))
    table_spec = PodTableDev(*(P() for _ in table))
    gd_spec = (_last_axis_spec(gd, _GD_NODE_FIELDS)
               if gd is not None else None)
    statics_spec = tuple(P(None, NODE_AXIS)
                         for _ in range(len(statics)))

    def local(na_l, carry_l, xs_r, table_r, wt_r, gd_l, statics_l):
        return _plan_local(cfg, n_global, fam, norm_live, has_groups,
                           has_ports, na_l, carry_l, xs_r, table_r, wt_r,
                           gd_l, statics_l)

    fn = _shard_map(local, mesh,
                    in_specs=(node_na, carry_spec, xs_spec, table_spec,
                              P(), gd_spec, statics_spec),
                    out_specs=(carry_spec, P()))
    return fn(na, carry, xs, table, wt, gd, statics)


def run_plan_sharded(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                     carry: Carry, xs, table: PodTableDev, wt,
                     gd: GroupsDev | None, statics, fam, norm_live: bool,
                     has_groups: bool = True, has_ports: bool = False):
    """`ops.program.run_plan` on the mesh: one compiled dispatch per
    mixed-signature span with the group counters as psum/all-reduces
    over the node axis. Serial-order exact (same conflict detection and
    repair as the single-device plan program); never donates — the mesh
    carry stays resident across the drain. `statics` are the
    SurfaceCache's [S, N] stacks, node axis sharded P(None, nodes)."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    xs, table, wt, statics = RAILS.stage((xs, table, wt, statics))
    return LEDGER.measured_call("run_plan_sharded", _run_plan_sharded_jit,
                                cfg, mesh, na, carry, xs, table, wt, gd,
                                statics, fam, norm_live, has_groups,
                                has_ports)


# ---------------------------------------------------------------------------
# gang placement on the mesh


def _gang_scan_local(cfg: ScoreConfig, n_global: int, w_contig: int,
                     na_l: NodeArrays, carry_l: Carry, xs,
                     table: PodTableDev, wt, needed, dom_l, statics_l):
    """SPMD body of the sharded gang scan tier — `ops.gang.
    _run_gang_scan_impl` with the node axis local. The contiguity domain
    counts are replicated [n_global] (dense global domain ids); the
    all-or-nothing verdict is a replicated scalar, so the reject unwind
    leaves every shard's carry untouched."""
    n_local = na_l.cap.shape[0]
    offset = (lax.axis_index(NODE_AXIS) * n_local).astype(jnp.int32)
    garange = offset + jnp.arange(n_local, dtype=jnp.int32)
    cols = jnp.array(cfg.score_cols, jnp.int32)
    nzmask = jnp.array(cfg.col_nonzero)
    slots = jnp.array(cfg.nonzero_slot, jnp.int32)
    static_m, taint_raw, na_raw, s_img = statics_l            # [S, n_local]

    def _fit_parts(u):
        pod = _gather_row(table, PodXs(valid=jnp.bool_(True),
                                       sig=jnp.int32(0), tidx=u))
        fit_ok = fit_mask(na_l.cap, carry_l.used, carry_l.npods,
                          na_l.allowed_pods, pod.req)
        s_fit, s_bal = _fit_scores(cfg, na_l, carry_l, pod)
        return fit_ok, s_fit, s_bal

    fit_ok0, s_fit0, s_bal0 = jax.vmap(_fit_parts)(wt)
    req_s = table.req[wt]
    nzreq_s = table.nonzero_req[wt]
    skipb_s = table.skip_balanced[wt]

    def own(v, in_shard):
        z = jnp.where(in_shard, v, jnp.zeros_like(v))
        if z.dtype == jnp.bool_:
            return lax.psum(z.astype(jnp.int32), NODE_AXIS).astype(bool)
        return lax.psum(z, NODE_AXIS)

    def step(state, x):
        used, nz, npods, fit_ok, s_fit, s_bal, domcnt, placed = state
        s = x.widx
        pod = _gather_row(table, PodXs(valid=x.valid, sig=jnp.int32(0),
                                       tidx=x.tidx))
        feasible = static_m[s] & fit_ok[s]
        s_taint = default_normalize(taint_raw[s], feasible, reverse=True,
                                    axis=NODE_AXIS)
        s_na = default_normalize(na_raw[s], feasible, reverse=False,
                                 axis=NODE_AXIS)
        total = (cfg.w_fit * s_fit[s] + cfg.w_balanced * s_bal[s]
                 + cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
                 + cfg.w_image * s_img[s])
        if w_contig:
            total = total + w_contig * default_normalize(
                domcnt[dom_l].astype(jnp.int64), feasible, reverse=False,
                axis=NODE_AXIS)
        masked = jnp.where(feasible, total, jnp.int64(-1))
        lbest = jnp.argmax(masked).astype(jnp.int32)
        lscore = masked[lbest]
        gscore = lax.pmax(lscore, NODE_AXIS)
        cand = jnp.where(lscore == gscore, offset + lbest, _INT_MAX)
        best = lax.pmin(cand, NODE_AXIS)
        assigned = (gscore >= 0) & x.valid
        lid = best - offset
        in_shard = (lid >= 0) & (lid < n_local)
        lid_safe = jnp.clip(lid, 0, n_local - 1).astype(jnp.int32)
        onehot = (garange == best) & assigned
        used2 = used + jnp.where(onehot[:, None], pod.req[None, :], 0)
        nz2 = nz + jnp.where(onehot[:, None], pod.nonzero_req[None, :], 0)
        npods2 = npods + onehot.astype(npods.dtype)

        cap_row = own(na_l.cap[lid_safe], in_shard)
        used_row = own(used2[lid_safe], in_shard)
        npods_row = own(npods2[lid_safe], in_shard)
        nz_row = own(nz2[lid_safe], in_shard)
        allowed_b = own(na_l.allowed_pods[lid_safe], in_shard)

        def _refresh(req, nzreq, skipb):
            fit_b = ((npods_row + 1 <= allowed_b)
                     & jnp.all((req == 0) | (used_row + req <= cap_row)))
            cap_r = cap_row[cols][None, :]
            used_nz_r = nz_row[slots] + nzreq[slots]
            used_pl_r = used_row[cols] + req[cols]
            used_cols_r = jnp.where(nzmask, used_nz_r, used_pl_r)[None, :]
            s_fit_b = least_allocated(cfg, cap_r, used_cols_r)[0]
            s_bal_b = jnp.where(skipb, 0,
                                balanced_allocation(cap_r,
                                                    used_pl_r[None, :])[0])
            return fit_b, s_fit_b, s_bal_b

        fo_b, sf_b, sb_b = jax.vmap(_refresh)(req_s, nzreq_s, skipb_s)
        wr = assigned & in_shard
        fit_ok2 = fit_ok.at[:, lid_safe].set(
            jnp.where(wr, fo_b, fit_ok[:, lid_safe]))
        s_fit2 = s_fit.at[:, lid_safe].set(
            jnp.where(wr, sf_b, s_fit[:, lid_safe]))
        s_bal2 = s_bal.at[:, lid_safe].set(
            jnp.where(wr, sb_b, s_bal[:, lid_safe]))
        if w_contig:
            dom_b = own(dom_l[lid_safe], in_shard)
            domcnt2 = domcnt.at[dom_b].add(
                jnp.where(assigned, 1, 0).astype(domcnt.dtype))
        else:
            domcnt2 = domcnt
        placed2 = placed + assigned.astype(placed.dtype)
        return ((used2, nz2, npods2, fit_ok2, s_fit2, s_bal2, domcnt2,
                 placed2), jnp.where(assigned, best, jnp.int32(-1)))

    state0 = (carry_l.used, carry_l.nonzero_used, carry_l.npods,
              fit_ok0, s_fit0, s_bal0,
              jnp.zeros((n_global,), jnp.int32), jnp.int32(0))
    (used_f, nz_f, npods_f, _, _, _, _, placed), raw = lax.scan(
        step, state0, xs)
    accept = placed >= needed

    def sel(a, b):
        return jnp.where(accept, a, b)

    cache = carry_l.cache._replace(
        sig=jnp.where(accept, jnp.int32(0), carry_l.cache.sig))
    carry_out = carry_l._replace(used=sel(used_f, carry_l.used),
                                 nonzero_used=sel(nz_f,
                                                  carry_l.nonzero_used),
                                 npods=sel(npods_f, carry_l.npods),
                                 cache=cache)
    packed = jnp.concatenate([
        raw, jnp.stack([accept.astype(jnp.int32), placed,
                        jnp.int32(1), jnp.int32(1)])])
    return carry_out, packed


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "w_contig"))
def _run_gang_scan_sharded_jit(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                               carry: Carry, xs, table: PodTableDev, wt,
                               needed, dom, statics, w_contig: int):
    n_global = na.cap.shape[0]
    node_na = NodeArrays(*(P(NODE_AXIS) for _ in na))
    carry_spec = _carry_spec(carry)
    xs_spec = type(xs)(*(P() for _ in xs._fields))
    table_spec = PodTableDev(*(P() for _ in table))
    statics_spec = tuple(P(None, NODE_AXIS)
                         for _ in range(len(statics)))

    def local(na_l, carry_l, xs_r, table_r, wt_r, need_r, dom_l, statics_l):
        return _gang_scan_local(cfg, n_global, w_contig, na_l, carry_l,
                                xs_r, table_r, wt_r, need_r, dom_l,
                                statics_l)

    fn = _shard_map(local, mesh,
                    in_specs=(node_na, carry_spec, xs_spec, table_spec,
                              P(), P(), P(NODE_AXIS), statics_spec),
                    out_specs=(carry_spec, P()))
    return fn(na, carry, xs, table, wt, needed, dom, statics)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "L", "K", "J"))
def _run_gang_uniform_sharded_jit(cfg: ScoreConfig, mesh: Mesh,
                                  na: NodeArrays, carry: Carry, x: PodXs,
                                  table: PodTableDev, n_actual, needed,
                                  L: int, K: int, J: int):
    n_global = na.cap.shape[0]
    node_na = NodeArrays(*(P(NODE_AXIS) for _ in na))
    carry_spec = _carry_spec(carry)
    x_spec = PodXs(*(P() if v is not None else None for v in x))
    table_spec = PodTableDev(*(P() for _ in table))

    def local(na_l, carry_l, x_r, table_r, n_act, need):
        new_carry, assignments, ok, depth_ok = _uniform_local_core(
            cfg, n_global, L, K, J, na_l, carry_l, x_r, table_r, n_act)
        placed = jnp.sum((assignments >= 0).astype(jnp.int32))
        accept = placed >= need
        apply = accept & ok & depth_ok
        carry_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(apply, a, b), new_carry, carry_l)
        return carry_out, assignments, accept, placed, ok, depth_ok

    fn = _shard_map(local, mesh,
                    in_specs=(node_na, carry_spec, x_spec, table_spec,
                              P(), P()),
                    out_specs=(carry_spec, P(), P(), P(), P(), P()))
    carry_out, assignments, accept, placed, ok, depth_ok = fn(
        na, carry, x, table, n_actual, needed)
    packed = jnp.concatenate([
        assignments,
        jnp.stack([accept, placed, ok, depth_ok]).astype(jnp.int32)])
    return carry_out, packed


def run_gang_sharded(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                     carry: Carry, xs, table: PodTableDev, wt=None,
                     needed=None, dom=None, statics=None, w_contig: int = 0,
                     uniform: bool = False, n_actual=None, L: int = 0,
                     K: int = 0, J: int = 0):
    """`ops.gang.run_gang` on the mesh — both tiers behind one entry,
    packed layouts identical to the single-device kernel's. Never
    donates: the scheduler keeps the input carry to replay failed
    uniform-tier preconditions on the scan tier, and the reject unwind
    is on-device on every shard."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    if uniform:
        x, table, n_actual, needed = RAILS.stage(
            (xs, table, n_actual, needed))
        return LEDGER.measured_call("run_gang_sharded",
                                    _run_gang_uniform_sharded_jit, cfg,
                                    mesh, na, carry, x, table, n_actual,
                                    needed, L, K, J)
    xs, table, wt, needed, statics = RAILS.stage(
        (xs, table, wt, needed, statics))
    return LEDGER.measured_call("run_gang_sharded",
                                _run_gang_scan_sharded_jit, cfg, mesh, na,
                                carry, xs, table, wt, needed, dom, statics,
                                w_contig)


# ---------------------------------------------------------------------------
# dirty-row upload onto the resident mesh copy (the PR-9 columnar-ingest
# win carried over: mesh drains stop paying full-matrix re-uploads)


@functools.partial(jax.jit, static_argnames=("mesh",))
def _scatter_rows_sharded_jit(mesh: Mesh, dev: NodeArrays, idx,
                              rows: NodeArrays) -> NodeArrays:
    def local(dev_l, idx_r, rows_r):
        n_local = dev_l.cap.shape[0]
        offset = (lax.axis_index(NODE_AXIS) * n_local).astype(jnp.int32)
        lid = idx_r - offset
        m = (lid >= 0) & (lid < n_local)
        # out-of-shard rows route to index n_local and DROP: clipping
        # them in-range would collide a masked duplicate with a real
        # in-shard write at the boundary rows, and XLA scatter picks an
        # arbitrary winner among duplicate indices — the real update can
        # silently lose. (Pad duplicates carry identical values, so they
        # stay order-independent.)
        tgt = jnp.where(m, lid, n_local).astype(jnp.int32)

        def one(d, r):
            return d.at[tgt].set(r.astype(d.dtype), mode="drop")

        return NodeArrays(*(one(d, r) for d, r in zip(dev_l, rows_r)))

    fn = _shard_map(local, mesh,
                    in_specs=(NodeArrays(*(P(NODE_AXIS) for _ in dev)),
                              P(), NodeArrays(*(P() for _ in rows))),
                    out_specs=NodeArrays(*(P(NODE_AXIS) for _ in dev)))
    return fn(dev, idx, rows)


def scatter_rows_sharded(mesh: Mesh, dev: NodeArrays, idx,
                         rows: NodeArrays) -> NodeArrays:
    """Scatter `rows` (replicated, [B, ...] per leaf) into the resident
    node-sharded arrays at global row ids `idx` (i32 [B], pow2-padded by
    repeating a real index — duplicate writes carry identical values).
    Each shard keeps only its own rows; the H2D bytes are the small
    replicated row block, not the full matrix."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    idx, rows = RAILS.stage((idx, rows))
    out = LEDGER.measured_call("scatter_rows_sharded",
                               _scatter_rows_sharded_jit, mesh, dev, idx,
                               rows)
    _note_shard_upload("host_snapshot", rows)
    return out


# ---------------------------------------------------------------------------
# on-device cluster analytics on the mesh: one all-gather, then the exact
# single-device probe reduction on the reassembled arrays


@functools.partial(jax.jit, static_argnames=("mesh", "ndom"))
def _cluster_probe_sharded_jit(mesh: Mesh, na: NodeArrays, carry: Carry,
                               dom, ndom: int):
    from ..ops.program import _probe_math

    def local(cap, valid, used, npods, dom_r):
        g = functools.partial(lax.all_gather, axis_name=NODE_AXIS,
                              axis=0, tiled=True)
        cap_g, valid_g, used_g, npods_g = g(cap), g(valid), g(used), \
            g(npods)
        R = cap.shape[1]

        # lane 0 runs the reduction on the gathered arrays; the other
        # lanes skip it (the sort/percentile pass is the probe's whole
        # cost — running it replicated on every lane multiplies the
        # drain's probe bill by the mesh size for identical answers)
        def compute(_):
            return _probe_math(cap_g, valid_g, used_g, npods_g, dom_r,
                               ndom)

        def skip(_):
            return (jnp.zeros((R, 7), jnp.float32),
                    jnp.zeros((4,), jnp.float32), jnp.int32(0))

        out = lax.cond(lax.axis_index(NODE_AXIS) == 0, compute, skip,
                       None)
        # broadcast lane 0's result by gathering and slicing — exact
        # (no cross-lane arithmetic that could perturb a float bit)
        return jax.tree_util.tree_map(
            lambda x: lax.all_gather(x, NODE_AXIS, axis=0)[0], out)

    # one tiled all-gather per column, then single-lane compute: feeding
    # the sharded carry straight into the single-device probe jit makes
    # GSPMD reshard around the cross-node sort/percentile ops instead —
    # an order of magnitude slower per drain on the host mesh
    fn = _shard_map(local, mesh,
                    in_specs=(P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
                              P(NODE_AXIS), P()),
                    out_specs=(P(), P(), P()))
    return fn(na.cap, na.valid, carry.used, carry.npods, dom)


def cluster_probe_sharded(mesh: Mesh, na: NodeArrays, carry: Carry, dom,
                          ndom: int):
    """`ops.program.cluster_probe`'s mesh twin: all-gathers the node
    shards inside one sharded program and runs the identical `_probe_math`
    reduction on the reassembled arrays, so every output element is
    bit-identical to the single-device probe (tests/test_cluster_probe.py
    oracle transitively holds). `dom` is replicated; the carry and node
    arrays stay resident shards — zero extra h2d, like the original."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    na, carry, dom = RAILS.stage((na, carry, dom))
    return LEDGER.measured_call("cluster_probe_sharded",
                                _cluster_probe_sharded_jit, mesh, na,
                                carry, dom, ndom)
