"""Node-axis sharding: the scheduler's long axis distributed over a device mesh.

The reference scales its node axis with adaptive sampling + √n-chunked
parallel iteration (SURVEY §2.6); the TPU design shards the node axis of the
tensorized cluster state over a `jax.sharding.Mesh` instead. Every filter and
score kernel in ops/program.py is row-independent over nodes, so the per-pod
evaluation runs unchanged on each shard; only the argmax and the carry update
need cross-device communication:

  local masked-score → local argmax → `lax.pmax` of the best score →
  `lax.pmin` of the global index among shards holding that score (this
  reproduces the single-device "first max index" tie-break exactly) →
  each shard applies the placement only if the winning row is local.

Two scalar collectives per pod step, riding ICI — plus, when group kernels
(PodTopologySpread / InterPodAffinity, ops/groups.py) are active:
  - `pmin` for the global minimum match count across domains,
  - a psum'd domain-flag vector for the global distinct-domain count,
  - pmax/pmin scalars for the score normalizations, and
  - a psum broadcast of the chosen node's topology values so every shard can
    apply the same-topology-value count update to its local slice.

The assignments stream is replicated; the carry stays sharded.
`run_batch_sharded` therefore returns bit-identical assignments to
`ops.program.run_batch` (asserted in tests/test_sharding.py) while holding
1/D of the node state per device — the "long-context" scaling story of
SURVEY §5.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.groups import GroupCarry, GroupsDev, group_update
from ..ops.program import (Carry, PodTableDev, PodXs, ScoreConfig, SigCache,
                           _apply_assignment, _eval_pod, _gather_row,
                           _row_refresh)
from ..state.tensorize import NodeArrays

NODE_AXIS = "nodes"

if hasattr(jax, "shard_map"):
    def _shard_map(f, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:
    # older jax (< 0.5): same semantics under jax.experimental, with the
    # replication check spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, mesh, in_specs, out_specs):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

_INT_MAX = jnp.iinfo(jnp.int32).max

# the signature-cache sig is a replicated scalar; every other carry leaf is
# sharded along the node axis
_CACHE_SPEC = SigCache(sig=P(), static_mask=P(NODE_AXIS), taint_raw=P(NODE_AXIS),
                       s_img=P(NODE_AXIS),
                       na_raw=P(NODE_AXIS), fit_ok=P(NODE_AXIS),
                       s_fit=P(NODE_AXIS), s_bal=P(NODE_AXIS))

# group tensors: node axis is the LAST dim of the node-indexed arrays; the
# per-row scalars and pairwise match matrices are replicated
_GD_NODE_FIELDS = ("spr_f_tv", "spr_f_elig", "spr_f_dom", "spr_s_tv",
                   "spr_s_elig", "spr_s_keys_ok", "spr_s_dom", "ipa_ra_tv",
                   "ipa_ra_dom", "ipa_raa_tv", "ipa_raa_dom", "ipa_stc_tv",
                   "ipa_stc_dom", "ipa_stp_tv", "ipa_stp_dom")
_GC_NODE_FIELDS = ("spr_f_cnt", "spr_s_cnt", "ipa_veto", "ipa_a_cnt",
                   "ipa_aa_cnt", "ipa_score")


def _last_axis_spec(tree, node_fields):
    def spec(name, arr):
        if name in node_fields:
            return P(*([None] * (np_ndim(arr) - 1) + [NODE_AXIS]))
        return P()
    return type(tree)(**{name: spec(name, getattr(tree, name))
                         for name in tree._fields})


def np_ndim(x) -> int:
    return getattr(x, "ndim", 0)


def _carry_spec(carry: Carry) -> Carry:
    groups_spec = None
    if carry.groups is not None:
        groups_spec = _last_axis_spec(carry.groups, _GC_NODE_FIELDS)
    return Carry(used=P(NODE_AXIS), nonzero_used=P(NODE_AXIS),
                 npods=P(NODE_AXIS), ports=P(NODE_AXIS), cache=_CACHE_SPEC,
                 groups=groups_spec)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the node axis."""
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    import numpy as np
    return Mesh(np.array(devices), (NODE_AXIS,))


def _sharded_step(cfg: ScoreConfig, axis: str, n_global: int,
                  na_l: NodeArrays, table: PodTableDev,
                  groups: GroupsDev | None, offset: jnp.ndarray, fam,
                  c: Carry, x: PodXs):
    """One pod placement on a node shard. Collectives: pmax + pmin (plus the
    global normalization maxes inside _eval_pod and the group-kernel
    collectives described in the module docstring)."""
    n_local = na_l.cap.shape[0]
    pod = _gather_row(table, x)
    mask, score, parts = _eval_pod(cfg, na_l, c, pod, axis=axis,
                                   groups=groups, tidx=x.tidx,
                                   n_global=n_global, fam=fam)
    masked = jnp.where(mask, score, -1)
    lbest = jnp.argmax(masked).astype(jnp.int32)
    lscore = masked[lbest]
    gscore = lax.pmax(lscore, axis)
    # global "first max index" tie-break == single-device argmax semantics
    cand = jnp.where(lscore == gscore, offset + lbest, _INT_MAX)
    gbest = lax.pmin(cand, axis)
    assigned = (gscore >= 0) & pod.valid
    lidx = gbest - offset
    in_shard = (lidx >= 0) & (lidx < n_local)
    lidx_safe = jnp.clip(lidx, 0, n_local - 1).astype(jnp.int32)
    gate = assigned & in_shard
    c2 = _apply_assignment(c, pod, lidx_safe, gate)
    c2 = c2._replace(cache=_row_refresh(cfg, na_l, c2, pod, lidx_safe,
                                        gate, parts))
    if groups is not None:
        def pick(arr):
            # chosen node's value, broadcast from the owning shard
            local = arr[..., lidx_safe]
            return lax.psum(jnp.where(in_shard, local,
                                      jnp.zeros_like(local)), axis)

        is_chosen = in_shard & (jnp.arange(n_local, dtype=jnp.int32)
                                == lidx_safe)
        # gate here is GLOBAL placement (counts update on every shard's
        # local slice via topology-value sharing)
        c2 = c2._replace(groups=group_update(groups, c2.groups, x.tidx,
                                             pick, is_chosen, assigned,
                                             fam=fam))
    return c2, jnp.where(assigned, gbest, -1)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "fam"))
def _run_batch_sharded_jit(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                           carry: Carry, pods: PodXs, table: PodTableDev,
                           groups: GroupsDev | None = None, fam=None):
    """`ops.program.run_batch` with the node axis sharded over `mesh`.

    N (the padded node count) must be divisible by the mesh size; the
    pow-of-two padding of ClusterState guarantees this for pow-of-two
    meshes. Returns (final sharded carry, replicated assignments[B]).
    """
    n_global = na.cap.shape[0]
    node_sharded_na = NodeArrays(*(P(NODE_AXIS) for _ in na))
    node_sharded_carry = _carry_spec(carry)
    # optional leaves (nom_idx=None — overlays are single-device-only)
    # keep their None spec: a P() over a None leaf breaks tree matching
    replicated_pods = PodXs(*(P() if x is not None else None for x in pods))
    replicated_table = PodTableDev(*(P() for _ in table))
    groups_spec = (_last_axis_spec(groups, _GD_NODE_FIELDS)
                   if groups is not None else None)

    def local(na_l: NodeArrays, carry_l: Carry, pods_r: PodXs,
              table_r: PodTableDev, groups_l):
        n_local = na_l.cap.shape[0]
        offset = (lax.axis_index(NODE_AXIS) * n_local).astype(jnp.int32)
        step = functools.partial(_sharded_step, cfg, NODE_AXIS, n_global,
                                 na_l, table_r, groups_l, offset, fam)
        return lax.scan(step, carry_l, pods_r)

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(node_sharded_na, node_sharded_carry, replicated_pods,
                  replicated_table, groups_spec),
        out_specs=(node_sharded_carry, P()))
    return fn(na, carry, pods, table, groups)


def run_batch_sharded(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                      carry: Carry, pods: PodXs, table: PodTableDev,
                      groups: GroupsDev | None = None, fam=None):
    """Ledger-instrumented entry for `_run_batch_sharded_jit` (compile
    ledger: perf/ledger.py — the sharded program's compiles are the
    expensive ones, one executable per mesh shape). Host-side per-pod
    inputs are explicitly staged like every single-device entry, so the
    mesh path runs under the sanitizer rails' ambient transfer guard
    too (ISSUE 10 satellite: run_batch_sharded was the only JIT entry
    outside the rails/ledger coverage)."""
    from ..analysis.rails import GLOBAL as RAILS
    from ..perf.ledger import GLOBAL as LEDGER
    pods, table = RAILS.stage((pods, table))
    return LEDGER.measured_call("run_batch_sharded", _run_batch_sharded_jit,
                                cfg, mesh, na, carry, pods, table, groups,
                                fam)


@functools.partial(jax.jit, static_argnames=("cfg", "fam"))
def _lane_probe_jit(cfg: ScoreConfig, na_l: NodeArrays, carry_l: Carry,
                    pods: PodXs, table: PodTableDev, fam=None):
    """One lane's LOCAL compute, collectives elided: the same per-pod
    eval/argmax/carry-update scan `_sharded_step` runs on each shard,
    minus the pmax/pmin exchange. Timing this per lane against the full
    sharded program's blocked wall is what decomposes the mesh gap into
    compute vs comms (ROADMAP item 1): the slowest lane bounds the
    compute share, the remainder is collectives + dispatch."""
    n_local = na_l.cap.shape[0]

    def step(c, x):
        pod = _gather_row(table, x)
        mask, score, parts = _eval_pod(cfg, na_l, c, pod, axis=None,
                                       groups=None, tidx=x.tidx,
                                       n_global=n_local, fam=fam)
        masked = jnp.where(mask, score, -1)
        best = jnp.argmax(masked).astype(jnp.int32)
        gate = (masked[best] >= 0) & pod.valid
        c2 = _apply_assignment(c, pod, best, gate)
        c2 = c2._replace(cache=_row_refresh(cfg, na_l, c2, pod, best,
                                            gate, parts))
        return c2, jnp.where(gate, best, -1)

    return lax.scan(step, carry_l, pods)


def _lane_carry(host_carry: Carry, sl: slice) -> Carry:
    """Slice the node axis of a host copy of the carry (groups must be
    None — the lane probe is group-free)."""
    cache = host_carry.cache
    cache_l = type(cache)(
        sig=cache.sig,
        **{f: getattr(cache, f)[sl] for f in cache._fields if f != "sig"})
    return Carry(used=host_carry.used[sl],
                 nonzero_used=host_carry.nonzero_used[sl],
                 npods=host_carry.npods[sl],
                 ports=host_carry.ports[sl], cache=cache_l, groups=None)


def profile_shard_lanes(cfg: ScoreConfig, mesh: Mesh, na: NodeArrays,
                        carry: Carry, pods: PodXs, table: PodTableDev,
                        groups: GroupsDev | None = None, fam=None) -> dict:
    """Sharded-lane profile (ISSUE 14): per-device local-compute seconds,
    time imbalance, and an all-reduce/comms share estimate for
    `run_batch_sharded` — the decomposition ROADMAP item 1 needs before
    porting the single-device toolchain onto the mesh.

    Measurement harness, NOT hot path: re-dispatches the (non-donating)
    sharded program on the given inputs with a blocking fence for the
    total wall, then times each lane's node slice through the group-free
    local scan (`_lane_probe_jit` — one executable for all lanes, they
    share a shape). `commsShare` attributes everything the slowest lane
    does not explain to collectives + dispatch; `imbalanceRatio` is
    max/mean over lanes. Transfers use the explicit device_get/device_put
    escapes so the sanitizer rails' guard stays honest. When group
    kernels are active only the total is measured (the local scan has no
    group-collective twin) and `skipped` says why."""
    import time as _t

    import numpy as np

    n_dev = int(mesh.devices.size)

    def run_full():
        out = _run_batch_sharded_jit(cfg, mesh, na, carry, pods, table,
                                     groups, fam)
        jax.block_until_ready(out)

    run_full()    # warm — a no-op re-dispatch when the drain already ran
    t0 = _t.perf_counter()
    run_full()
    total = _t.perf_counter() - t0
    prof = {"nDevices": n_dev, "totalSeconds": round(total, 6),
            "laneSeconds": [], "imbalanceRatio": 0.0, "commsShare": 0.0,
            "pods": int(np.asarray(jax.device_get(pods.valid)).shape[0])}
    if groups is not None or carry.groups is not None:
        prof["skipped"] = "group kernels active: lane probe is group-free"
        return prof

    host = jax.tree_util.tree_map(
        np.asarray, jax.device_get((na, carry, pods, table)))
    host_na, host_carry, host_pods, host_table = host
    n_nodes = int(host_na.cap.shape[0])
    nl = n_nodes // n_dev
    prof["nodesPerLane"] = nl
    pods_d, table_d = jax.device_put((host_pods, host_table))
    lane_in = []
    for d in range(n_dev):
        sl = slice(d * nl, (d + 1) * nl)
        na_l = NodeArrays(*(np.ascontiguousarray(x[sl]) for x in host_na))
        lane_in.append(jax.device_put((na_l, _lane_carry(host_carry, sl))))
    # warm the (single, shared-shape) lane executable outside the timings
    jax.block_until_ready(
        _lane_probe_jit(cfg, lane_in[0][0], lane_in[0][1], pods_d, table_d,
                        fam=fam))
    lanes = []
    for na_l, carry_l in lane_in:
        t0 = _t.perf_counter()
        jax.block_until_ready(
            _lane_probe_jit(cfg, na_l, carry_l, pods_d, table_d, fam=fam))
        lanes.append(_t.perf_counter() - t0)
    mean = sum(lanes) / len(lanes)
    peak = max(lanes)
    prof["laneSeconds"] = [round(s, 6) for s in lanes]
    prof["imbalanceRatio"] = round(peak / mean, 4) if mean > 0 else 0.0
    prof["commsShare"] = (round(max(0.0, 1.0 - peak / total), 4)
                          if total > 0 else 0.0)
    return prof


def _note_shard_upload(phase: str, tree) -> None:
    """Attribute a mesh placement's H2D bytes to its drain phase — the
    same `scheduler_h2d_bytes_total{phase}` surface the single-device
    uploads report through (perf/ledger.py)."""
    from ..perf.ledger import GLOBAL as LEDGER
    LEDGER.note_h2d_tree(phase, tree)


def shard_node_arrays(mesh: Mesh, na: NodeArrays) -> NodeArrays:
    """Place the staging arrays onto the mesh, node axis split."""
    spec = NamedSharding(mesh, P(NODE_AXIS))
    out = NodeArrays(*(jax.device_put(jnp.asarray(x), spec) for x in na))
    _note_shard_upload("host_snapshot", out)
    return out


def shard_groups(mesh: Mesh, gd: GroupsDev) -> GroupsDev:
    """Place group static tensors: node-indexed arrays split, rest replicated."""
    out = {}
    for name in gd._fields:
        arr = jnp.asarray(getattr(gd, name))
        if name in _GD_NODE_FIELDS:
            spec = NamedSharding(mesh, P(*([None] * (arr.ndim - 1) + [NODE_AXIS])))
        else:
            spec = NamedSharding(mesh, P())
        out[name] = jax.device_put(arr, spec)
    gd = GroupsDev(**out)
    _note_shard_upload("host_group_seed", gd)
    return gd


def shard_group_carry(mesh: Mesh, gc: GroupCarry) -> GroupCarry:
    out = {}
    for name in gc._fields:
        arr = jnp.asarray(getattr(gc, name))
        if name in _GC_NODE_FIELDS:
            spec = NamedSharding(mesh, P(*([None] * (arr.ndim - 1) + [NODE_AXIS])))
        else:
            spec = NamedSharding(mesh, P())
        out[name] = jax.device_put(arr, spec)
    gc = GroupCarry(**out)
    _note_shard_upload("host_group_seed", gc)
    return gc
