"""Shared plugin helpers (reference: framework/plugins/helper)."""

from __future__ import annotations

from ..framework.interface import MAX_NODE_SCORE


def default_normalize_score(max_priority: int, reverse: bool, scores: list[int]) -> list[int]:
    """Reference: plugins/helper/normalize_score.go DefaultNormalizeScore."""
    max_count = max(scores) if scores else 0
    if max_count == 0:
        if reverse:
            return [max_priority] * len(scores)
        return scores
    out = []
    for s in scores:
        s = max_priority * s // max_count
        if reverse:
            s = max_priority - s
        out.append(s)
    return out


def default_normalize(scores: list[int], reverse: bool = False) -> list[int]:
    return default_normalize_score(MAX_NODE_SCORE, reverse, scores)
