"""VolumeBinding: the PVC↔PV binding state machine.

Mirrors pkg/scheduler/framework/plugins/volumebinding/ (volume_binding.go +
binder.go, 2,472 LoC) reduced to the in-memory API model:

- PreFilter (volume_binding.go:203): resolve the pod's PVCs; a missing PVC
  is UnschedulableAndUnresolvable; a pod with no PVC-backed volumes Skips.
- Filter (:268 → binder.FindPodVolumes, binder.go:285): per node, three
  answers — bound PVCs' PVs must reach the node (PV nodeAffinity);
  unbound WaitForFirstConsumer PVCs must find a matching Available PV
  (findMatchingVolumes: class + access modes + capacity + nodeAffinity,
  smallest-fitting-PV-first) or a provisioner (static binding falls back to
  dynamic provisioning eligibility); unbound Immediate-class PVCs mean the
  PV controller hasn't caught up — UnschedulableAndUnresolvable.
- Reserve (:312 → AssumePodVolumes, binder.go:406): the chosen node's
  matches are held in CycleState as assumed bindings (in-memory
  AssumeCache analog — the same PV can't be matched twice in one cycle
  thanks to the reserved set).
- Unreserve (:341): drop assumed bindings, release reserved PVs.
- PreBind (:327 → BindPodVolumes, binder.go:479): issue the API binds
  (claimRef + volumeName); provisioning-bound claims mark the PVC Bound to
  a synthesized provisioned PV (the in-memory PV controller).

Scoring (scorer.go capacity-ratio shaping) is omitted — the Filter-side
availability mask is what placement correctness needs; pods with volumes
run on the host oracle path (no tensor form, by design: the state machine
is API-coupled, SURVEY §2.4)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.types import (BINDING_WAIT_FOR_FIRST_CONSUMER, ObjectMeta,
                         PersistentVolume, PersistentVolumeClaim, Pod)
from ..framework.interface import CycleState, Status
from ..framework.types import NodeInfo
from .nodeaffinity import node_selector_matches

NAME = "VolumeBinding"

_STATE_KEY = "PreFilter" + NAME

ERR_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_NO_MATCH = "node(s) didn't find available persistent volumes to bind"
ERR_CONFLICT = "node(s) had volume node affinity conflict"


def pod_pvc_names(pod: Pod) -> list[str]:
    return [v.claim_name for v in pod.spec.volumes if v.claim_name]


def pv_reaches_node(pv: PersistentVolume, node_info: NodeInfo) -> bool:
    """CheckVolumeNodeAffinity (component-helpers volume/nodeaffinity)."""
    if pv.node_affinity is None:
        return True
    return node_selector_matches(pv.node_affinity,
                                 node_info.node.metadata.labels,
                                 node_info.name)


@dataclass
class _PodVolumeState:
    """binder.go PodVolumeClaims + per-node PodVolumes."""

    bound_claims: list[PersistentVolumeClaim] = field(default_factory=list)
    unbound_wffc: list[PersistentVolumeClaim] = field(default_factory=list)
    # per-node: pvc uid → matched PV name (static binding candidates)
    node_matches: dict[str, dict[str, str]] = field(default_factory=dict)
    # per-node: pvc uids needing dynamic provisioning
    node_provisions: dict[str, list[str]] = field(default_factory=dict)
    # Reserve output: the chosen node's decisions
    assumed_bindings: dict[str, str] = field(default_factory=dict)
    assumed_provisions: list[str] = field(default_factory=list)

    def clone(self) -> "_PodVolumeState":
        return _PodVolumeState(
            bound_claims=list(self.bound_claims),
            unbound_wffc=list(self.unbound_wffc),
            node_matches={k: dict(v) for k, v in self.node_matches.items()},
            node_provisions={k: list(v)
                             for k, v in self.node_provisions.items()},
            assumed_bindings=dict(self.assumed_bindings),
            assumed_provisions=list(self.assumed_provisions))


class VolumeBinding:
    """PF, F, R, PB, EE — reference volume_binding.go."""

    def __init__(self, client=None):
        self.client = client
        # PVs reserved by assumed (not yet API-bound) pods: AssumeCache
        # analog — a second pod in the same drain must not match them
        self._reserved_pvs: dict[str, str] = {}   # pv name → pod uid

    def name(self) -> str:
        return NAME

    # -- PreFilter (volume_binding.go:203) ------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes
                   ) -> tuple[Optional[object], Status]:
        claims = pod_pvc_names(pod)
        if not claims:
            return None, Status.skip()
        if self.client is None:
            return None, Status.error("volume binding needs a client",
                                      plugin=NAME)
        s = _PodVolumeState()
        for name in claims:
            pvc = self.client.get_pvc(pod.namespace, name)
            if pvc is None:
                return None, Status.unresolvable(
                    f'persistentvolumeclaim "{name}" not found', plugin=NAME)
            if pvc.is_bound():
                s.bound_claims.append(pvc)
                continue
            sc = self.client.get_storage_class(pvc.storage_class_name)
            mode = sc.volume_binding_mode if sc else None
            if mode == BINDING_WAIT_FOR_FIRST_CONSUMER:
                s.unbound_wffc.append(pvc)
            else:
                # Immediate (or unknown class): the PV controller owns the
                # bind; until then the pod cannot schedule anywhere
                return None, Status.unresolvable(ERR_UNBOUND_IMMEDIATE,
                                                 plugin=NAME)
        state.write(_STATE_KEY, s)
        return None, Status.success()

    # -- Filter (binder.go:285 FindPodVolumes) --------------------------------

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        s: Optional[_PodVolumeState] = state.read_or_none(_STATE_KEY)
        if s is None:
            return Status.success()
        for pvc in s.bound_claims:
            pv = self.client.get_pv(pvc.volume_name)
            if pv is None or not pv_reaches_node(pv, node_info):
                return Status.unschedulable(ERR_CONFLICT, plugin=NAME)
        if not s.unbound_wffc:
            return Status.success()
        matches: dict[str, str] = {}
        provisions: list[str] = []
        used: set[str] = set(self._reserved_pvs)
        for pvc in s.unbound_wffc:
            pv = self._find_matching_pv(pvc, node_info, used)
            if pv is not None:
                matches[pvc.uid] = pv.name
                used.add(pv.name)
                continue
            sc = self.client.get_storage_class(pvc.storage_class_name)
            if sc is not None and sc.provisioner:
                provisions.append(pvc.uid)
                continue
            return Status.unschedulable(ERR_NO_MATCH, plugin=NAME)
        s.node_matches[node_info.name] = matches
        s.node_provisions[node_info.name] = provisions
        return Status.success()

    def _find_matching_pv(self, pvc: PersistentVolumeClaim,
                          node_info: NodeInfo,
                          used: set[str]) -> Optional[PersistentVolume]:
        """findMatchingVolume (pv/util.go): same class, access modes a
        superset, enough capacity, reaches the node; the SMALLEST fitting
        PV wins (waste minimization)."""
        best: Optional[PersistentVolume] = None
        for pv in self.client.list_pvs():
            if pv.claim_ref or pv.name in used:
                continue
            if pv.storage_class_name != pvc.storage_class_name:
                continue
            if not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if pv.capacity_bytes < pvc.requested_bytes:
                continue
            if not pv_reaches_node(pv, node_info):
                continue
            if best is None or pv.capacity_bytes < best.capacity_bytes:
                best = pv
        return best

    # -- Reserve / Unreserve (binder.go:406/470) -------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[_PodVolumeState] = state.read_or_none(_STATE_KEY)
        if s is None:
            return Status.success()
        s.assumed_bindings = dict(s.node_matches.get(node_name, {}))
        s.assumed_provisions = list(s.node_provisions.get(node_name, []))
        for pv_name in s.assumed_bindings.values():
            self._reserved_pvs[pv_name] = pod.uid
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        s: Optional[_PodVolumeState] = state.read_or_none(_STATE_KEY)
        if s is None:
            return
        for pv_name in s.assumed_bindings.values():
            if self._reserved_pvs.get(pv_name) == pod.uid:
                del self._reserved_pvs[pv_name]
        s.assumed_bindings = {}
        s.assumed_provisions = []

    # -- PreBind (binder.go:479 BindPodVolumes) --------------------------------

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[_PodVolumeState] = state.read_or_none(_STATE_KEY)
        if s is None:
            return Status.success()
        for pvc_uid, pv_name in s.assumed_bindings.items():
            pvc = self.client.pvcs.get(pvc_uid)
            pv = self.client.get_pv(pv_name)
            if pvc is None or pv is None:
                return Status.error(f"assumed binding vanished: {pvc_uid}",
                                    plugin=NAME)
            self.client.bind_pvc(pvc, pv)
            self._reserved_pvs.pop(pv_name, None)
        for pvc_uid in s.assumed_provisions:
            pvc = self.client.pvcs.get(pvc_uid)
            if pvc is None:
                return Status.error(f"claim to provision vanished: {pvc_uid}",
                                    plugin=NAME)
            # in-memory provisioner: synthesize a node-pinned PV and bind it
            # (the reference waits for the external provisioner; checkBindings
            # polls — our API model completes synchronously)
            from ..api.types import (LabelSelectorRequirement, NodeSelector,
                                     NodeSelectorTerm)
            pv = PersistentVolume(
                metadata=ObjectMeta(name=f"pvc-{pvc.namespace}-{pvc.name}"),
                capacity_bytes=pvc.requested_bytes,
                storage_class_name=pvc.storage_class_name,
                access_modes=pvc.access_modes,
                node_affinity=NodeSelector(terms=(NodeSelectorTerm(
                    match_fields=(LabelSelectorRequirement(
                        key="metadata.name", operator="In",
                        values=(node_name,)),)),)))
            self.client.create_pv(pv)
            self.client.bind_pvc(pvc, pv)
        return Status.success()

    # -- queueing hints --------------------------------------------------------

    def events_to_register(self):
        from ..backend.queue import ClusterEventWithHint
        from ..framework.types import (ActionType, ClusterEvent,
                                       EventResource, QueueingHint)

        def after_pvc_change(pod: Pod, old, new):
            obj = new if new is not None else old
            if obj is None:
                return QueueingHint.QUEUE
            mine = set(pod_pvc_names(pod))
            if (getattr(obj, "namespace", "") == pod.namespace
                    and getattr(obj, "name", "") in mine):
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        def after_pv_add(pod: Pod, old, new):
            # a new PV can only help pods that still have unbound claims
            for name in pod_pvc_names(pod):
                pvc = (self.client.get_pvc(pod.namespace, name)
                       if self.client else None)
                if pvc is not None and not pvc.is_bound():
                    return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.PVC,
                             ActionType.ADD | ActionType.UPDATE),
                after_pvc_change),
            ClusterEventWithHint(
                ClusterEvent(EventResource.PV, ActionType.ADD),
                after_pv_add),
        ]
