"""NodeVolumeLimits (CSI), VolumeRestrictions, VolumeZone.

Mirrors pkg/scheduler/framework/plugins/{nodevolumelimits,volumerestrictions,
volumezone}:

- NodeVolumeLimits (csi.go): count the node's attached CSI volumes per
  driver (existing pods' PVC→PV→driver plus inline CSI volumes) and reject
  when adding the pod's volumes would exceed the node's advertised
  `attachable-volumes-csi-<driver>` allocatable. The reference resolves
  limits through CSINode objects; our node model advertises the same
  quantity directly in allocatable, which is where CSINode mirrors it from.
- VolumeRestrictions (volume_restrictions.go): a ReadWriteOnce /
  ReadWriteOncePod claim already mounted by a pod on ANOTHER node vetoes
  this node set except the holder's (accessMode exclusivity); two pods on
  the same node may share RWO (node-scoped mode).
- VolumeZone (volume_zone.go): a bound PV carrying zone/region labels
  restricts the pod to nodes whose matching topology labels agree.
"""

from __future__ import annotations

from typing import Optional

from ..api.types import Pod
from ..framework.interface import CycleState, Status
from ..framework.types import NodeInfo
from .volumebinding import pod_pvc_names

NODE_VOLUME_LIMITS = "NodeVolumeLimitsCSI"
VOLUME_RESTRICTIONS = "VolumeRestrictions"
VOLUME_ZONE = "VolumeZone"

CSI_LIMIT_PREFIX = "attachable-volumes-csi-"

# volume_zone.go topologyLabels
ZONE_LABELS = ("topology.kubernetes.io/zone", "topology.kubernetes.io/region",
               "failure-domain.beta.kubernetes.io/zone",
               "failure-domain.beta.kubernetes.io/region")

RWO = "ReadWriteOnce"
RWOP = "ReadWriteOncePod"


def _volume_driver(v, namespace: str, client) -> Optional[str]:
    """The attachable volume's CSI driver (inline, or PVC→PV→driver)."""
    if v.csi_driver:
        return v.csi_driver
    if v.claim_name and client is not None:
        pvc = client.get_pvc(namespace, v.claim_name)
        if pvc is not None and pvc.volume_name:
            pv = client.get_pv(pvc.volume_name)
            if pv is not None and pv.csi_driver:
                return pv.csi_driver
    return None


def _attachment_key(v, namespace: str, pod_uid: str) -> str:
    """A claim attaches once per node no matter how many pods mount it;
    inline volumes attach per pod (csi.go uniqueVolumeName)."""
    return (f"{namespace}/{v.claim_name}" if v.claim_name
            else f"{pod_uid}/{v.name}")


class NodeVolumeLimits:
    """PF, F, EE — nodevolumelimits/csi.go."""

    def __init__(self, client=None):
        self.client = client

    def name(self) -> str:
        return NODE_VOLUME_LIMITS

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        wanted = [v for v in pod.spec.volumes
                  if _volume_driver(v, pod.namespace, self.client)]
        if not wanted:
            return Status.success()
        limits = {k[len(CSI_LIMIT_PREFIX):]: v
                  for k, v in node_info.allocatable.items()
                  if k.startswith(CSI_LIMIT_PREFIX)}
        if not limits:
            return Status.success()
        # unique attachments already on the node: attachment key → driver
        # (a claim shared by several pods attaches exactly once)
        attached: dict[str, str] = {}
        for pi in node_info.pods:
            for v in pi.pod.spec.volumes:
                drv = _volume_driver(v, pi.pod.namespace, self.client)
                if drv is not None:
                    attached[_attachment_key(v, pi.pod.namespace,
                                             pi.pod.uid)] = drv
        counts: dict[str, int] = {}
        for drv in attached.values():
            counts[drv] = counts.get(drv, 0) + 1
        # the pod's volumes add attachments only when not already attached
        for v in wanted:
            key = _attachment_key(v, pod.namespace, pod.uid)
            if key in attached:
                continue
            drv = _volume_driver(v, pod.namespace, self.client)
            attached[key] = drv
            counts[drv] = counts.get(drv, 0) + 1
            limit = limits.get(drv)
            if limit is not None and counts[drv] > limit:
                return Status.unschedulable(
                    "node(s) exceed max volume count", plugin=self.name())
        return Status.success()


_VR_STATE_KEY = "PreFilter" + VOLUME_RESTRICTIONS


class VolumeRestrictions:
    """PF, F, EE — volumerestrictions/volume_restrictions.go. The
    cross-cluster holder scan runs ONCE in PreFilter (the reference does
    the same); Filter is a set lookup per node."""

    def __init__(self, client=None):
        self.client = client

    def name(self) -> str:
        return VOLUME_RESTRICTIONS

    def _exclusive_claims(self, pod: Pod) -> set[str]:
        out = set()
        for name in pod_pvc_names(pod):
            pvc = (self.client.get_pvc(pod.namespace, name)
                   if self.client else None)
            if pvc is None:
                continue
            modes = set(pvc.access_modes)
            if RWO in modes or RWOP in modes:
                out.add(f"{pod.namespace}/{name}")
        return out

    def pre_filter(self, state: CycleState, pod: Pod, nodes
                   ) -> tuple[Optional[object], Status]:
        claims = self._exclusive_claims(pod)
        if not claims:
            return None, Status.skip()
        holder_nodes: set[str] = set()
        for ni in nodes:
            for pi in ni.pods:
                if pi.pod.uid == pod.uid:
                    continue
                for v in pi.pod.spec.volumes:
                    if not v.claim_name:
                        continue
                    key = f"{pi.pod.namespace}/{v.claim_name}"
                    if key not in claims:
                        continue
                    pvc = self.client.get_pvc(pi.pod.namespace,
                                              v.claim_name)
                    modes = set(pvc.access_modes) if pvc else set()
                    if RWOP in modes:
                        # ReadWriteOncePod: exclusive across ALL pods
                        return None, Status.unschedulable(
                            "pod uses a ReadWriteOncePod volume already "
                            "in use", plugin=self.name())
                    holder_nodes.add(ni.name)
        state.write(_VR_STATE_KEY, holder_nodes)
        return None, Status.success()

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        holder_nodes = state.read_or_none(_VR_STATE_KEY)
        if not holder_nodes:
            return Status.success()
        if node_info.name not in holder_nodes:
            # RWO: node-exclusive — only a holder's node works
            return Status.unschedulable(
                "volume is already attached to another node",
                plugin=self.name())
        return Status.success()


class VolumeZone:
    """F, EE — volumezone/volume_zone.go: bound PVs' zone labels must match
    the node's topology labels."""

    def __init__(self, client=None):
        self.client = client

    def name(self) -> str:
        return VOLUME_ZONE

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        if self.client is None:
            return Status.success()
        node_labels = node_info.node.metadata.labels
        for name in pod_pvc_names(pod):
            pvc = self.client.get_pvc(pod.namespace, name)
            if pvc is None or not pvc.volume_name:
                continue
            pv = self.client.get_pv(pvc.volume_name)
            if pv is None:
                continue
            for key in ZONE_LABELS:
                want = pv.metadata.labels.get(key)
                if want is None:
                    continue
                # reference allows the label value to be a __-separated set
                allowed = set(want.split("__"))
                have = node_labels.get(key)
                if have is None or have not in allowed:
                    return Status.unresolvable(
                        "node(s) had no available volume zone",
                        plugin=self.name())
        return Status.success()
