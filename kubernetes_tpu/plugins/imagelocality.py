"""ImageLocality score plugin (host/oracle path).

Parity with reference pkg/scheduler/framework/plugins/imagelocality/
image_locality.go: score = MaxNodeScore·(clamp(Σ scaled image sizes) −
minThreshold)/(maxThreshold − minThreshold), where each present image
contributes size·(numNodesWithImage/totalNodes) (image_locality.go:95-131),
and image names are normalized with an implicit ":latest" tag
(image_locality.go:138-143).

Tensor form: a (nodes × images) size matrix dotted with the pod's image
indicator vector — see ops/program.py.
"""

from __future__ import annotations

from ..api.types import Pod
from ..framework.interface import MAX_NODE_SCORE, CycleState, Status
from ..framework.types import NodeInfo

NAME = "ImageLocality"

MB = 1024 * 1024
MIN_THRESHOLD = 23 * MB
MAX_CONTAINER_THRESHOLD = 1000 * MB

_PRE_SCORE_KEY = "PreScore" + NAME


def normalized_image_name(name: str) -> str:
    if name.rfind(":") <= name.rfind("/"):
        name = name + ":latest"
    return name


def calculate_priority(sum_scores: int, num_containers: int) -> int:
    max_threshold = MAX_CONTAINER_THRESHOLD * num_containers
    if sum_scores < MIN_THRESHOLD:
        sum_scores = MIN_THRESHOLD
    elif sum_scores > max_threshold:
        sum_scores = max_threshold
    return MAX_NODE_SCORE * (sum_scores - MIN_THRESHOLD) // (max_threshold - MIN_THRESHOLD)


class ImageLocality:
    """S, Sg — reference image_locality.go. NumNodes per image comes from a
    PreScore pass over the node list (the reference maintains the same
    aggregate in the cache's imageStates, cache.go)."""

    def name(self) -> str:
        return NAME

    def pre_score(self, state: CycleState, pod: Pod, nodes: list[NodeInfo],
                  all_nodes=None) -> Status:
        pool = all_nodes if all_nodes is not None else nodes
        num_nodes_with: dict[str, int] = {}
        for ni in pool:
            for img in ni.image_sizes:
                num_nodes_with[img] = num_nodes_with.get(img, 0) + 1
        state.write(_PRE_SCORE_KEY, (num_nodes_with, len(pool)))
        return Status.success()

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo
              ) -> tuple[int, Status]:
        pre = state.read_or_none(_PRE_SCORE_KEY)
        if pre is None:
            num_nodes_with, total = {}, 1
        else:
            num_nodes_with, total = pre
        total = max(total, 1)
        containers = list(pod.spec.init_containers) + list(pod.spec.containers)
        total_sum = 0
        for c in containers:
            img = normalized_image_name(c.image)
            size = node_info.image_sizes.get(img)
            if size is not None:
                spread = num_nodes_with.get(img, 1) / total
                total_sum += int(size * spread)
        if not containers:
            return 0, Status.success()
        return calculate_priority(total_sum, len(containers)), Status.success()

    def normalize_scores(self, state, pod, scores, node_names=None) -> Status:
        return Status.success()

