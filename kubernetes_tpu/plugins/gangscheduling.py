"""GangScheduling: all-or-nothing pod groups.

Mirrors pkg/scheduler/framework/plugins/gangscheduling/gangscheduling.go:
- PreEnqueue (:120-158): a gang pod stays out of the scheduling queue until
  its Workload object exists and the group has ≥ MinCount known pods.
- Reserve / Unreserve (:163-187): mark the pod assumed / forgotten in the
  WorkloadManager — assumed pods hold their node's resources while parked.
- Permit (:201-251): Wait until assumed+assigned ≥ MinCount, then Allow()
  every parked member; quorum-missing pods also re-activate the group's
  unscheduled pods so they get scheduling attempts promptly.
- events_to_register: a Workload add can only make this plugin's rejects
  schedulable (isSchedulableAfterWorkloadAdded, :100).

The `handle` is the Scheduler: get_waiting_pod / activate /
workload_manager / get_workload, the subset of framework.Handle the
reference plugin consumes.
"""

from __future__ import annotations

from typing import Optional

from ..api.types import Pod
from ..backend.workloadmanager import (parse_workload_ref,
                                       pod_group_min_count)
from ..framework.interface import Code, CycleState, Status

WAIT = Status(Code.WAIT, ("waiting for minCount pods from a gang to be "
                          "waiting on permit",), "GangScheduling")


class GangScheduling:
    def __init__(self, handle=None, scheduling_timeout_seconds=None):
        self.handle = handle
        # per-profile wait budget (GangSchedulingArgs via config
        # pluginArgs; defaults to the WorkloadManager's 300s)
        from ..backend.workloadmanager import DEFAULT_SCHEDULING_TIMEOUT
        self.scheduling_timeout_seconds = (
            scheduling_timeout_seconds or DEFAULT_SCHEDULING_TIMEOUT)

    def name(self) -> str:
        return "GangScheduling"

    # -- PreEnqueue (gangscheduling.go:120) -----------------------------------

    def pre_enqueue(self, pod: Pod) -> Status:
        if not pod.spec.workload_ref:
            return Status.success()
        name, group = parse_workload_ref(pod.spec.workload_ref)
        workload = self.handle.get_workload(pod.namespace, name)
        if workload is None:
            return Status.unresolvable(
                f"waiting for pod's workload {name!r} to appear",
                plugin=self.name())
        min_count = pod_group_min_count(workload, group)
        if min_count is None:
            return Status.unresolvable(
                f"pod group {group!r} doesn't exist for workload {name!r}",
                plugin=self.name())
        info = self.handle.workload_manager.pod_group_info(pod)
        if info is None or len(info.all_pods) < min_count:
            return Status.unresolvable(
                "waiting for minCount pods from a gang to appear in "
                "scheduling queue", plugin=self.name())
        return Status.success()

    # -- Reserve / Unreserve (gangscheduling.go:163-187) ----------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        if not pod.spec.workload_ref:
            return Status.success()
        info = self.handle.workload_manager.pod_group_info(pod)
        if info is None:
            return Status.error(
                f"no pod group state for {pod.spec.workload_ref!r}",
                plugin=self.name())
        info.assume_pod(pod.uid)
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        if not pod.spec.workload_ref:
            return
        info = self.handle.workload_manager.pod_group_info(pod)
        if info is not None:
            info.forget_pod(pod.uid)

    # -- Permit (gangscheduling.go:201) ---------------------------------------

    def permit(self, state: CycleState, pod: Pod,
               node_name: str) -> tuple[Status, float]:
        if not pod.spec.workload_ref:
            return Status.success(), 0.0
        name, group = parse_workload_ref(pod.spec.workload_ref)
        workload = self.handle.get_workload(pod.namespace, name)
        if workload is None:
            return Status.error(
                f"failed to get workload {pod.namespace}/{name}",
                plugin=self.name()), 0.0
        min_count = pod_group_min_count(workload, group)
        if min_count is None:
            return Status.error(
                f"pod group {group!r} doesn't exist for workload {name!r}",
                plugin=self.name()), 0.0
        info = self.handle.workload_manager.pod_group_info(pod)
        if info is None:
            return Status.error("no pod group state", plugin=self.name()), 0.0
        quorum = info.assumed | info.assigned
        if len(quorum) < min_count:
            timeout = info.scheduling_timeout(
                self.handle.now(), self.scheduling_timeout_seconds)
            if timeout <= 0:
                # the group deadline already expired: reject outright —
                # waking members of a dead gang would ping-pong them
                # between activeQ and unschedulable forever
                return Status.unschedulable(
                    "gang scheduling deadline expired",
                    plugin=self.name()), 0.0
            # wake the group's unscheduled members so they can contribute
            self.handle.activate([info.all_pods[u]
                                  for u in info.unscheduled
                                  if u in info.all_pods])
            return WAIT, timeout
        # quorum met: release every parked member, then permit this pod
        for uid in list(info.assumed):
            if uid == pod.uid:
                continue
            waiting = self.handle.get_waiting_pod(uid)
            if waiting is not None:
                waiting.allow(self.name())
        return Status.success(), 0.0

    # -- queueing hints (gangscheduling.go:100) --------------------------------

    def events_to_register(self):
        from ..backend.queue import ClusterEventWithHint
        from ..framework.types import (ActionType, ClusterEvent,
                                       EventResource, QueueingHint)

        def after_workload_change(pod: Pod, old, new) -> QueueingHint:
            if not pod.spec.workload_ref or new is None:
                return QueueingHint.SKIP
            name, _ = parse_workload_ref(pod.spec.workload_ref)
            meta = getattr(new, "metadata", None)
            if (meta is not None and meta.name == name
                    and meta.namespace == pod.namespace):
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [ClusterEventWithHint(
            ClusterEvent(EventResource.WORKLOAD, ActionType.ADD),
            after_workload_change)]
