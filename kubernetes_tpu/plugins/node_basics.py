"""The small node-predicate plugins: NodeName, NodeUnschedulable,
TaintToleration, NodePorts, SchedulingGates, PrioritySort.

Reference directories under pkg/scheduler/framework/plugins/:
nodename/node_name.go, nodeunschedulable/node_unschedulable.go,
tainttoleration/taint_toleration.go, nodeports/node_ports.go,
schedulinggates/scheduling_gates.go, queuesort/priority_sort.go.
"""

from __future__ import annotations

from typing import Optional

from ..api.types import Pod, Taint, TaintEffect, Toleration
from ..framework.interface import CycleState, PreFilterResult, Status
from ..framework.types import NodeInfo, QueuedPodInfo
from .helper import default_normalize

NODE_NAME = "NodeName"
NODE_UNSCHEDULABLE = "NodeUnschedulable"
TAINT_TOLERATION = "TaintToleration"
NODE_PORTS = "NodePorts"
SCHEDULING_GATES = "SchedulingGates"
PRIORITY_SORT = "PrioritySort"

_PORTS_PRE_FILTER_KEY = "PreFilter" + NODE_PORTS
_TAINT_PRE_SCORE_KEY = "PreScore" + TAINT_TOLERATION


def _hint_events():
    from ..backend.queue import ClusterEventWithHint
    from ..framework.types import ActionType, ClusterEvent, EventResource
    return ClusterEventWithHint, ActionType, ClusterEvent, EventResource


class NodeName:
    """F, Sg — nodename/node_name.go: pod.Spec.NodeName must equal node name."""

    def name(self) -> str:
        return NODE_NAME

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.name:
            return Status.unresolvable(
                "node(s) didn't match the requested node name", plugin=NODE_NAME)
        return Status.success()

    def events_to_register(self):
        """node_name.go EventsToRegister: only the arrival of the named
        node can help."""
        CEWH, AT, CE, ER = _hint_events()

        def after_node_add(pod: Pod, old, new):
            from ..framework.types import QueueingHint
            if new is not None and pod.spec.node_name == new.metadata.name:
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [CEWH(CE(ER.NODE, AT.ADD), after_node_add)]



class NodeUnschedulable:
    """F, EE, Sg — node_unschedulable.go: reject unschedulable nodes unless
    the pod tolerates the node.kubernetes.io/unschedulable:NoSchedule taint."""

    TAINT = Taint(key="node.kubernetes.io/unschedulable", value="",
                  effect=TaintEffect.NO_SCHEDULE.value)

    def name(self) -> str:
        return NODE_UNSCHEDULABLE

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if not node_info.node.spec.unschedulable:
            return Status.success()
        if any(t.tolerates(self.TAINT) for t in pod.spec.tolerations):
            return Status.success()
        return Status.unresolvable("node(s) were unschedulable", plugin=NODE_UNSCHEDULABLE)

    def events_to_register(self):
        """node_unschedulable.go isSchedulableAfterNodeChange: only a node
        that is (now) schedulable — or whose cordon the pod tolerates —
        can help. Cordon flips arrive as UPDATE_NODE_TAINT (the reference
        maps spec.unschedulable to the taint event)."""
        CEWH, AT, CE, ER = _hint_events()

        def after_node_change(pod: Pod, old, new):
            from ..framework.types import QueueingHint
            if new is None:
                return QueueingHint.QUEUE
            if (not new.spec.unschedulable
                    or any(t.tolerates(self.TAINT)
                           for t in pod.spec.tolerations)):
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [CEWH(CE(ER.NODE, AT.ADD | AT.UPDATE_NODE_TAINT),
                     after_node_change)]

def find_matching_untolerated_taint(taints: list[Taint], tolerations: list[Toleration],
                                    effects: tuple[str, ...]) -> Optional[Taint]:
    """Reference: component-helpers v1helper.FindMatchingUntoleratedTaint."""
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return taint
    return None


class TaintToleration:
    """PF?, F, PS, S, N, EE, Sg — taint_toleration.go.

    Filter: untolerated NoSchedule/NoExecute taint ⇒ UnschedulableAndUnresolvable.
    Score: count of untolerated PreferNoSchedule taints, normalized reversed.
    """

    FILTER_EFFECTS = (TaintEffect.NO_SCHEDULE.value, TaintEffect.NO_EXECUTE.value)

    def name(self) -> str:
        return TAINT_TOLERATION

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        taint = find_matching_untolerated_taint(
            node_info.node.spec.taints, pod.spec.tolerations, self.FILTER_EFFECTS)
        if taint is not None:
            return Status.unresolvable(
                f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}",
                plugin=TAINT_TOLERATION)
        return Status.success()

    def pre_score(self, state: CycleState, pod: Pod, nodes, all_nodes=None) -> Status:
        prefer_tolerations = [t for t in pod.spec.tolerations
                              if not t.effect or t.effect == TaintEffect.PREFER_NO_SCHEDULE.value]
        state.write(_TAINT_PRE_SCORE_KEY, prefer_tolerations)
        return Status.success()

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> tuple[int, Status]:
        tolerations = state.read_or_none(_TAINT_PRE_SCORE_KEY)
        if tolerations is None:
            tolerations = [t for t in pod.spec.tolerations
                           if not t.effect or t.effect == TaintEffect.PREFER_NO_SCHEDULE.value]
        count = sum(
            1 for taint in node_info.node.spec.taints
            if taint.effect == TaintEffect.PREFER_NO_SCHEDULE.value
            and not any(t.tolerates(taint) for t in tolerations))
        return count, Status.success()

    def normalize_scores(self, state: CycleState, pod: Pod, scores: list[int],
                         node_names=None) -> Status:
        scores[:] = default_normalize(scores, reverse=True)
        return Status.success()

    def events_to_register(self):
        """taint_toleration.go isSchedulableAfterNodeChange: queue only
        when the pod tolerates the (new) node's hard taints — e.g. a
        taint removal."""
        CEWH, AT, CE, ER = _hint_events()

        def after_node_change(pod: Pod, old, new):
            from ..framework.types import QueueingHint
            if new is None:
                return QueueingHint.QUEUE
            taint = find_matching_untolerated_taint(
                new.spec.taints, pod.spec.tolerations, self.FILTER_EFFECTS)
            return (QueueingHint.SKIP if taint is not None
                    else QueueingHint.QUEUE)

        return [CEWH(CE(ER.NODE, AT.ADD | AT.UPDATE_NODE_TAINT),
                     after_node_change)]



class NodePorts:
    """PF, F, EE, Sg — node_ports.go: host-port conflicts."""

    def name(self) -> str:
        return NODE_PORTS

    @staticmethod
    def _container_ports(pod: Pod):
        return [p for c in pod.spec.containers for p in c.ports if p.host_port > 0]

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> tuple[Optional[PreFilterResult], Status]:
        ports = self._container_ports(pod)
        state.write(_PORTS_PRE_FILTER_KEY, ports)
        if not ports:
            return None, Status.skip()
        return None, Status.success()

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        ports = state.read_or_none(_PORTS_PRE_FILTER_KEY)
        if ports is None:
            ports = self._container_ports(pod)
        for p in ports:
            if node_info.used_ports.conflicts(p.protocol, p.host_port, p.host_ip):
                return Status.unschedulable("node(s) didn't have free ports for the requested pod ports",
                                            plugin=NODE_PORTS)
        return Status.success()

    def events_to_register(self):
        """node_ports.go: an assigned pod's deletion helps only when it
        held one of the ports this pod wants; new nodes always might."""
        CEWH, AT, CE, ER = _hint_events()

        def after_pod_delete(pod: Pod, old, new):
            from ..framework.types import QueueingHint
            if old is None:
                return QueueingHint.QUEUE
            mine = {(p.protocol or "TCP", p.host_port)
                    for p in self._container_ports(pod)}
            theirs = {(p.protocol or "TCP", p.host_port)
                      for p in self._container_ports(old)}
            return (QueueingHint.QUEUE if mine & theirs
                    else QueueingHint.SKIP)

        return [CEWH(CE(ER.NODE, AT.ADD), None),
                CEWH(CE(ER.ASSIGNED_POD, AT.DELETE), after_pod_delete)]



class SchedulingGates:
    """PE, EE — scheduling_gates.go: gate pods until spec.schedulingGates empty."""

    def name(self) -> str:
        return SCHEDULING_GATES

    def pre_enqueue(self, pod: Pod) -> Status:
        if not pod.spec.scheduling_gates:
            return Status.success()
        gates = ", ".join(g.name for g in pod.spec.scheduling_gates)
        return Status.unresolvable(f"waiting for scheduling gates: {gates}",
                                   plugin=SCHEDULING_GATES)

    # no events_to_register: gated pods never reach the unschedulable
    # pool's hint path (move_all skips gated entries) — gate removal is
    # handled by queue.update re-running PreEnqueue


class NodeDeclaredFeatures:
    """PF, F, EE — nodedeclaredfeatures/nodedeclaredfeatures.go: every
    feature the pod requires must appear in the node's declared feature
    set, else UnschedulableAndUnresolvable. The reference infers the pod's
    requirements from its spec via the ndf library; our object model
    declares them directly in spec.required_node_features."""

    def name(self) -> str:
        return "NodeDeclaredFeatures"

    def pre_filter(self, state: CycleState, pod: Pod, nodes):
        if not pod.spec.required_node_features:
            return None, Status.skip()
        return None, Status.success()

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        declared = set(node_info.node.status.declared_features)
        missing = [f for f in pod.spec.required_node_features
                   if f not in declared]
        if missing:
            return Status.unresolvable(
                "node declared features check failed - unsatisfied "
                f"requirements: {', '.join(missing)}",
                plugin=self.name())
        return Status.success()

    def events_to_register(self):
        CEWH, AT, CE, ER = _hint_events()

        def after_node_change(pod: Pod, old, new):
            from ..framework.types import QueueingHint
            if new is None:
                return QueueingHint.QUEUE
            declared = set(new.status.declared_features)
            if all(f in declared for f in pod.spec.required_node_features):
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [CEWH(CE(ER.NODE,
                        AT.ADD | AT.UPDATE_NODE_DECLARED_FEATURE),
                     after_node_change)]


class PrioritySort:
    """QueueSort — queuesort/priority_sort.go: priority desc, then queue
    timestamp asc."""

    def name(self) -> str:
        return PRIORITY_SORT

    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        p1 = a.pod.spec.priority
        p2 = b.pod.spec.priority
        if p1 != p2:
            return p1 > p2
        return a.timestamp < b.timestamp
