"""NodeResourcesFit + NodeResourcesBalancedAllocation (host/oracle path).

Algorithm parity with the reference:
- Filter: fitsRequest — pkg/scheduler/framework/plugins/noderesources/fit.go:649-738
- LeastAllocated: least_allocated.go:30-60 (int64 division, weighted)
- MostAllocated: most_allocated.go (mirror of least)
- RequestedToCapacityRatio: requested_to_capacity_ratio.go (piecewise-linear)
- BalancedAllocation: balanced_allocation.go:195-237 (std-dev of fractions)

The same arithmetic is implemented in tensor form in ops/program.py; these
host implementations are the decision-parity oracle the device program is
tested against.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from ..api import resources as res
from ..api.types import Pod
from ..framework.interface import (MAX_NODE_SCORE, CycleState, PreFilterResult,
                                   Status)
from ..framework.types import NodeInfo

FIT_NAME = "NodeResourcesFit"
BALANCED_NAME = "NodeResourcesBalancedAllocation"

_PRE_FILTER_KEY = "PreFilter" + FIT_NAME
_PRE_SCORE_KEY = "PreScore" + FIT_NAME
_BALANCED_PRE_SCORE_KEY = "PreScore" + BALANCED_NAME


@dataclass(frozen=True)
class ResourceSpec:
    name: str
    weight: int = 1


DEFAULT_RESOURCES = (ResourceSpec(res.CPU, 1), ResourceSpec(res.MEMORY, 1))

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"


@dataclass(frozen=True)
class UtilizationShapePoint:
    utilization: int  # 0..100
    score: int        # 0..10 (maps onto 0..MaxNodeScore)


@dataclass
class FitArgs:
    scoring_strategy: str = LEAST_ALLOCATED
    resources: tuple[ResourceSpec, ...] = DEFAULT_RESOURCES
    ignored_resources: frozenset[str] = frozenset()
    ignored_resource_groups: frozenset[str] = frozenset()
    shape: tuple[UtilizationShapePoint, ...] = (
        UtilizationShapePoint(0, 0), UtilizationShapePoint(100, 10))


def is_extended_resource(name: str) -> bool:
    """Extended = has a domain prefix and isn't a native resource."""
    return "/" in name and not name.startswith("kubernetes.io/")


# ---------------------------------------------------------------------------
# scorers (exact int64 arithmetic of the reference)


def least_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0 or requested > capacity:
        return 0
    return ((capacity - requested) * MAX_NODE_SCORE) // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    """Reference: most_allocated.go mostRequestedScore."""
    if capacity == 0:
        return 0
    if requested > capacity:
        # `requested` might exceed `capacity` because pods with no requests
        # get non-zero default values.
        return 0
    return (requested * MAX_NODE_SCORE) // capacity


def _weighted(score_fn, requested: list[int], allocatable: list[int],
              resources: tuple[ResourceSpec, ...]) -> int:
    node_score, weight_sum = 0, 0
    for i in range(len(requested)):
        if allocatable[i] == 0:
            continue
        w = resources[i].weight
        node_score += score_fn(requested[i], allocatable[i]) * w
        weight_sum += w
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def requested_to_capacity_ratio_scorer(shape: tuple[UtilizationShapePoint, ...]):
    """Piecewise linear over utilization percent; scores scaled by
    MaxNodeScore/10 (reference: requested_to_capacity_ratio.go
    buildRequestedToCapacityRatioScorerFunction)."""
    xs = [p.utilization for p in shape]
    ys = [p.score * MAX_NODE_SCORE // 10 for p in shape]

    def curve(utilization: int) -> int:
        if utilization <= xs[0]:
            return ys[0]
        if utilization >= xs[-1]:
            return ys[-1]
        for i in range(1, len(xs)):
            if utilization < xs[i]:
                span = xs[i] - xs[i - 1]
                return ys[i - 1] + (ys[i] - ys[i - 1]) * (utilization - xs[i - 1]) // span
        return ys[-1]

    def scorer(requested: list[int], allocatable: list[int],
               resources: tuple[ResourceSpec, ...]) -> int:
        node_score, weight_sum = 0, 0
        for i in range(len(requested)):
            if allocatable[i] == 0:
                continue
            w = resources[i].weight
            util = min(requested[i] * 100 // allocatable[i], 100) if allocatable[i] else 0
            node_score += curve(util) * w
            weight_sum += w
        if weight_sum == 0:
            return 0
        return node_score // weight_sum

    return scorer


def balanced_resource_scorer(requested: list[int], allocatable: list[int]) -> int:
    """Reference: balanced_allocation.go:195-237."""
    fractions: list[float] = []
    total = 0.0
    for i in range(len(requested)):
        if allocatable[i] == 0:
            continue
        f = min(requested[i] / allocatable[i], 1.0)
        total += f
        fractions.append(f)
    std = 0.0
    if len(fractions) == 2:
        std = abs((fractions[0] - fractions[1]) / 2)
    elif len(fractions) > 2:
        mean = total / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
    return int((1 - std) * MAX_NODE_SCORE)


# ---------------------------------------------------------------------------
# shared score-side helpers


def pod_resource_request_list(pod: Pod, resources: tuple[ResourceSpec, ...],
                              use_requested: bool) -> list[int]:
    req = res.pod_requests(pod) if use_requested else res.pod_requests_nonmissing(pod)
    return [req.get(spec.name, 0) for spec in resources]


def _allocatable_and_requested(node_info: NodeInfo, name: str, pod_request: int,
                               use_requested: bool) -> tuple[int, int]:
    """Reference: resource_allocation.go calculateResourceAllocatableRequest."""
    if pod_request == 0 and name not in (res.CPU, res.MEMORY, res.EPHEMERAL_STORAGE):
        # scalar resource the pod doesn't request → bypass
        return 0, 0
    alloc = node_info.allocatable.get(name, 0)
    if name == res.CPU and not use_requested:
        req = node_info.non_zero_cpu
    elif name == res.MEMORY and not use_requested:
        req = node_info.non_zero_mem
    else:
        req = node_info.requested.get(name, 0)
    return alloc, req + pod_request


def _score(node_info: NodeInfo, pod_requests: list[int],
           resources: tuple[ResourceSpec, ...], use_requested: bool,
           scorer) -> int:
    requested = [0] * len(resources)
    allocatable = [0] * len(resources)
    for i, spec in enumerate(resources):
        alloc, req = _allocatable_and_requested(node_info, spec.name,
                                                pod_requests[i], use_requested)
        if alloc == 0:
            continue
        allocatable[i] = alloc
        requested[i] = req
    return scorer(requested, allocatable)


# ---------------------------------------------------------------------------
# Fit plugin


class Fit:
    """PF, F, PS, S, EE, Sg — reference fit.go."""

    def __init__(self, args: Optional[FitArgs] = None):
        self.args = args or FitArgs()
        if self.args.scoring_strategy == REQUESTED_TO_CAPACITY_RATIO:
            curve = requested_to_capacity_ratio_scorer(self.args.shape)
            self._scorer = lambda r, a: curve(r, a, self.args.resources)
        elif self.args.scoring_strategy == MOST_ALLOCATED:
            self._scorer = lambda r, a: _weighted(most_requested_score, r, a, self.args.resources)
        else:
            self._scorer = lambda r, a: _weighted(least_requested_score, r, a, self.args.resources)

    def name(self) -> str:
        return FIT_NAME

    # -- PreFilter ----------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> tuple[Optional[PreFilterResult], Status]:
        state.write(_PRE_FILTER_KEY, res.pod_requests(pod))
        return None, Status.success()

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        pod_request: dict[str, int] = state.read_or_none(_PRE_FILTER_KEY)
        if pod_request is None:
            pod_request = res.pod_requests(pod)
        insufficient = insufficient_resources(pod_request, node_info,
                                              self.args.ignored_resources,
                                              self.args.ignored_resource_groups)
        if insufficient:
            reasons = tuple(r for r, _ in insufficient)
            if any(unresolvable for _, unresolvable in insufficient):
                return Status.unresolvable(*reasons, plugin=FIT_NAME)
            return Status.unschedulable(*reasons, plugin=FIT_NAME)
        return Status.success()

    # -- Score --------------------------------------------------------------

    def pre_score(self, state: CycleState, pod: Pod, nodes, all_nodes=None) -> Status:
        state.write(_PRE_SCORE_KEY,
                    pod_resource_request_list(pod, self.args.resources, use_requested=False))
        return Status.success()

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> tuple[int, Status]:
        reqs = state.read_or_none(_PRE_SCORE_KEY)
        if reqs is None:
            reqs = pod_resource_request_list(pod, self.args.resources, use_requested=False)
        return _score(node_info, reqs, self.args.resources, False, self._scorer), Status.success()

    def normalize_scores(self, state, pod, scores, node_names=None) -> Status:
        return Status.success()

    def events_to_register(self):
        """fit.go EventsToRegister + isSchedulableAfterNodeChange /
        isSchedulableAfterPodEvent: node arrivals or allocatable growth
        queue only when the pod's requests could fit the node outright;
        an assigned pod's deletion queues only when it releases a resource
        this pod asks for."""
        from ..backend.queue import ClusterEventWithHint
        from ..framework.types import (ActionType, ClusterEvent,
                                       EventResource, QueueingHint)

        def after_node_change(pod: Pod, old, new):
            if new is None:
                return QueueingHint.QUEUE
            requests = res.pod_requests(pod)
            alloc = new.status.allocatable
            for r, v in requests.items():
                if v > 0 and v > alloc.get(r, 0):
                    return QueueingHint.SKIP
            if alloc.get(res.PODS, 1) < 1:
                return QueueingHint.SKIP
            return QueueingHint.QUEUE

        def after_pod_event(pod: Pod, old, new):
            # DELETE of an assigned pod (old=pod, new=None) frees its whole
            # request; a scale-down frees only the old−new delta. Queue
            # only when a freed resource overlaps one this pod asks for.
            if old is None:
                return QueueingHint.QUEUE
            freed = dict(res.pod_requests(old))
            if new is not None:
                for r, v in res.pod_requests(new).items():
                    freed[r] = freed.get(r, 0) - v
            mine = res.pod_requests(pod)
            for r, v in mine.items():
                if v > 0 and freed.get(r, 0) > 0:
                    return QueueingHint.QUEUE
            # a deletion also frees a pod-count slot; only relevant when
            # the pod requests nothing else
            return (QueueingHint.QUEUE
                    if new is None and not any(mine.values())
                    else QueueingHint.SKIP)

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.NODE,
                             ActionType.ADD | ActionType.UPDATE_NODE_ALLOCATABLE),
                after_node_change),
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD,
                             ActionType.DELETE | ActionType.UPDATE_POD_SCALE_DOWN),
                after_pod_event),
        ]

def insufficient_resources(pod_request: dict[str, int], node_info: NodeInfo,
                           ignored: frozenset[str] = frozenset(),
                           ignored_groups: frozenset[str] = frozenset(),
                           ) -> list[tuple[str, bool]]:
    """fitsRequest (fit.go:649-738) → [(reason, unresolvable)]."""
    out: list[tuple[str, bool]] = []
    allowed_pods = node_info.allocatable.get(res.PODS, 0)
    if len(node_info.pods) + 1 > allowed_pods:
        out.append(("Too many pods", False))

    interesting = {k: v for k, v in pod_request.items() if k != res.PODS}
    if all(v == 0 for v in interesting.values()):
        return out

    for name in (res.CPU, res.MEMORY, res.EPHEMERAL_STORAGE):
        req = pod_request.get(name, 0)
        if req <= 0:
            continue
        alloc = node_info.allocatable.get(name, 0)
        used = node_info.requested.get(name, 0)
        if req > alloc - used:
            out.append((f"Insufficient {name}", req > alloc))

    for name, req in pod_request.items():
        if name in (res.CPU, res.MEMORY, res.EPHEMERAL_STORAGE, res.PODS) or req == 0:
            continue
        if is_extended_resource(name):
            prefix = name.split("/")[0]
            if name in ignored or prefix in ignored_groups:
                continue
        alloc = node_info.allocatable.get(name, 0)
        used = node_info.requested.get(name, 0)
        if req > alloc - used:
            out.append((f"Insufficient {name}", req > alloc))
    return out


# ---------------------------------------------------------------------------
# BalancedAllocation plugin


@dataclass
class BalancedAllocationArgs:
    resources: tuple[ResourceSpec, ...] = DEFAULT_RESOURCES


class BalancedAllocation:
    """PS, S — reference balanced_allocation.go. useRequested=true."""

    def __init__(self, args: Optional[BalancedAllocationArgs] = None):
        self.args = args or BalancedAllocationArgs()

    def name(self) -> str:
        return BALANCED_NAME

    def pre_score(self, state: CycleState, pod: Pod, nodes, all_nodes=None) -> Status:
        reqs = pod_resource_request_list(pod, self.args.resources, use_requested=True)
        if all(r == 0 for r in reqs):
            # best-effort pod: skip to avoid piling onto one node
            # (reference balanced_allocation.go:84 → issue #129138)
            return Status.skip()
        state.write(_BALANCED_PRE_SCORE_KEY, reqs)
        return Status.success()

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> tuple[int, Status]:
        reqs = state.read_or_none(_BALANCED_PRE_SCORE_KEY)
        if reqs is None:
            reqs = pod_resource_request_list(pod, self.args.resources, use_requested=True)
            if all(r == 0 for r in reqs):
                return 0, Status.success()
        score = _score(node_info, reqs, self.args.resources, True,
                       lambda r, a: balanced_resource_scorer(r, a))
        return score, Status.success()

    def normalize_scores(self, state, pod, scores, node_names=None) -> Status:
        return Status.success()

