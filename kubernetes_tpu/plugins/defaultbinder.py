"""DefaultBinder bind plugin.

Parity with reference pkg/scheduler/framework/plugins/defaultbinder/
default_binder.go:51: POST the Binding subresource — here a call into the
API client's `bind` (routed through the async dispatcher when enabled,
mirroring the APICacher path).
"""

from __future__ import annotations

from ..api.types import Pod
from ..framework.interface import CycleState, Status

NAME = "DefaultBinder"


class DefaultBinder:
    """B — reference default_binder.go."""

    def __init__(self, client):
        self.client = client

    def name(self) -> str:
        return NAME

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        try:
            self.client.bind(pod, node_name)
        except Exception as e:  # API failure surfaces as Error status
            return Status.error(str(e), plugin=NAME)
        return Status.success()
