"""DefaultPreemption: the PostFilter plugin.

Mirrors pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go:
- `PostFilter` (:107) delegates to the preemption Evaluator and converts
  its result to a nominated node name.
- candidate sizing (:174) lives in the Evaluator.
- victim deletion + nomination publication happen in `prepare` here (the
  reference's Evaluator.prepareCandidate, preemption.go:180): victims go to
  the API dispatcher as DELETE calls, and lower-priority pods nominated on
  the chosen node lose their nomination (preemption.go:210).

The plugin is constructed by the Scheduler with live handles (dispatcher,
nominator) — the reference wires the same dependencies through
frameworkImpl."""

from __future__ import annotations

from typing import Optional

from ..api.types import Pod
from ..framework.interface import CycleState, Status
from ..framework.preemption import Evaluator


class DefaultPreemption:
    def __init__(self, dispatcher=None, nominator=None, snapshot=None,
                 pdb_lister=None, extenders=(), device_ctx=None):
        self.dispatcher = dispatcher
        self.nominator = nominator
        self.snapshot = snapshot
        self.pdb_lister = pdb_lister
        self.extenders = tuple(extenders)
        # framework.preemption.DeviceDryRunContext — enables the batched
        # device dry-run (SURVEY §7 step 8); None keeps the host loop
        self.device_ctx = device_ctx
        self._evaluator: Optional[Evaluator] = None
        self._fwk = None

    def name(self) -> str:
        return "DefaultPreemption"

    def set_framework(self, fwk) -> None:
        """Called by the Scheduler after the Framework exists (the Evaluator
        needs the full plugin set for its dry-run filters)."""
        self._fwk = fwk
        self._evaluator = Evaluator(
            fwk, nominator=self.nominator,
            is_delete_pending=(self.dispatcher.is_delete_pending
                               if self.dispatcher is not None else None),
            pdb_lister=self.pdb_lister,
            extenders=self.extenders,
            device_ctx=self.device_ctx)

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> tuple[Optional[str], Status]:
        """default_preemption.go:107 → (nominated node name, status)."""
        if self._evaluator is None or self.snapshot is None:
            return None, Status.unschedulable("preemption not wired",
                                              plugin=self.name())
        from ..framework.types import Diagnosis
        diagnosis = Diagnosis(node_to_status=dict(filtered_node_status_map))
        nodes = self.snapshot.node_info_list
        candidate, status = self._evaluator.preempt(state, pod, nodes,
                                                    diagnosis)
        if not status.is_success() or candidate is None:
            return None, status
        self._prepare(pod, candidate)
        return candidate.node_name, Status.success()

    def _prepare(self, pod: Pod, candidate) -> None:
        """preemption.go:180 prepareCandidate: delete victims, demote
        lower-priority nominations on the node."""
        from ..backend.dispatcher import APICall, CallType
        for pi in candidate.victims:
            self.dispatcher.add(APICall(CallType.DELETE, pi.pod))
        if self.nominator is not None:
            for q in self.nominator.pods_for_node(candidate.node_name):
                if q.pod.spec.priority < pod.spec.priority:
                    self.nominator.delete(q.pod)
                    # clear the live object too: Nominator.add falls back to
                    # pod.status.nominated_node_name on requeue and must not
                    # resurrect the demoted nomination
                    q.pod.status.nominated_node_name = ""
                    self.dispatcher.add(APICall(
                        CallType.STATUS_PATCH, q.pod,
                        condition={}, nominated_node_name=""))
