"""PodTopologySpread plugin (host/oracle path).

Algorithm parity with the reference (pkg/scheduler/framework/plugins/
podtopologyspread/):
- PreFilter/Filter: filtering.go — per-constraint match counts per topology
  value, two-entry criticalPaths min tracking (filtering.go:97-136), skew
  judgment `matchNum + selfMatch - minMatchNum > maxSkew` (filtering.go:338-356),
  minDomains treating the global min as 0 when domains < minDomains
  (filtering.go:66-77).
- AddPod/RemovePod PreFilterExtensions for preemption dry-runs
  (filtering.go:156-214).
- PreScore/Score/Normalize: scoring.go — counts over all nodes restricted to
  filtered-node topology values, score = cnt·log(size+2) + (maxSkew−1)
  (scoring.go:297-307), normalize = MaxNodeScore·(max+min−s)/max
  (scoring.go:229-267).

Node inclusion policies (NodeAffinityPolicy default Honor, NodeTaintsPolicy
default Ignore — common.go:108-123) are always enabled, matching the
reference's GA feature-gate state.

The tensor form of this plugin lives in ops/program.py: the count maps become
a (constraints × topology-values) matrix, criticalPaths a min-reduce, and the
scan-carried state updates the counts after each placement.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Optional

from ..api.types import (LabelSelector, Pod, TopologySpreadConstraint,
                         UnsatisfiableConstraintAction)
from ..framework.interface import (MAX_NODE_SCORE, CycleState, PreFilterResult,
                                   Status)
from ..framework.types import NodeInfo, PodInfo
from .nodeaffinity import required_node_affinity_matches
from .node_basics import find_matching_untolerated_taint

NAME = "PodTopologySpread"
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"

ERR_REASON_CONSTRAINTS_NOT_MATCH = "node(s) didn't match pod topology spread constraints"
ERR_REASON_NODE_LABEL_NOT_MATCH = (
    ERR_REASON_CONSTRAINTS_NOT_MATCH + " (missing required label)")

_PRE_FILTER_KEY = "PreFilter" + NAME
_PRE_SCORE_KEY = "PreScore" + NAME

_MAX_INT32 = 2 ** 31 - 1

HONOR = "Honor"
IGNORE = "Ignore"

# System default constraints used when the pod declares none
# (reference: apis/config/v1/defaults.go SetDefaults_KubeSchedulerConfiguration
# → defaultConstraints maxSkew 3 zone / 5 hostname, ScheduleAnyway).
SYSTEM_DEFAULT_CONSTRAINTS = (
    TopologySpreadConstraint(max_skew=3, topology_key=LABEL_ZONE,
                             when_unsatisfiable=UnsatisfiableConstraintAction.SCHEDULE_ANYWAY.value),
    TopologySpreadConstraint(max_skew=5, topology_key=LABEL_HOSTNAME,
                             when_unsatisfiable=UnsatisfiableConstraintAction.SCHEDULE_ANYWAY.value),
)


@dataclass
class _Constraint:
    """Internal parsed constraint (reference common.go:34-41)."""

    max_skew: int
    topology_key: str
    selector: LabelSelector
    min_domains: int = 1
    node_affinity_policy: str = HONOR
    node_taints_policy: str = IGNORE


def _parse_constraints(constraints, pod_labels: dict[str, str], action: str,
                       match_label_keys_enabled: bool = True) -> list[_Constraint]:
    """filterTopologySpreadConstraints (common.go:87-128): keep constraints
    with the requested action; merge matchLabelKeys values into the selector."""
    out: list[_Constraint] = []
    for c in constraints:
        if c.when_unsatisfiable != action:
            continue
        selector = c.label_selector or LabelSelector()
        if match_label_keys_enabled and c.match_label_keys:
            extra = {k: pod_labels[k] for k in c.match_label_keys if k in pod_labels}
            if extra:
                merged = dict(selector.match_labels)
                merged.update(extra)
                selector = LabelSelector(
                    match_labels=tuple(sorted(merged.items())),
                    match_expressions=selector.match_expressions)
        out.append(_Constraint(
            max_skew=c.max_skew,
            topology_key=c.topology_key,
            selector=selector,
            min_domains=c.min_domains if c.min_domains is not None else 1,
            node_affinity_policy=c.node_affinity_policy or HONOR,
            node_taints_policy=c.node_taints_policy or IGNORE,
        ))
    return out


def _selector_empty(sel: LabelSelector) -> bool:
    return not sel.match_labels and not sel.match_expressions


def _count_pods_match_selector(pod_infos: list[PodInfo], selector: LabelSelector,
                               ns: str) -> int:
    """common.go:145-160 — empty selector matches nothing; namespace-scoped."""
    if _selector_empty(selector):
        return 0
    count = 0
    for pi in pod_infos:
        pod = pi.pod
        if pod.namespace != ns:
            continue
        if selector.matches(pod.metadata.labels):
            count += 1
    return count


def _node_has_all_topology_keys(node_labels: dict[str, str],
                                constraints: list[_Constraint]) -> bool:
    return all(c.topology_key in node_labels for c in constraints)


def _match_node_inclusion_policies(c: _Constraint, pod: Pod, node_info: NodeInfo) -> bool:
    """common.go:43-57."""
    node = node_info.node
    if c.node_affinity_policy == HONOR:
        if not required_node_affinity_matches(pod, node.metadata.labels, node.name):
            return False
    if c.node_taints_policy == HONOR:
        if find_matching_untolerated_taint(
                node.spec.taints, pod.spec.tolerations,
                ("NoSchedule", "NoExecute")) is not None:
            return False
    return True


class _CriticalPaths:
    """Two-entry min tracker (filtering.go:97-136). paths[0] holds the true
    minimum; paths[1] is ≥ paths[0] but not necessarily the 2nd minimum."""

    __slots__ = ("v0", "n0", "v1", "n1")

    def __init__(self) -> None:
        self.v0, self.n0 = None, _MAX_INT32
        self.v1, self.n1 = None, _MAX_INT32

    def copy(self) -> "_CriticalPaths":
        cp = _CriticalPaths()
        cp.v0, cp.n0, cp.v1, cp.n1 = self.v0, self.n0, self.v1, self.n1
        return cp

    def update(self, tp_val: str, num: int) -> None:
        if tp_val == self.v0:
            self.n0 = num
            if self.n0 > self.n1:
                self.v0, self.n0, self.v1, self.n1 = self.v1, self.n1, self.v0, self.n0
        elif tp_val == self.v1:
            self.n1 = num
            if self.n0 > self.n1:
                self.v0, self.n0, self.v1, self.n1 = self.v1, self.n1, self.v0, self.n0
        elif num < self.n0:
            self.v1, self.n1 = self.v0, self.n0
            self.v0, self.n0 = tp_val, num
        elif num < self.n1:
            self.v1, self.n1 = tp_val, num

    def min_match(self) -> int:
        return self.n0


@dataclass
class _PreFilterState:
    constraints: list[_Constraint] = field(default_factory=list)
    critical_paths: list[_CriticalPaths] = field(default_factory=list)
    tp_value_to_match_num: list[dict[str, int]] = field(default_factory=list)

    def clone(self) -> "_PreFilterState":
        """filtering.go preFilterState.Clone() — mutable counts copied,
        parsed constraints shared (immutable)."""
        return _PreFilterState(
            constraints=self.constraints,
            critical_paths=[cp.copy() for cp in self.critical_paths],
            tp_value_to_match_num=[dict(d) for d in self.tp_value_to_match_num])

    def min_match_num(self, i: int, min_domains: int) -> int:
        """filtering.go:66-77 — fewer eligible domains than minDomains ⇒
        treat the global minimum as 0."""
        if len(self.tp_value_to_match_num[i]) < min_domains:
            return 0
        return self.critical_paths[i].min_match()


@dataclass
class _PreScoreState:
    constraints: list[_Constraint] = field(default_factory=list)
    ignored_nodes: set[str] = field(default_factory=set)
    topology_value_to_pod_counts: list[dict[str, int]] = field(default_factory=list)
    topology_normalizing_weight: list[float] = field(default_factory=list)


@dataclass
class PodTopologySpreadArgs:
    default_constraints: tuple[TopologySpreadConstraint, ...] = ()
    # "System" defaulting uses cluster-level defaults and relaxed topology
    # requirements in scoring (reference plugin.go systemDefaulted).
    defaulting_type: str = "List"  # "List" | "System"


class PodTopologySpread:
    """PF(+Extensions), F, PS, S, N, EE, Sg — reference podtopologyspread/."""

    def __init__(self, args: Optional[PodTopologySpreadArgs] = None):
        self.args = args or PodTopologySpreadArgs()
        self.system_defaulted = self.args.defaulting_type == "System"
        self.default_constraints = (
            SYSTEM_DEFAULT_CONSTRAINTS if self.system_defaulted
            else self.args.default_constraints)

    def name(self) -> str:
        return NAME

    # -- constraint selection -------------------------------------------------

    def _get_constraints(self, pod: Pod, action: str) -> list[_Constraint]:
        if pod.spec.topology_spread_constraints:
            return _parse_constraints(pod.spec.topology_spread_constraints,
                                      pod.metadata.labels, action)
        constraints = _parse_constraints(self.default_constraints,
                                         pod.metadata.labels, action)
        if not constraints:
            return []
        # buildDefaultConstraints uses the owning workload's selector
        # (common.go:62-75). We have no service/RS listers in the in-memory
        # model; use the pod's own labels as the selector, which is what the
        # workload selector resolves to for homogeneous groups.
        selector = LabelSelector.of(dict(pod.metadata.labels))
        if _selector_empty(selector):
            return []
        return [replace(c, selector=selector) for c in constraints]

    # -- PreFilter ------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
                   ) -> tuple[Optional[PreFilterResult], Status]:
        constraints = self._get_constraints(
            pod, UnsatisfiableConstraintAction.DO_NOT_SCHEDULE.value)
        if not constraints:
            return None, Status.skip()
        s = _PreFilterState(constraints=constraints)
        s.tp_value_to_match_num = [dict() for _ in constraints]
        for ni in nodes:
            node = ni.node
            if not _node_has_all_topology_keys(node.metadata.labels, constraints):
                continue
            for i, c in enumerate(constraints):
                if not _match_node_inclusion_policies(c, pod, ni):
                    continue
                value = node.metadata.labels[c.topology_key]
                count = _count_pods_match_selector(ni.pods, c.selector, pod.namespace)
                s.tp_value_to_match_num[i][value] = (
                    s.tp_value_to_match_num[i].get(value, 0) + count)
        s.critical_paths = [_CriticalPaths() for _ in constraints]
        for i in range(len(constraints)):
            for value, num in s.tp_value_to_match_num[i].items():
                s.critical_paths[i].update(value, num)
        state.write(_PRE_FILTER_KEY, s)
        return None, Status.success()

    def events_to_register(self):
        """podtopologyspread.go EventsToRegister: assigned-pod churn in the
        pod's namespace matching a spread selector moves its counts; node
        add / label change can alter the topology domains."""
        from ..api.types import UnsatisfiableConstraintAction as UCA
        from ..backend.queue import ClusterEventWithHint
        from ..framework.types import (ActionType, ClusterEvent,
                                       EventResource, QueueingHint)

        def after_pod_change(pod: Pod, old, new):
            other = new if new is not None else old
            if other is None:
                return QueueingHint.QUEUE
            if other.namespace != pod.namespace:
                return QueueingHint.SKIP
            constraints = (self._get_constraints(pod, UCA.DO_NOT_SCHEDULE.value)
                           + self._get_constraints(pod, UCA.SCHEDULE_ANYWAY.value))
            for c in constraints:
                for cand in (old, new):
                    if (cand is not None
                            and c.selector.matches(cand.metadata.labels)):
                        return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD,
                             ActionType.ADD | ActionType.DELETE
                             | ActionType.UPDATE_POD_LABEL),
                after_pod_change),
            ClusterEventWithHint(
                ClusterEvent(EventResource.NODE,
                             ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
                None),
        ]

    # -- PreFilterExtensions (preemption dry-run support) ---------------------

    def add_pod(self, state: CycleState, pod_to_schedule: Pod,
                pod_info_to_add: PodInfo, node_info: NodeInfo) -> Status:
        self._update_with_pod(state, pod_info_to_add.pod, pod_to_schedule,
                              node_info, +1)
        return Status.success()

    def remove_pod(self, state: CycleState, pod_to_schedule: Pod,
                   pod_info_to_remove: PodInfo, node_info: NodeInfo) -> Status:
        self._update_with_pod(state, pod_info_to_remove.pod, pod_to_schedule,
                              node_info, -1)
        return Status.success()

    def _update_with_pod(self, state: CycleState, updated_pod: Pod,
                         preemptor: Pod, node_info: NodeInfo, delta: int) -> None:
        s: Optional[_PreFilterState] = state.read_or_none(_PRE_FILTER_KEY)
        if s is None or updated_pod.namespace != preemptor.namespace:
            return
        node = node_info.node
        if not _node_has_all_topology_keys(node.metadata.labels, s.constraints):
            return
        for i, c in enumerate(s.constraints):
            if not c.selector.matches(updated_pod.metadata.labels):
                continue
            if not _match_node_inclusion_policies(c, preemptor, node_info):
                continue
            v = node.metadata.labels[c.topology_key]
            s.tp_value_to_match_num[i][v] = s.tp_value_to_match_num[i].get(v, 0) + delta
            s.critical_paths[i].update(v, s.tp_value_to_match_num[i][v])

    # -- Filter ---------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: Optional[_PreFilterState] = state.read_or_none(_PRE_FILTER_KEY)
        if s is None or not s.constraints:
            return Status.success()
        node = node_info.node
        for i, c in enumerate(s.constraints):
            tp_val = node.metadata.labels.get(c.topology_key)
            if tp_val is None:
                return Status.unresolvable(ERR_REASON_NODE_LABEL_NOT_MATCH,
                                           plugin=NAME)
            min_match = s.min_match_num(i, c.min_domains)
            self_match = 1 if c.selector.matches(pod.metadata.labels) else 0
            match_num = s.tp_value_to_match_num[i].get(tp_val, 0)
            if match_num + self_match - min_match > c.max_skew:
                return Status.unschedulable(ERR_REASON_CONSTRAINTS_NOT_MATCH,
                                            plugin=NAME)
        return Status.success()

    # -- PreScore / Score / Normalize ----------------------------------------

    def pre_score(self, state: CycleState, pod: Pod,
                  filtered_nodes: list[NodeInfo],
                  all_nodes: Optional[list[NodeInfo]] = None) -> Status:
        all_nodes = all_nodes if all_nodes is not None else filtered_nodes
        if not all_nodes:
            return Status.skip()
        constraints = self._get_constraints(
            pod, UnsatisfiableConstraintAction.SCHEDULE_ANYWAY.value)
        if not constraints:
            return Status.skip()
        require_all = bool(pod.spec.topology_spread_constraints) or not self.system_defaulted

        s = _PreScoreState(constraints=constraints)
        s.topology_value_to_pod_counts = [dict() for _ in constraints]
        topo_size = [0] * len(constraints)
        for ni in filtered_nodes:
            labels = ni.node.metadata.labels
            if require_all and not _node_has_all_topology_keys(labels, constraints):
                s.ignored_nodes.add(ni.name)
                continue
            for i, c in enumerate(constraints):
                if c.topology_key == LABEL_HOSTNAME:
                    continue
                value = labels.get(c.topology_key, "")
                if value not in s.topology_value_to_pod_counts[i]:
                    s.topology_value_to_pod_counts[i][value] = 0
                    topo_size[i] += 1
        for i, c in enumerate(constraints):
            sz = topo_size[i]
            if c.topology_key == LABEL_HOSTNAME:
                sz = len(filtered_nodes) - len(s.ignored_nodes)
            s.topology_normalizing_weight.append(math.log(sz + 2))

        # accumulate counts over ALL nodes whose topology value is eligible
        # (scoring.go:155-193)
        for ni in all_nodes:
            labels = ni.node.metadata.labels
            if require_all and not _node_has_all_topology_keys(labels, constraints):
                continue
            for i, c in enumerate(constraints):
                if not _match_node_inclusion_policies(c, pod, ni):
                    continue
                value = labels.get(c.topology_key, "")
                if value not in s.topology_value_to_pod_counts[i]:
                    continue
                count = _count_pods_match_selector(ni.pods, c.selector, pod.namespace)
                s.topology_value_to_pod_counts[i][value] += count
        state.write(_PRE_SCORE_KEY, s)
        return Status.success()

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo
              ) -> tuple[int, Status]:
        s: Optional[_PreScoreState] = state.read_or_none(_PRE_SCORE_KEY)
        if s is None:
            return 0, Status.success()
        if node_info.name in s.ignored_nodes:
            return 0, Status.success()
        labels = node_info.node.metadata.labels
        score = 0.0
        for i, c in enumerate(s.constraints):
            tp_val = labels.get(c.topology_key)
            if tp_val is None:
                continue
            if c.topology_key == LABEL_HOSTNAME:
                cnt = _count_pods_match_selector(node_info.pods, c.selector, pod.namespace)
            else:
                cnt = s.topology_value_to_pod_counts[i].get(tp_val, 0)
            score += cnt * s.topology_normalizing_weight[i] + (c.max_skew - 1)
        return round(score), Status.success()

    def normalize_scores(self, state: CycleState, pod: Pod,
                         scores: list[int],
                         node_names: Optional[list[str]] = None) -> Status:
        """scoring.go:229-267. `scores` is mutated in place; node_names (if
        given) is parallel to scores for the IgnoredNodes lookup."""
        s: Optional[_PreScoreState] = state.read_or_none(_PRE_SCORE_KEY)
        if s is None:
            return Status.success()
        names = node_names or [""] * len(scores)
        INVALID = -1
        min_score, max_score = _MAX_INT32, 0
        for i in range(len(scores)):
            if names[i] in s.ignored_nodes:
                scores[i] = INVALID
                continue
            min_score = min(min_score, scores[i])
            max_score = max(max_score, scores[i])
        for i in range(len(scores)):
            if scores[i] == INVALID:
                scores[i] = 0
                continue
            if max_score == 0:
                scores[i] = MAX_NODE_SCORE
                continue
            scores[i] = MAX_NODE_SCORE * (max_score + min_score - scores[i]) // max_score
        return Status.success()

    # -- signature ------------------------------------------------------------

