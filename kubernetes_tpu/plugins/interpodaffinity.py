"""InterPodAffinity plugin (host/oracle path).

Algorithm parity with the reference (pkg/scheduler/framework/plugins/
interpodaffinity/):
- PreFilter (filtering.go:273-312): builds three topologyPair→count maps —
  existing pods' required anti-affinity terms matching the incoming pod
  (over nodes that have such pods), and the incoming pod's required
  affinity / anti-affinity terms matching existing pods (over all nodes).
- Filter (filtering.go:405-432): affinity check (UnschedulableAndUnresolvable,
  with the self-affinity escape hatch filtering.go:381-397), then incoming
  anti-affinity (Unschedulable), then existing-pods anti-affinity
  (Unschedulable).
- AddPod/RemovePod PreFilterExtensions (filtering.go:322-341) for preemption.
- PreScore/Score/Normalize (scoring.go): symmetric weighted topology score —
  incoming preferred terms vs existing pods, existing pods' preferred terms
  (and hard terms × HardPodAffinityWeight) vs incoming pod; normalize to
  0..100 by min/max (scoring.go:263-293).

AffinityTerm namespace semantics (staging framework/types.go:379-392):
a term matches pods in its namespace set (defaulting to the owner pod's
namespace) or namespaces selected by namespaceSelector; the incoming pod's
namespaceSelector is resolved to a concrete namespace set at PreFilter
(plugin.go:144-157 mergeAffinityTermNamespacesIfNotEmpty).

Note: `matchLabelKeys` on affinity terms is merged into the labelSelector by
the API server at pod admission in the reference, so the scheduler never
sees it; our ingestion layer does the same.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from ..api.types import Affinity, LabelSelector, Pod, PodAffinityTerm
from ..framework.interface import (MAX_NODE_SCORE, CycleState, PreFilterResult,
                                   Status)
from ..framework.types import NodeInfo, PodInfo

NAME = "InterPodAffinity"

ERR_EXISTING_ANTI_AFFINITY = "node(s) didn't satisfy existing pods anti-affinity rules"
ERR_AFFINITY = "node(s) didn't match pod affinity rules"
ERR_ANTI_AFFINITY = "node(s) didn't match pod anti-affinity rules"

_PRE_FILTER_KEY = "PreFilter" + NAME
_PRE_SCORE_KEY = "PreScore" + NAME

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1  # apis/config/v1/defaults.go


# ---------------------------------------------------------------------------
# parsed affinity terms


@dataclass
class ParsedTerm:
    """staging framework/types.go AffinityTerm."""

    namespaces: frozenset[str]
    selector: Optional[LabelSelector]       # None ⇒ matches nothing
    topology_key: str
    namespace_selector: Optional[LabelSelector]  # None ⇒ selects nothing

    def matches(self, pod: Pod, ns_labels: Optional[dict[str, str]]) -> bool:
        in_ns = pod.namespace in self.namespaces
        if not in_ns and self.namespace_selector is not None and ns_labels is not None:
            in_ns = self.namespace_selector.matches(ns_labels)
        if not in_ns:
            return False
        if self.selector is None:
            return False
        return self.selector.matches(pod.metadata.labels)


@dataclass
class WeightedTerm:
    term: ParsedTerm
    weight: int


def _parse_term(pod: Pod, t: PodAffinityTerm) -> ParsedTerm:
    """newAffinityTerm (staging types.go:419-432): empty namespaces AND nil
    namespaceSelector ⇒ the pod's own namespace."""
    if not t.namespaces and t.namespace_selector is None:
        namespaces = frozenset([pod.namespace])
    else:
        namespaces = frozenset(t.namespaces)
    return ParsedTerm(namespaces=namespaces, selector=t.label_selector,
                      topology_key=t.topology_key,
                      namespace_selector=t.namespace_selector)


def parse_pod_affinity_terms(pod: Pod) -> tuple[list[ParsedTerm], list[ParsedTerm],
                                                list[WeightedTerm], list[WeightedTerm]]:
    """→ (required affinity, required anti-affinity, preferred affinity,
    preferred anti-affinity)."""
    aff: Optional[Affinity] = pod.spec.affinity
    req_a: list[ParsedTerm] = []
    req_aa: list[ParsedTerm] = []
    pref_a: list[WeightedTerm] = []
    pref_aa: list[WeightedTerm] = []
    if aff is None:
        return req_a, req_aa, pref_a, pref_aa
    if aff.pod_affinity:
        req_a = [_parse_term(pod, t) for t in aff.pod_affinity.required]
        pref_a = [WeightedTerm(_parse_term(pod, w.term), w.weight)
                  for w in aff.pod_affinity.preferred]
    if aff.pod_anti_affinity:
        req_aa = [_parse_term(pod, t) for t in aff.pod_anti_affinity.required]
        pref_aa = [WeightedTerm(_parse_term(pod, w.term), w.weight)
                   for w in aff.pod_anti_affinity.preferred]
    return req_a, req_aa, pref_a, pref_aa


def _pod_matches_all_affinity_terms(terms: list[ParsedTerm], pod: Pod) -> bool:
    """filtering.go:186-199 — vacuously false for no terms; nsLabels nil
    because the incoming pod's namespaceSelector was merged into namespaces."""
    if not terms:
        return False
    return all(t.matches(pod, None) for t in terms)


# ---------------------------------------------------------------------------
# state


@dataclass
class _PreFilterState:
    existing_anti_affinity_counts: dict[tuple[str, str], int] = field(default_factory=dict)
    affinity_counts: dict[tuple[str, str], int] = field(default_factory=dict)
    anti_affinity_counts: dict[tuple[str, str], int] = field(default_factory=dict)
    req_affinity_terms: list[ParsedTerm] = field(default_factory=list)
    req_anti_affinity_terms: list[ParsedTerm] = field(default_factory=list)
    pod: Optional[Pod] = None
    namespace_labels: dict[str, str] = field(default_factory=dict)

    def clone(self) -> "_PreFilterState":
        """filtering.go preFilterState.Clone() — count maps copied,
        parsed terms shared (immutable)."""
        return _PreFilterState(
            existing_anti_affinity_counts=dict(self.existing_anti_affinity_counts),
            affinity_counts=dict(self.affinity_counts),
            anti_affinity_counts=dict(self.anti_affinity_counts),
            req_affinity_terms=self.req_affinity_terms,
            req_anti_affinity_terms=self.req_anti_affinity_terms,
            pod=self.pod,
            namespace_labels=self.namespace_labels)


def _update_counts(counts: dict[tuple[str, str], int], node_labels: dict[str, str],
                   tk: str, value: int) -> None:
    tv = node_labels.get(tk)
    if tv is None:
        return
    pair = (tk, tv)
    counts[pair] = counts.get(pair, 0) + value
    if counts[pair] == 0:
        del counts[pair]


def _update_with_affinity_terms(counts, terms: list[ParsedTerm], pod: Pod,
                                node_labels, value: int) -> None:
    if _pod_matches_all_affinity_terms(terms, pod):
        for t in terms:
            _update_counts(counts, node_labels, t.topology_key, value)


def _update_with_anti_affinity_terms(counts, terms: list[ParsedTerm], pod: Pod,
                                     ns_labels, node_labels, value: int) -> None:
    for t in terms:
        if t.matches(pod, ns_labels):
            _update_counts(counts, node_labels, t.topology_key, value)


@dataclass
class _PreScoreState:
    topology_score: dict[str, dict[str, int]] = field(default_factory=dict)
    namespace_labels: dict[str, str] = field(default_factory=dict)
    pref_affinity_terms: list[WeightedTerm] = field(default_factory=list)
    pref_anti_affinity_terms: list[WeightedTerm] = field(default_factory=list)


# ---------------------------------------------------------------------------
# plugin


@dataclass
class InterPodAffinityArgs:
    hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
    ignore_preferred_terms_of_existing_pods: bool = False


class NamespaceLister:
    """namespace name → labels; resolves namespaceSelectors. The in-memory
    analog of the reference's nsLister (plugin.go:144-169)."""

    def __init__(self, namespaces: Optional[dict[str, dict[str, str]]] = None):
        self.namespaces = namespaces if namespaces is not None else {}

    def labels_of(self, ns: str) -> dict[str, str]:
        return self.namespaces.get(ns, {})

    def select(self, selector: LabelSelector) -> frozenset[str]:
        return frozenset(n for n, lbls in self.namespaces.items()
                         if selector.matches(lbls))


class InterPodAffinity:
    """PF(+Extensions), F, PS, S, N, EE, Sg — reference interpodaffinity/."""

    def __init__(self, args: Optional[InterPodAffinityArgs] = None,
                 ns_lister: Optional[NamespaceLister] = None):
        self.args = args or InterPodAffinityArgs()
        self.ns_lister = ns_lister or NamespaceLister()

    def name(self) -> str:
        return NAME

    def _merge_term_namespaces(self, term: ParsedTerm) -> ParsedTerm:
        """mergeAffinityTermNamespacesIfNotEmpty (plugin.go:144-157): resolve
        the namespaceSelector to concrete namespaces; empty selector selects
        every namespace."""
        if term.namespace_selector is None:
            return term
        selected = self.ns_lister.select(term.namespace_selector)
        return ParsedTerm(namespaces=term.namespaces | selected,
                          selector=term.selector,
                          topology_key=term.topology_key,
                          namespace_selector=None)

    # -- PreFilter ------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
                   ) -> tuple[Optional[PreFilterResult], Status]:
        req_a, req_aa, _, _ = parse_pod_affinity_terms(pod)
        req_a = [self._merge_term_namespaces(t) for t in req_a]
        req_aa = [self._merge_term_namespaces(t) for t in req_aa]

        s = _PreFilterState(req_affinity_terms=req_a,
                            req_anti_affinity_terms=req_aa, pod=pod,
                            namespace_labels=self.ns_lister.labels_of(pod.namespace))

        # existing pods' required anti-affinity vs the incoming pod
        # (filtering.go:204-228; only nodes that have such pods)
        for ni in nodes:
            if not ni.pods_with_required_anti_affinity:
                continue
            labels = ni.node.metadata.labels
            for existing in ni.pods_with_required_anti_affinity:
                terms = _required_anti_affinity_terms_of(existing)
                _update_with_anti_affinity_terms(
                    s.existing_anti_affinity_counts, terms, pod,
                    s.namespace_labels, labels, 1)

        # incoming pod's required terms vs all existing pods
        # (filtering.go:234-271)
        if req_a or req_aa:
            for ni in nodes:
                labels = ni.node.metadata.labels
                for existing in ni.pods:
                    _update_with_affinity_terms(
                        s.affinity_counts, req_a, existing.pod, labels, 1)
                    _update_with_anti_affinity_terms(
                        s.anti_affinity_counts, req_aa, existing.pod, None,
                        labels, 1)

        if not s.existing_anti_affinity_counts and not req_a and not req_aa:
            return None, Status.skip()
        state.write(_PRE_FILTER_KEY, s)
        return None, Status.success()

    def events_to_register(self):
        """interpodaffinity EventsToRegister (plugin.go): an assigned pod
        helps when it matches one of my terms (affinity satisfied, or an
        anti-affinity blocker removed on delete), or when I match one of
        ITS anti-affinity terms (the symmetric veto disappearing); node
        add / label change can create new matching topologies."""
        from ..backend.queue import ClusterEventWithHint
        from ..framework.types import (ActionType, ClusterEvent,
                                       EventResource, QueueingHint)

        def after_pod_change(pod: Pod, old, new):
            # BOTH sides of an update matter: a label removal can clear an
            # anti-affinity blocker (the old pod matched, the new doesn't)
            candidates = [p for p in (old, new) if p is not None]
            if not candidates:
                return QueueingHint.QUEUE
            req_a, req_aa, pref_a, pref_aa = parse_pod_affinity_terms(pod)
            my_terms = req_a + req_aa + [w.term for w in pref_a + pref_aa]
            my_ns_labels = self.ns_lister.labels_of(pod.namespace)
            for other in candidates:
                ns_labels = self.ns_lister.labels_of(other.namespace)
                for t in my_terms:
                    if t.matches(other, ns_labels):
                        return QueueingHint.QUEUE
                _, o_req_aa, _, _ = parse_pod_affinity_terms(other)
                for t in o_req_aa:
                    if t.matches(pod, my_ns_labels):
                        return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD,
                             ActionType.ADD | ActionType.DELETE
                             | ActionType.UPDATE_POD_LABEL),
                after_pod_change),
            ClusterEventWithHint(
                ClusterEvent(EventResource.NODE,
                             ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
                None),
        ]

    # -- PreFilterExtensions --------------------------------------------------

    def add_pod(self, state: CycleState, pod_to_schedule: Pod,
                pod_info_to_add: PodInfo, node_info: NodeInfo) -> Status:
        self._update_with_pod(state, pod_info_to_add, node_info, 1)
        return Status.success()

    def remove_pod(self, state: CycleState, pod_to_schedule: Pod,
                   pod_info_to_remove: PodInfo, node_info: NodeInfo) -> Status:
        self._update_with_pod(state, pod_info_to_remove, node_info, -1)
        return Status.success()

    def _update_with_pod(self, state: CycleState, pi: PodInfo,
                         node_info: NodeInfo, multiplier: int) -> None:
        s: Optional[_PreFilterState] = state.read_or_none(_PRE_FILTER_KEY)
        if s is None:
            return
        labels = node_info.node.metadata.labels
        _update_with_anti_affinity_terms(
            s.existing_anti_affinity_counts,
            _required_anti_affinity_terms_of(pi), s.pod,
            s.namespace_labels, labels, multiplier)
        _update_with_affinity_terms(
            s.affinity_counts, s.req_affinity_terms, pi.pod, labels, multiplier)
        _update_with_anti_affinity_terms(
            s.anti_affinity_counts, s.req_anti_affinity_terms, pi.pod, None,
            labels, multiplier)

    # -- Filter ---------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: Optional[_PreFilterState] = state.read_or_none(_PRE_FILTER_KEY)
        if s is None:
            return Status.success()
        labels = node_info.node.metadata.labels

        if not self._satisfy_pod_affinity(s, labels):
            return Status.unresolvable(ERR_AFFINITY, plugin=NAME)
        if not self._satisfy_pod_anti_affinity(s, labels):
            return Status.unschedulable(ERR_ANTI_AFFINITY, plugin=NAME)
        if not self._satisfy_existing_pods_anti_affinity(s, labels):
            return Status.unschedulable(ERR_EXISTING_ANTI_AFFINITY, plugin=NAME)
        return Status.success()

    @staticmethod
    def _satisfy_existing_pods_anti_affinity(s: _PreFilterState,
                                             node_labels: dict[str, str]) -> bool:
        if s.existing_anti_affinity_counts:
            for tk, tv in node_labels.items():
                if s.existing_anti_affinity_counts.get((tk, tv), 0) > 0:
                    return False
        return True

    @staticmethod
    def _satisfy_pod_anti_affinity(s: _PreFilterState,
                                   node_labels: dict[str, str]) -> bool:
        if s.anti_affinity_counts:
            for term in s.req_anti_affinity_terms:
                tv = node_labels.get(term.topology_key)
                if tv is not None and s.anti_affinity_counts.get((term.topology_key, tv), 0) > 0:
                    return False
        return True

    @staticmethod
    def _satisfy_pod_affinity(s: _PreFilterState, node_labels: dict[str, str]) -> bool:
        pods_exist = True
        for term in s.req_affinity_terms:
            tv = node_labels.get(term.topology_key)
            if tv is None:
                return False  # all topology labels must exist on the node
            if s.affinity_counts.get((term.topology_key, tv), 0) <= 0:
                pods_exist = False
        if not pods_exist:
            # first-pod-in-series escape hatch (filtering.go:381-397)
            if not s.affinity_counts and _pod_matches_all_affinity_terms(
                    s.req_affinity_terms, s.pod):
                return True
            return False
        return True

    # -- PreScore / Score / Normalize -----------------------------------------

    def pre_score(self, state: CycleState, pod: Pod, nodes: list[NodeInfo],
                  all_nodes: Optional[list[NodeInfo]] = None) -> Status:
        all_nodes = all_nodes if all_nodes is not None else nodes
        _, _, pref_a, pref_aa = parse_pod_affinity_terms(pod)
        has_constraints = bool(pref_a or pref_aa)
        if self.args.ignore_preferred_terms_of_existing_pods and not has_constraints:
            return Status.skip()

        pref_a = [WeightedTerm(self._merge_term_namespaces(w.term), w.weight)
                  for w in pref_a]
        pref_aa = [WeightedTerm(self._merge_term_namespaces(w.term), w.weight)
                   for w in pref_aa]
        s = _PreScoreState(pref_affinity_terms=pref_a,
                           pref_anti_affinity_terms=pref_aa,
                           namespace_labels=self.ns_lister.labels_of(pod.namespace))

        # Unless the incoming pod has preferred terms, only nodes hosting
        # pods with affinity need processing (scoring.go:148-163).
        for ni in all_nodes:
            node_labels = ni.node.metadata.labels
            if not node_labels:
                continue
            pods_to_process = ni.pods if has_constraints else ni.pods_with_affinity
            for existing in pods_to_process:
                self._process_existing_pod(s, existing, node_labels, pod)
        if not s.topology_score:
            return Status.skip()
        state.write(_PRE_SCORE_KEY, s)
        return Status.success()

    def _process_existing_pod(self, s: _PreScoreState, existing: PodInfo,
                              node_labels: dict[str, str], incoming: Pod) -> None:
        """scoring.go:81-124 processExistingPod."""
        ts = s.topology_score

        def process(term: ParsedTerm, weight: int, target: Pod,
                    ns_labels, multiplier: int) -> None:
            if term.matches(target, ns_labels):
                tv = node_labels.get(term.topology_key)
                if tv is not None:
                    ts.setdefault(term.topology_key, {})
                    ts[term.topology_key][tv] = (
                        ts[term.topology_key].get(tv, 0) + weight * multiplier)

        for w in s.pref_affinity_terms:
            process(w.term, w.weight, existing.pod, None, 1)
        for w in s.pref_anti_affinity_terms:
            process(w.term, w.weight, existing.pod, None, -1)

        ex_req_a, _, ex_pref_a, ex_pref_aa = parse_pod_affinity_terms(existing.pod)
        if self.args.hard_pod_affinity_weight > 0:
            for t in ex_req_a:
                process(t, self.args.hard_pod_affinity_weight, incoming,
                        s.namespace_labels, 1)
        for w in ex_pref_a:
            process(w.term, w.weight, incoming, s.namespace_labels, 1)
        for w in ex_pref_aa:
            process(w.term, w.weight, incoming, s.namespace_labels, -1)

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo
              ) -> tuple[int, Status]:
        s: Optional[_PreScoreState] = state.read_or_none(_PRE_SCORE_KEY)
        if s is None:
            return 0, Status.success()
        labels = node_info.node.metadata.labels
        score = 0
        for tk, tv_scores in s.topology_score.items():
            tv = labels.get(tk)
            if tv is not None:
                score += tv_scores.get(tv, 0)
        return score, Status.success()

    def normalize_scores(self, state: CycleState, pod: Pod, scores: list[int],
                         node_names=None) -> Status:
        s: Optional[_PreScoreState] = state.read_or_none(_PRE_SCORE_KEY)
        if s is None or not s.topology_score:
            return Status.success()
        if not scores:
            return Status.success()
        min_c, max_c = min(scores), max(scores)
        diff = max_c - min_c
        for i in range(len(scores)):
            f = 0.0
            if diff > 0:
                f = MAX_NODE_SCORE * (scores[i] - min_c) / diff
            scores[i] = int(f)
        return Status.success()


def _required_anti_affinity_terms_of(pi: PodInfo) -> list[ParsedTerm]:
    """Parsed required anti-affinity terms of an existing pod, cached on the
    PodInfo (the reference pre-parses terms at PodInfo creation)."""
    cached = getattr(pi, "_parsed_req_anti_affinity", None)
    if cached is None:
        _, cached, _, _ = parse_pod_affinity_terms(pi.pod)
        pi._parsed_req_anti_affinity = cached
    return cached
