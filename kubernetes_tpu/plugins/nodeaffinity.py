"""NodeAffinity plugin — reference plugins/nodeaffinity/node_affinity.go and
the matcher in component-helpers/scheduling/corev1/nodeaffinity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api.types import (LabelSelectorRequirement, NodeAffinity as NodeAffinitySpec,
                         NodeSelector, NodeSelectorTerm, Pod,
                         PreferredSchedulingTerm, SelectorOperator,
                         _requirement_matches)
from ..framework.interface import CycleState, PreFilterResult, Status
from ..framework.types import NodeInfo
from .helper import default_normalize

NODE_AFFINITY = "NodeAffinity"
_PRE_SCORE_KEY = "PreScore" + NODE_AFFINITY

ERR_REASON = "node(s) didn't match Pod's node affinity/selector"
OBJECT_NAME_FIELD = "metadata.name"


def _term_matches(term: NodeSelectorTerm, node_labels: dict[str, str], node_name: str) -> bool:
    """A term with no expressions and no fields selects nothing; expressions
    and fields within a term are ANDed."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not _requirement_matches(req, node_labels):
            return False
    fields = {OBJECT_NAME_FIELD: node_name}
    for req in term.match_fields:
        if not _requirement_matches(req, fields):
            return False
    return True


def node_selector_matches(selector: Optional[NodeSelector], node_labels: dict[str, str],
                          node_name: str) -> bool:
    """Terms are ORed; a present selector with zero terms matches nothing."""
    if selector is None:
        return True
    return any(_term_matches(t, node_labels, node_name) for t in selector.terms)


def required_node_affinity_matches(pod: Pod, node_labels: dict[str, str], node_name: str) -> bool:
    """GetRequiredNodeAffinity semantics: spec.nodeSelector map AND
    affinity.nodeAffinity.required."""
    for k, v in pod.spec.node_selector.items():
        if node_labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required is not None:
        if not node_selector_matches(aff.node_affinity.required, node_labels, node_name):
            return False
    return True


@dataclass
class NodeAffinityArgs:
    """Reference: config.NodeAffinityArgs — per-profile added affinity."""

    added_affinity: Optional[NodeAffinitySpec] = None


class NodeAffinity:
    """PF, F, PS, S, EE, Sg."""

    def __init__(self, args: Optional[NodeAffinityArgs] = None):
        self.args = args or NodeAffinityArgs()

    def name(self) -> str:
        return NODE_AFFINITY

    # -- PreFilter: metadata.name field-selector shortcut --------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> tuple[Optional[PreFilterResult], Status]:
        aff = pod.spec.affinity
        required = (aff.node_affinity.required
                    if aff and aff.node_affinity and aff.node_affinity.required is not None
                    else None)
        if required is None or not required.terms:
            return None, Status.success()
        node_names: set[str] = set()
        for term in required.terms:
            if not term.match_fields:
                return None, Status.success()  # term without field constraints → all nodes
            term_names: Optional[set[str]] = None
            for req in term.match_fields:
                if req.key == OBJECT_NAME_FIELD and req.operator == SelectorOperator.IN.value:
                    vals = set(req.values)
                    term_names = vals if term_names is None else term_names & vals
            if term_names is None:
                return None, Status.success()
            node_names |= term_names
        if not node_names:
            return None, Status.unresolvable(ERR_REASON, plugin=NODE_AFFINITY)
        return PreFilterResult(node_names), Status.success()

    # -- Filter --------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        labels = node_info.node.metadata.labels
        name = node_info.name
        if self.args.added_affinity and self.args.added_affinity.required is not None:
            if not node_selector_matches(self.args.added_affinity.required, labels, name):
                return Status.unresolvable(ERR_REASON, plugin=NODE_AFFINITY)
        if not required_node_affinity_matches(pod, labels, name):
            return Status.unresolvable(ERR_REASON, plugin=NODE_AFFINITY)
        return Status.success()

    # -- Score ---------------------------------------------------------------

    def _preferred_terms(self, pod: Pod) -> tuple[PreferredSchedulingTerm, ...]:
        aff = pod.spec.affinity
        terms = tuple(aff.node_affinity.preferred) if aff and aff.node_affinity else ()
        if self.args.added_affinity:
            terms = terms + tuple(self.args.added_affinity.preferred)
        return terms

    def pre_score(self, state: CycleState, pod: Pod, nodes, all_nodes=None) -> Status:
        terms = self._preferred_terms(pod)
        state.write(_PRE_SCORE_KEY, terms)
        if not terms:
            return Status.skip()
        return Status.success()

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> tuple[int, Status]:
        terms = state.read_or_none(_PRE_SCORE_KEY)
        if terms is None:
            terms = self._preferred_terms(pod)
        labels = node_info.node.metadata.labels
        score = sum(t.weight for t in terms
                    if t.weight and _term_matches(t.preference, labels, node_info.name))
        return score, Status.success()

    def normalize_scores(self, state: CycleState, pod: Pod, scores: list[int],
                         node_names=None) -> Status:
        scores[:] = default_normalize(scores)
        return Status.success()

    def events_to_register(self):
        """node_affinity.go isSchedulableAfterNodeChange: queue only when
        the (new) node satisfies the pod's nodeSelector + required
        affinity."""
        from ..backend.queue import ClusterEventWithHint
        from ..framework.types import (ActionType, ClusterEvent,
                                       EventResource, QueueingHint)

        def after_node_change(pod: Pod, old, new):
            if new is None:
                return QueueingHint.QUEUE
            # the helper covers nodeSelector AND required affinity terms
            if required_node_affinity_matches(pod, new.metadata.labels,
                                              new.metadata.name):
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [ClusterEventWithHint(
            ClusterEvent(EventResource.NODE,
                         ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
            after_node_change)]

