"""DynamicResources (DRA): structured-parameters device allocation.

Mirrors pkg/scheduler/framework/plugins/dynamicresources/ (registered at
registry.go:48; 2,687 LoC in the reference), scoped to the structured
model this framework's API carries (api/types.py ResourceSlice /
ResourceClaim / DeviceRequest):

- PreFilter (dynamicresources.go PreFilter): resolve the pod's claims; a
  claim that is already allocated pins the pod to its allocation's node
  (PreFilterResult node shortcut). Pods without claims → Skip.
- Filter (:Filter): a node passes if every unallocated claim can be
  satisfied from the node's ResourceSlices — devices matching the
  request's attribute selectors, minus devices occupied by other claims'
  allocations and by this scheduler's in-flight assumed allocations (the
  SharedDRAManager assume-cache role, scheduler.go:327-350).
- Reserve/Unreserve (:Reserve): allocate devices into the assume cache /
  roll back.
- PreBind (:PreBind): write the allocation + reservedFor to the API
  server, making it visible to other schedulers and restarts.
- EventsToRegister: ResourceClaim and ResourceSlice changes can make a
  rejected pod schedulable.

Claims are API-coupled (allocation state machine), so claim-bearing pods
take the host path — the builder marks them host_fallback exactly like
volume-bearing pods (state/batch.py), matching SURVEY §2.4's "keep Go
path" note while the tensor form stays an optimization opportunity.
"""

from __future__ import annotations

from typing import Optional

from ..api.types import (Device, DeviceAllocation, Pod, ResourceClaim)
from ..framework.interface import CycleState, PreFilterResult, Status
from ..framework.types import ActionType, ClusterEvent, EventResource, NodeInfo

NAME = "DynamicResources"
_STATE_KEY = "PreFilterDynamicResources"


class _StateData:
    def __init__(self, claims: list[ResourceClaim]):
        self.claims = claims
        # (claim uid, node) → DeviceAllocation candidate from Filter
        self.informational: dict[tuple, DeviceAllocation] = {}
        # occupancy + device index, computed ONCE in PreFilter (occupancy
        # cannot change within a pod's filter pass; recomputing per node
        # would be O(nodes × claims + nodes × slices))
        self.occupied: set[tuple[str, str, str]] = set()
        self.node_devices: dict[str, list] = {}

    def clone(self) -> "_StateData":
        c = _StateData(list(self.claims))
        c.informational = dict(self.informational)
        c.occupied = set(self.occupied)
        c.node_devices = self.node_devices
        return c


class DynamicResources:
    """PF, F, R, PB, EE — reference dynamicresources.go."""

    def __init__(self, client=None):
        self.client = client
        # assume cache: claim uid → DeviceAllocation (assumed, pre-PreBind);
        # survives across cycles so concurrent pods see each other's holds
        self.assumed: dict[str, DeviceAllocation] = {}

    def name(self) -> str:
        return NAME

    # -- EnqueueExtensions ----------------------------------------------------

    def events_to_register(self):
        from ..backend.queue import ClusterEventWithHint
        return [
            ClusterEventWithHint(ClusterEvent(
                EventResource.RESOURCE_CLAIM,
                ActionType.ADD | ActionType.UPDATE)),
            ClusterEventWithHint(ClusterEvent(
                EventResource.RESOURCE_SLICE,
                ActionType.ADD | ActionType.UPDATE)),
        ]

    # -- helpers --------------------------------------------------------------

    def _pod_claims(self, pod: Pod) -> tuple[list[ResourceClaim], Optional[str]]:
        claims = []
        for name in pod.spec.resource_claims:
            c = (self.client.get_resource_claim(pod.namespace, name)
                 if self.client is not None else None)
            if c is None:
                return [], f"resourceclaim {pod.namespace}/{name} not found"
            claims.append(c)
        return claims, None

    def _occupied_devices(self) -> set[tuple[str, str, str]]:
        """(node, driver, device) ids held by allocated claims (API truth)
        plus in-flight assumed allocations."""
        occupied: set[tuple[str, str, str]] = set()
        if self.client is not None:
            for c in self.client.list_resource_claims():
                if c.allocation is not None:
                    occupied |= c.allocation.device_ids()
        for alloc in self.assumed.values():
            occupied |= alloc.device_ids()
        return occupied

    def _device_index(self) -> dict[str, list]:
        """node → [(driver, Device)] from the published slices."""
        index: dict[str, list] = {}
        if self.client is not None:
            for s in self.client.list_resource_slices():
                index.setdefault(s.node_name, []).extend(
                    (s.driver, d) for d in s.devices)
        return index

    @staticmethod
    def _allocate_on_node(claim: ResourceClaim, node_name: str,
                          node_devices: list, occupied: set
                          ) -> Optional[DeviceAllocation]:
        """Try to satisfy every request of `claim` from `node_devices`,
        first-fit in slice/device order (the structured-parameters
        allocator's deterministic ordering). `occupied` is not mutated."""
        results: dict[str, tuple] = {}
        taken: set[tuple[str, str, str]] = set()
        for req in claim.requests:
            picked = []
            for driver, dev in node_devices:
                if len(picked) >= req.count:
                    break
                if req.driver and driver != req.driver:
                    continue
                did = (node_name, driver, dev.name)
                if did in occupied or did in taken:
                    continue
                if not req.matches(dev):
                    continue
                picked.append((driver, dev.name))
                taken.add(did)
            if len(picked) < req.count:
                return None
            results[req.name] = tuple(picked)
        return DeviceAllocation(node_name=node_name, results=results)

    # -- PreFilter ------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes
                   ) -> tuple[Optional[PreFilterResult], Status]:
        if not pod.spec.resource_claims:
            return None, Status.skip()
        claims, err = self._pod_claims(pod)
        if err:
            return None, Status.unschedulable(err, plugin=NAME)
        data = _StateData(claims)
        data.occupied = self._occupied_devices()
        data.node_devices = self._device_index()
        state.write(_STATE_KEY, data)
        # an allocated claim pins the pod to its node (PreFilter shortcut)
        pinned = {c.allocation.node_name for c in claims
                  if c.allocation is not None}
        if len(pinned) > 1:
            return None, Status.unschedulable(
                "claims are allocated on different nodes", plugin=NAME)
        if pinned:
            return PreFilterResult(node_names=pinned), Status.success()
        return None, Status.success()

    # -- Filter ---------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        data = state.read_or_none(_STATE_KEY)
        if data is None:
            return Status.success()
        # node-local occupancy: a pod's OWN claims must not double-book a
        # device — each claim's candidate pick occupies for the next
        occupied = set(data.occupied)
        node_devices = data.node_devices.get(node_info.name, ())
        for claim in data.claims:
            if claim.allocation is not None:
                if claim.allocation.node_name != node_info.name:
                    return Status.unschedulable(
                        f"claim {claim.name} allocated on "
                        f"{claim.allocation.node_name}", plugin=NAME)
                continue
            alloc = self._allocate_on_node(claim, node_info.name,
                                           node_devices, occupied)
            if alloc is None:
                return Status.unschedulable(
                    f"cannot allocate claim {claim.name} on "
                    f"{node_info.name}", plugin=NAME)
            occupied |= alloc.device_ids()
            # remember the candidate allocation for Reserve; keyed per node
            data.informational[(claim.uid, node_info.name)] = alloc
        return Status.success()

    # -- Reserve / Unreserve --------------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        data = state.read_or_none(_STATE_KEY)
        if data is None:
            return Status.success()
        # AUTHORITATIVE occupancy re-check: other pods may have assumed
        # devices since PreFilter snapshotted it, so a Filter-time
        # candidate is only trusted if its devices are still free
        occupied = self._occupied_devices()
        reserved_here: list[str] = []
        for claim in data.claims:
            if claim.allocation is not None:
                continue
            alloc = data.informational.get((claim.uid, node_name))
            if alloc is not None and (alloc.device_ids() & occupied):
                alloc = None  # stale candidate: devices got taken
            if alloc is None:
                alloc = self._allocate_on_node(
                    claim, node_name,
                    data.node_devices.get(node_name, ()), occupied)
            if alloc is None:
                for uid in reserved_here:   # roll back partial reserve
                    self.assumed.pop(uid, None)
                return Status.unschedulable(
                    f"claim {claim.name} no longer allocatable on "
                    f"{node_name}", plugin=NAME)
            self.assumed[claim.uid] = alloc
            reserved_here.append(claim.uid)
            occupied |= alloc.device_ids()
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        data = state.read_or_none(_STATE_KEY)
        if data is None:
            return
        for claim in data.claims:
            self.assumed.pop(claim.uid, None)

    # -- PreBind --------------------------------------------------------------

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        data = state.read_or_none(_STATE_KEY)
        if data is None:
            return Status.success()
        for claim in data.claims:
            alloc = self.assumed.pop(claim.uid, None)
            if alloc is None and claim.allocation is None:
                return Status.error(
                    f"claim {claim.name} lost its assumed allocation",
                    plugin=NAME)
            if alloc is not None:
                claim.allocation = alloc
            if pod.uid not in claim.reserved_for:
                claim.reserved_for.append(pod.uid)
            if self.client is not None:
                self.client.update_claim_status(claim)
        return Status.success()
