"""Shadow-oracle audit: continuous production-time bind-parity verification.

The paper's headline contract — device bind decisions identical to the
default host plugins — is verified offline by the parity fuzz suites;
nothing watched it in live operation. This module is the always-on half
(`ShadowOracleAudit` gate): a sampler captures a deterministic replay
record per sampled drain, appends it to a hash-chained drain ledger, and
a background worker re-executes the record through the HOST ORACLE
(framework.runtime.schedule_pod — the real plugin implementations, not
the kernels) and diffs:

  - per-pod assignments           → oracle_divergence_total{kind=assignment}
  - scheduled/unschedulable       → oracle_divergence_total{kind=verdict}
  - FailedScheduling reason
    histograms (reference format) → oracle_divergence_total{kind=reason}

Capture runs at a QUIESCED pipeline point (the scheduler drains pending
commits and refreshes the snapshot before cloning), so the cloned
NodeInfos are exactly the state the device carry encodes — a divergence
is a real decision difference, never capture skew. The replay itself is
bounded (`shadow_audit_max_replay_pods` prefix — the serial greedy's
first K decisions depend only on prior state) and runs off the hot path
on a daemon worker; reason diffs only run on fully-replayed drains and
only when no external cluster event landed between dispatch and commit
(the device diagnoses against the commit-time snapshot).

The ledger is a hash chain: each record's sha256 covers the previous
hash plus the input fingerprints (pod-table rows, node statics gen, plan
key, gate/strategy fingerprint, carry hash), so any retroactive edit of
an audited drain breaks `verify()`. With `shadow_audit_dir` set, every
audited drain also writes a standalone pickle that
`tools/audit_replay.py` re-runs without a live scheduler.

Full diffs attach to the drain's FlightRecorder entry; /debug/audit
serves recent audits + divergence detail.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue as _queue
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Optional

from ..framework.interface import CycleState
from ..framework.types import Diagnosis, FitError, PodInfo

GENESIS = "0" * 64

# submit-queue depth beyond which new samples are dropped (outcome
# "skipped") instead of growing without bound — the audit must never
# become a memory leak when the worker falls behind a 100%-sampled soak
MAX_QUEUE = 64


def _sha(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p if isinstance(p, bytes) else str(p).encode())
    return h.hexdigest()


def gates_fingerprint(gates) -> str:
    """Stable fingerprint of the feature-gate configuration."""
    known = gates.known()
    return _sha(json.dumps(sorted((n, gates.enabled(n)) for n in known)))


@dataclass
class AuditRecord:
    """One sampled drain: replay inputs + device decisions + verdict."""

    drain_id: int
    profile_name: str
    strategy: str
    weights: dict                  # plugin weights (CLI framework rebuild)
    pods: list                     # [(uid, Pod, PodInfo)] in queue order
    nodes: list                    # PRIVATE NodeInfo clones at capture
    # monotonic ledger sequence number, assigned at append: the tail
    # cursor for streaming subscribers (ha/standby.py). 0 = unappended.
    seq: int = 0
    framework: object = None       # live replay framework (not pickled)
    fingerprints: dict = field(default_factory=dict)
    ext_gen: int = 0               # scheduler external-mutation counter
    captured_at: float = 0.0
    prev_hash: str = GENESIS
    hash: str = ""
    # filled at commit time (scheduling thread)
    device: dict = field(default_factory=dict)      # uid → node | None
    reasons_dev: dict = field(default_factory=dict)  # uid → message
    reasons_ok: bool = True        # False: skip the reason diff
    # filled by the worker
    outcome: str = "pending"       # clean|divergent|skipped|error|pending
    skip_reason: str = ""
    oracle: dict = field(default_factory=dict)
    reasons_oracle: dict = field(default_factory=dict)
    diffs: dict = field(default_factory=dict)
    truncated: bool = False
    replay_s: float = 0.0
    # device-side replay context for exact /debug/explain (never pickled)
    explain_ctx: object = None
    # the drain's FlightRecord (diff attachment target; never pickled)
    _flight: object = None

    def chain_bytes(self) -> bytes:
        return json.dumps({"drain": self.drain_id,
                           "profile": self.profile_name,
                           "fingerprints": self.fingerprints},
                          sort_keys=True).encode()

    def divergence_count(self) -> int:
        return sum(len(v) for v in self.diffs.values())

    def to_dict(self, details: bool = False) -> dict:
        d = {"drainId": self.drain_id, "profile": self.profile_name,
             "pods": len(self.pods), "outcome": self.outcome,
             "truncated": self.truncated,
             "divergences": self.divergence_count(),
             "replaySeconds": round(self.replay_s, 4),
             "capturedAt": round(self.captured_at, 3),
             "fingerprints": dict(self.fingerprints),
             "prevHash": self.prev_hash, "hash": self.hash}
        if self.skip_reason:
            d["skipReason"] = self.skip_reason
        if details or self.diffs:
            d["diffs"] = self.diffs
        return d

    def to_payload(self) -> dict:
        """Standalone-replayable pickle payload (tools/audit_replay.py):
        everything but the live framework and device arrays."""
        return {
            "drainId": self.drain_id, "profile": self.profile_name,
            "strategy": self.strategy, "weights": dict(self.weights),
            "pods": [(uid, pod, pi) for uid, pod, pi in self.pods],
            "nodes": self.nodes,
            "fingerprints": dict(self.fingerprints),
            "prevHash": self.prev_hash, "hash": self.hash,
            "device": dict(self.device),
            "reasonsDevice": dict(self.reasons_dev),
            "reasonsOk": self.reasons_ok,
        }


@dataclass
class ExplainCtx:
    """Device-side inputs for exact after-the-fact explain: re-running
    the drain PREFIX through run_batch from the captured carry
    reconstructs the per-step state any pod's decision was made against
    (parity between run_batch and the dispatched program is the fuzzed
    system invariant — and exactly what the audit itself watches)."""

    cfg: object
    na: object
    carry0: object        # device copy of the pre-drain carry
    table: object
    gd: object
    fam: object
    sig: object           # numpy [n]
    tidx: object          # numpy [n]
    uids: tuple = ()
    names: tuple = ()     # node_names at capture (row → name decode)
    assignments: object = None   # numpy [n], filled at commit


# ---------------------------------------------------------------------------
# host-oracle replay (shared by the worker and tools/audit_replay.py)


def replay_decisions(framework, nodes: list, pods: list,
                     device: Optional[dict] = None, cap: int = 0):
    """Serial host-oracle replay over PRIVATE NodeInfo clones (mutated in
    place). Returns (oracle {uid: verdict dict | None}, reasons
    {uid: message}, truncated).

    The verdict dict carries `host` (the oracle's own tie-break pick),
    `argmax` (EVERY node tied at max score — the reference breaks ties
    with a seeded RNG, so any member is a correct decision:
    runtime.ScheduleResult.argmax_set is the system's documented parity
    contract) and `scores`. When `device` decisions are given, the
    replay FOLLOWS the device's placements for pods the device bound, so
    each step is judged against the actual committed state and one wrong
    decision counts once instead of cascading.

    Reasons are computed against the POST-REPLAY state — mirroring the
    device path, whose mask diagnosis runs against the post-commit
    snapshot (scheduler._device_fit_error)."""
    from ..framework.runtime import schedule_pod
    limit = len(pods) if cap <= 0 else min(cap, len(pods))
    truncated = limit < len(pods)
    by_name = {ni.name: ni for ni in nodes}
    oracle: dict = {}
    failed: list = []
    for uid, pod, pi in pods[:limit]:
        state = CycleState()
        try:
            result = schedule_pod(framework, state, pod, nodes)
            oracle[uid] = {"host": result.suggested_host,
                           "argmax": set(result.argmax_set),
                           "scores": dict(result.scores)}
        except FitError:
            oracle[uid] = None
            failed.append((uid, pod))
        # apply the COMMITTED placement (fall back to the oracle's own
        # pick when no device decision is recorded for this pod)
        placed = None
        if device is not None:
            placed = device.get(uid)
        elif oracle[uid] is not None:
            placed = oracle[uid]["host"]
        if placed is not None and placed in by_name:
            assumed = pod.with_node_name(placed)
            by_name[placed].add_pod(
                PodInfo(pod=assumed, requests=pi.requests,
                        cpu_nonzero=pi.cpu_nonzero,
                        mem_nonzero=pi.mem_nonzero))
    reasons: dict = {}
    for uid, pod in failed:
        state = CycleState()
        diagnosis = Diagnosis()
        pre_result, status = framework.run_pre_filter_plugins(state, pod,
                                                              nodes)
        if not status.is_success():
            diagnosis.pre_filter_msg = "; ".join(status.reasons)
            if status.plugin:
                diagnosis.unschedulable_plugins.add(status.plugin)
        else:
            framework.find_nodes_that_pass_filters(state, pod, nodes,
                                                   pre_result, diagnosis)
        reasons[uid] = str(FitError(pod, len(nodes), diagnosis))
    return oracle, reasons, truncated


def diff_decisions(rec_device: dict, rec_reasons: dict, oracle: dict,
                   oracle_reasons: dict, reasons_ok: bool = True) -> dict:
    """Assignment/verdict/reason diffs over the replayed pod set. An
    assignment diverges when the device's choice lands OUTSIDE the
    oracle's argmax set — any tied node is a correct decision (the
    reference's randomized tie-break), so tie-order differences (e.g.
    node churn reordering the zone round-robin list against the device
    row order) are not divergences."""
    diffs: dict = {"assignment": [], "verdict": [], "reason": []}
    for uid, verdict in oracle.items():
        d_node = rec_device.get(uid)
        if (d_node is None) != (verdict is None):
            diffs["verdict"].append(
                {"pod": uid, "device": d_node,
                 "oracle": verdict["host"] if verdict else None})
        elif verdict is not None and d_node not in verdict["argmax"]:
            diffs["assignment"].append(
                {"pod": uid, "device": d_node, "oracle": verdict["host"],
                 "deviceScore": verdict["scores"].get(d_node),
                 "oracleScore": verdict["scores"].get(verdict["host"])})
        elif d_node is None and reasons_ok:
            d_msg = rec_reasons.get(uid, "")
            o_msg = oracle_reasons.get(uid, "")
            if d_msg != o_msg:
                diffs["reason"].append(
                    {"pod": uid, "device": d_msg, "oracle": o_msg})
    return {k: v for k, v in diffs.items() if v}


# ---------------------------------------------------------------------------
# hash-chained drain ledger


class DrainLedger:
    """Fixed-capacity ring of AuditRecords forming a hash chain.

    Appended by the scheduling thread at capture time (chain order ==
    dispatch order), outcome fields updated in place by the audit worker,
    read by the debug HTTP thread AND tailed by a standby scheduler
    (ha/standby.py). Three threads touch live records concurrently, so
    the discipline is explicit:

    - ring/head/appended/anchor are guarded by `_lock` (annotations
      below, checked by jaxsan's lock discipline);
    - the chain fields of an appended record (seq, prev_hash, hash, and
      the `chain_bytes()` inputs drain_id/profile_name/fingerprints) are
      IMMUTABLE after `append` — `verify()` may read them from a ring
      snapshot without holding the lock;
    - every OTHER record field (device decisions, outcome, diffs, the
      replay-payload clears) mutates only under `lock` — the audit
      worker takes it via the `lock` property, so a tail subscriber
      never observes a half-written outcome or a nodes list being
      cleared mid-iteration.
    """

    def __init__(self, capacity: int = 128):
        self._lock = threading.Lock()
        self.ring: list = []        # guarded_by: _lock
        self.capacity = capacity
        self.head = GENESIS         # guarded_by: _lock
        self.appended = 0           # guarded_by: _lock
        # prev_hash of the oldest retained record: verify() anchors here
        self._window_anchor = GENESIS  # guarded_by: _lock
        # handoff annex (sharded control plane): each shard steal /
        # rebalance notes the predecessor ledger's (head, cursor) here,
        # folded into its own hash chain — see record_handoff()
        self.handoffs: list = []       # guarded_by: _lock
        self.handoff_head = GENESIS    # guarded_by: _lock

    @property
    def lock(self):
        """The ledger lock, shared with record mutators (the audit
        worker) and tail subscribers so record field updates are atomic
        with respect to reads — see the class docstring discipline."""
        return self._lock

    def append(self, rec: AuditRecord) -> AuditRecord:
        with self._lock:
            rec.prev_hash = self.head
            rec.hash = _sha(self.head, rec.chain_bytes())
            self.head = rec.hash
            self.ring.append(rec)
            self.appended += 1
            rec.seq = self.appended
            if len(self.ring) > self.capacity:
                dropped = self.ring.pop(0)
                self._window_anchor = dropped.hash
        return rec

    def verify(self) -> bool:
        """Recompute the retained window's chain; False = a record was
        edited after the fact (or the chain was spliced). Safe against a
        concurrent appender: chain fields are immutable post-append, so
        verifying a ring snapshot taken under the lock cannot see a
        half-linked record."""
        with self._lock:
            records = list(self.ring)
            anchor = self._window_anchor
            head = self.head
        prev = anchor
        for rec in records:
            if rec.prev_hash != prev:
                return False
            if _sha(prev, rec.chain_bytes()) != rec.hash:
                return False
            prev = rec.hash
        return prev == head

    # -- streaming (ha/standby.py tail subscription) --------------------------

    def cursor(self) -> int:
        """Sequence number of the newest appended record (tail cursor)."""
        with self._lock:
            return self.appended

    def head_hash(self) -> str:
        """Current chain head (splice anchor for a successor ledger)."""
        with self._lock:
            return self.head

    def tail(self, after_seq: int, limit: int = 0) -> list:
        """Retained records with seq > after_seq, oldest first. A cursor
        that fell off the ring window simply yields everything retained —
        the subscriber detects the gap via `lag()` and resyncs."""
        with self._lock:
            out = [r for r in self.ring if r.seq > after_seq]
        if limit and len(out) > limit:
            out = out[:limit]
        return out

    def lag(self, after_seq: int) -> int:
        """How many drains a subscriber at `after_seq` is behind."""
        with self._lock:
            return max(0, self.appended - after_seq)

    def splice(self, head: str, seq: int = 0) -> None:
        """Adopt a predecessor ledger's head as this EMPTY ledger's chain
        anchor (HA takeover): the successor's first record links to the
        dead leader's last, so the combined chain across the handoff
        verifies end to end. Refuses on a non-empty ledger — splicing
        mid-chain is exactly the tamper `verify()` exists to catch."""
        with self._lock:
            if self.ring or self.appended:
                raise ValueError("splice requires an empty ledger")
            self.head = head
            self._window_anchor = head
            self.appended = seq

    # -- shard handoff annex --------------------------------------------------

    def record_handoff(self, shard_id: int, head: str, seq: int) -> dict:
        """Anchor a predecessor shard ledger's chain position on THIS
        (possibly non-empty) ledger. `splice()` only works on an empty
        ledger — a cold takeover — but a shard steal lands on a live
        successor whose own chain must not be rewritten. The annex is a
        separate hash chain folding each handoff (shard id, predecessor
        head, predecessor cursor), so the handoff history is
        tamper-evident exactly like the drain chain itself."""
        with self._lock:
            prev = self.handoff_head
            h = _sha(prev, f"{shard_id}|{head}|{seq}".encode("utf-8"))
            entry = {"shard": int(shard_id), "head": head, "seq": int(seq),
                     "prev": prev, "hash": h}
            self.handoffs.append(entry)
            self.handoff_head = h
        return entry

    def verify_handoffs(self) -> bool:
        """Recompute the handoff annex chain; False = an entry was edited
        (or inserted) after the fact."""
        with self._lock:
            entries = list(self.handoffs)
            head = self.handoff_head
        prev = GENESIS
        for e in entries:
            if e["prev"] != prev:
                return False
            if _sha(prev, f"{e['shard']}|{e['head']}|{e['seq']}"
                    .encode("utf-8")) != e["hash"]:
                return False
            prev = e["hash"]
        return prev == head

    def find(self, drain_id: int) -> Optional[AuditRecord]:
        with self._lock:
            for rec in reversed(self.ring):
                if rec.drain_id == drain_id:
                    return rec
        return None

    def find_pod(self, uid: str) -> Optional[AuditRecord]:
        """Newest record whose drain contains the pod (explain lookup)."""
        with self._lock:
            for rec in reversed(self.ring):
                ctx = rec.explain_ctx
                if ctx is not None and uid in ctx.uids:
                    return rec
        return None

    def records(self, limit: int = 0) -> list:
        with self._lock:
            out = list(self.ring)
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def dump(self, limit: int = 0, details: bool = False) -> dict:
        valid = self.verify()
        with self._lock:
            # to_dict reads worker-mutated fields (outcome, diffs):
            # serialize under the lock so a concurrent _process can't
            # hand the HTTP thread a half-written record
            recs = list(self.ring)
            if limit and len(recs) > limit:
                recs = recs[-limit:]
            return {"head": self.head, "appended": self.appended,
                    "chainValid": valid,
                    "records": [r.to_dict(details=details) for r in recs]}


# ---------------------------------------------------------------------------
# the audit sampler + background worker


class ShadowOracleAudit:
    """See module docstring. Owned by one Scheduler; the worker thread is
    lazy (first sampled drain) and a daemon."""

    def __init__(self, sample_rate: float = 1.0 / 64.0,
                 max_replay_pods: int = 64, dirpath: str = "",
                 metrics=None, slo=None, gates=None, capacity: int = 32,
                 synchronous: bool = False):
        self.sample_rate = float(sample_rate)
        self.max_replay_pods = int(max_replay_pods)
        self.dirpath = dirpath
        self.metrics = metrics
        self.slo = slo
        self.ledger = DrainLedger(capacity=capacity)
        self.synchronous = synchronous
        self.gates_fp = gates_fingerprint(gates) if gates is not None else ""
        self._accum = 0.0
        self._queue: _queue.Queue = _queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()

    # -- sampling -------------------------------------------------------------

    def want(self) -> bool:
        """Deterministic rate-accumulator sampling (no RNG: replays must
        be reproducible run to run)."""
        if self.sample_rate <= 0.0:
            return False
        self._accum += self.sample_rate
        if self._accum < 1.0:
            return False
        self._accum -= 1.0
        if self._queue.qsize() >= MAX_QUEUE:
            self._count("skipped")
            return False
        return True

    # -- capture (scheduling thread, quiesced pipeline) -----------------------

    def capture(self, drain_id: int, profile, qpis: list, snapshot,
                batch, n: int, state, builder, ext_gen: int
                ) -> AuditRecord:
        """Clone the quiesced snapshot + fingerprint the drain inputs and
        append to the hash chain. `batch` is the built PodBatch; `state`
        the ClusterState; `builder` the BatchBuilder (table identity)."""
        nodes = [ni.snapshot_clone() for ni in snapshot.node_info_list]
        # carry hash: per-node aggregate state the device carry encodes —
        # under the quiesce this IS the decision input
        ch = hashlib.sha256()
        for ni in nodes:
            ch.update(ni.name.encode())
            ch.update(str(sorted(ni.requested.items())).encode())
            ch.update(str(len(ni.pods)).encode())
        sig = batch.sig[:n]
        tidx = batch.tidx[:n]
        rows = sorted(set(int(t) for t in tidx))
        table = builder.table
        row_hash = hashlib.sha256(sig.tobytes())
        row_hash.update(tidx.tobytes())
        for u in rows:
            row_hash.update(table.req[u].tobytes())
        fingerprints = {
            "podTableRows": row_hash.hexdigest(),
            "staticsGen": int(state.statics_gen),
            "planKey": _sha(builder.reset_count, builder.table_used,
                            sig.tobytes(), tidx.tobytes(),
                            profile.score_config.strategy, self.gates_fp),
            "gates": self.gates_fp,
            "strategy": profile.score_config.strategy,
            "carry": ch.hexdigest(),
            "pods": int(n),
        }
        rec = AuditRecord(
            drain_id=drain_id, profile_name=profile.name,
            strategy=profile.score_config.strategy,
            weights=dict(profile.framework.weights),
            pods=[(q.pod.uid, q.pod, q.pod_info) for q in qpis],
            nodes=nodes, framework=profile.framework,
            fingerprints=fingerprints, ext_gen=ext_gen,
            captured_at=_time.time())
        return self.ledger.append(rec)

    def attach_device(self, rec: AuditRecord, cfg, na, carry, table,
                      batch, n: int, gd, fam, names=()) -> None:
        """Keep the device-side replay inputs for exact explain. The
        carry is COPIED on device (the dispatch chain donates/consumes
        the original)."""
        import jax
        import numpy as np
        carry0 = jax.tree_util.tree_map(lambda x: x.copy()
                                        if hasattr(x, "copy") else x,
                                        carry)
        rec.explain_ctx = ExplainCtx(
            cfg=cfg, na=na, carry0=carry0, table=table, gd=gd, fam=fam,
            sig=np.array(batch.sig[:n]), tidx=np.array(batch.tidx[:n]),
            uids=tuple(uid for uid, _p, _pi in rec.pods),
            names=tuple(names))

    def abandon(self, rec: AuditRecord, reason: str) -> None:
        """The drain degraded off the audited dispatch path before its
        results existed (host fallback, overlay, device fault)."""
        with self.ledger.lock:
            rec.outcome = "skipped"
            rec.skip_reason = reason
        self._count("skipped")

    # -- submit (scheduling thread, commit time) ------------------------------

    def submit(self, rec: AuditRecord, out, names: list, fail_msgs: dict,
               flight_rec=None, ext_gen: int = 0) -> None:
        """Record the committed device decisions and hand the record to
        the worker (or process inline in synchronous mode)."""
        import numpy as np
        device: dict = {}
        for i, (uid, _pod, _pi) in enumerate(rec.pods):
            a = int(out[i]) if i < len(out) else -1
            device[uid] = names[a] if a >= 0 else None
        with self.ledger.lock:
            rec.device = device
            rec.reasons_dev = dict(fail_msgs)
            # an external cluster event between dispatch and commit moves
            # the snapshot the device diagnosis reads — assignments stay
            # exact (computed from the captured carry), reasons are not
            # comparable
            rec.reasons_ok = ext_gen == rec.ext_gen
            if rec.explain_ctx is not None:
                rec.explain_ctx.assignments = np.array(out[:len(rec.pods)])
            rec._flight = flight_rec
        if self.synchronous:
            self._process(rec)
            return
        self._ensure_worker()
        self._queue.put(rec)

    # -- worker ---------------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._worker_lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(target=self._run, daemon=True,
                                            name="shadow-oracle-audit")
            self._worker.start()

    def _run(self) -> None:
        while True:
            rec = self._queue.get()
            try:
                self._process(rec)
            except Exception:       # the audit must never kill the worker
                with self.ledger.lock:
                    rec.outcome = "error"
                self._count("error")
            finally:
                self._queue.task_done()

    def flush(self, timeout: float = 30.0) -> None:
        """Wait for every submitted record to finish replaying (tests,
        bench end)."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return
            _time.sleep(0.01)

    def _process(self, rec: AuditRecord) -> None:
        t0 = _time.perf_counter()
        try:
            # replay over fresh clones: rec.nodes is the LEDGERED capture
            # state — the CLI pickle and /debug re-read it pristine. The
            # clone pass is the only rec.nodes read; take it under the
            # ledger lock so the eventual clear (below) can never race a
            # tail subscriber or a second iteration of this list.
            with self.ledger.lock:
                nodes = [ni.snapshot_clone() for ni in rec.nodes]
                device = dict(rec.device)
                reasons_dev = dict(rec.reasons_dev)
                reasons_ok = rec.reasons_ok
            oracle, oracle_reasons, truncated = replay_decisions(
                rec.framework, nodes, rec.pods, device=device,
                cap=self.max_replay_pods)
        except Exception as e:
            with self.ledger.lock:
                rec.outcome = "error"
                rec.skip_reason = f"replay: {e}"
                rec.replay_s = _time.perf_counter() - t0
            self._count("error")
            return
        diffs = diff_decisions(
            device, reasons_dev, oracle, oracle_reasons,
            reasons_ok=reasons_ok and not truncated)
        divergent = bool(diffs)
        # one atomic publication of the verdict: a tail subscriber (or
        # /debug/audit) sees either a fully "pending" record or a fully
        # replayed one — never outcome without diffs or vice versa
        with self.ledger.lock:
            rec.replay_s = _time.perf_counter() - t0
            rec.oracle = oracle
            rec.reasons_oracle = oracle_reasons
            rec.truncated = truncated
            rec.diffs = diffs
            rec.outcome = "divergent" if divergent else "clean"
        if self.metrics is not None:
            for kind, items in diffs.items():
                self.metrics.oracle_divergence.inc(kind, by=len(items))
            self.metrics.audit_replay_duration.observe(rec.replay_s)
        self._count(rec.outcome)
        if self.slo is not None:
            self.slo.observe("divergence", good=0 if divergent else 1,
                             bad=1 if divergent else 0)
        flight = getattr(rec, "_flight", None)
        if flight is not None:
            flight.audit = {"outcome": rec.outcome,
                            "divergences": rec.divergence_count(),
                            "diffs": diffs,
                            "hash": rec.hash}
        if self.dirpath:
            self._persist(rec)
        if not divergent:
            # memory bound: a clean record's replay payload (O(nodes)
            # NodeInfo clones) is no longer needed — the hash chain,
            # fingerprints and explain context stay; divergent records
            # keep everything for the post-mortem (and the pickle, when
            # persistence is on, already captured the full payload)
            with self.ledger.lock:
                rec.nodes = []
                rec.oracle = {}
                rec.reasons_oracle = {}

    def _count(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.shadow_audit_drains.inc(outcome)

    def _persist(self, rec: AuditRecord) -> None:
        try:
            os.makedirs(self.dirpath, exist_ok=True)
            path = os.path.join(self.dirpath,
                                f"drain_{rec.drain_id:08d}.pkl")
            with open(path, "wb") as f:
                pickle.dump(rec.to_payload(), f)
        except Exception:           # persistence is best-effort
            pass

    # -- serving --------------------------------------------------------------

    def dump(self, limit: int = 32, details: bool = False) -> dict:
        d = self.ledger.dump(limit=limit, details=details)
        d["sampleRate"] = self.sample_rate
        d["maxReplayPods"] = self.max_replay_pods
        d["queued"] = self._queue.qsize()
        return d
