"""Streaming telemetry timeline: a per-second aggregate ring.

One bucket per wall-clock second, accumulating every SLI the scheduler
streams (binds, failures, requeues by cause, drains, e2e segment
sums/counts) plus the latest `cluster_probe` snapshot and an SLO sample
taken when the bucket closes. The ring holds the last `horizon` seconds;
`/debug/timeline?seconds=N` serves the newest N buckets as JSON, and a
config-gated JSON-lines exporter (`timeline_export_path`) appends each
bucket to disk as it rotates out of "current" — one line per second, so
a tail of the file IS the live timeline.

Buckets are plain dicts keyed by integer second; the hot-path cost of a
sample is one dict lookup + a few float adds.
"""

from __future__ import annotations

import json
import time as _time
from collections import OrderedDict
from typing import Callable, Optional

from .journey import CAUSES, SEGMENTS


def _new_bucket(sec: int) -> dict:
    return {
        "t": sec,
        "binds": 0,
        "failures": 0,
        "requeues": {},          # cause → count
        "drains": 0,
        "pops": 0,
        "e2e": {},               # segment → [sum_seconds, count]
        "probe": None,           # latest cluster_probe snapshot this second
        "slo": None,             # SLO sample stamped when the bucket closes
    }


class Timeline:
    """Per-second aggregate ring over all SLIs + probe outputs."""

    def __init__(self, horizon: int = 900,
                 clock: Callable[[], float] = _time.monotonic,
                 export_path: str = "",
                 slo_sample: Optional[Callable[[], dict]] = None,
                 enabled: bool = True):
        self.horizon = horizon
        self.clock = clock
        self.export_path = export_path
        self.slo_sample = slo_sample
        self.enabled = enabled
        self._buckets: OrderedDict[int, dict] = OrderedDict()
        self._exported = 0   # buckets written to the JSON-lines export

    # -- bucket plumbing ------------------------------------------------------

    def _bucket(self, now: float) -> dict:
        sec = int(now)
        b = self._buckets.get(sec)
        if b is None:
            self._rotate(sec)
            b = self._buckets[sec] = _new_bucket(sec)
        return b

    def _rotate(self, new_sec: int) -> None:
        """A new second began: stamp the closing bucket with an SLO
        sample, stream closed buckets to the exporter, evict old ones."""
        if self._buckets:
            last = next(reversed(self._buckets))
            if self.slo_sample is not None and new_sec > last:
                try:
                    self._buckets[last]["slo"] = self.slo_sample()
                except Exception:  # sampling must never break the hot path
                    pass
        if self.export_path:
            self._export_closed(new_sec)
        while len(self._buckets) >= self.horizon:
            self._buckets.popitem(last=False)
            if self._exported > 0:
                self._exported -= 1

    def _export_closed(self, new_sec: int) -> None:
        closed = [b for sec, b in self._buckets.items() if sec < new_sec]
        # `_exported` counts closed buckets already streamed; eviction only
        # ever removes exported buckets, so index from the tail.
        fresh = closed[self._exported:]
        if not fresh:
            return
        try:
            with open(self.export_path, "a") as fh:
                for b in fresh:
                    fh.write(json.dumps(b, separators=(",", ":")) + "\n")
            self._exported = len(closed)
        except OSError:
            self.export_path = ""  # disable on a broken sink, don't spin

    # -- hot-path samples -----------------------------------------------------

    def bump(self, now: float, field: str, by: int = 1) -> None:
        if not self.enabled:
            return
        b = self._bucket(now)
        b[field] = b.get(field, 0) + by

    def requeue(self, now: float, cause: str, by: int = 1) -> None:
        if not self.enabled:
            return
        rq = self._bucket(now)["requeues"]
        rq[cause] = rq.get(cause, 0) + by

    def segment(self, now: float, segment: str, total: float,
                count: int) -> None:
        """Accumulate `count` observations summing to `total` seconds."""
        if not self.enabled or count <= 0:
            return
        e2e = self._bucket(now)["e2e"]
        cell = e2e.get(segment)
        if cell is None:
            e2e[segment] = [total, count]
        else:
            cell[0] += total
            cell[1] += count

    def probe(self, now: float, snapshot: dict) -> None:
        if not self.enabled:
            return
        self._bucket(now)["probe"] = snapshot

    # -- queries / export -----------------------------------------------------

    def series(self, seconds: int = 60) -> dict:
        """The newest `seconds` buckets, oldest first."""
        buckets = list(self._buckets.values())[-max(int(seconds), 1):]
        return {
            "horizonSeconds": self.horizon,
            "causes": list(CAUSES),
            "segments": list(SEGMENTS),
            "buckets": buckets,
        }

    def to_jsonl(self, path: str) -> int:
        """Dump the whole ring as JSON lines (one bucket per line);
        returns the number of buckets written. Used by
        `bench.py --timeline-dir` for one timeline per workload."""
        buckets = list(self._buckets.values())
        with open(path, "w") as fh:
            for b in buckets:
                fh.write(json.dumps(b, separators=(",", ":")) + "\n")
        return len(buckets)
