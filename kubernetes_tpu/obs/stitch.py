"""Cross-shard journey stitching (ISSUE 19).

Each scheduler instance owns a per-process JourneyLedger; a pod that is
parked on one shard, stolen mid-drain and bound by another leaves a
FRAGMENT of its lifecycle on every instance it touched. The stitcher
merges those fragments by pod uid into one causal cross-shard timeline:

- every transition is tagged with the writer instance's identity and
  carries the fence stamp the ledger recorded (the writer's held
  (lease, generation) set), so a zombie's post-depose transitions are
  attributable to the OLD fencing epoch while the adopter's carry the
  new one;
- transitions merge in timestamp order (all in-process ledgers share
  one monotonic clock; the cross-process step will align scrape
  clocks) — ties keep member order, which is deterministic;
- the e2e SLI clock is the MINIMUM first-enqueue across instances: a
  steal must not restart the queue→bind clock any more than a requeue
  does (parking seeds the clock on the peer, so the adopter's clock
  already matches the origin's);
- segment decomposition reuses `JourneyLedger._segments` over the
  merged transition list, so a stitched timeline decomposes exactly
  like a single-instance one.

`coverage()` is the bench/test proof: every bound pod must stitch to
exactly ONE timeline ending in bind_confirm, with zero orphaned
per-instance fragments left dangling.
"""

from __future__ import annotations

from .journey import CAUSES, EVENTS, JourneyLedger

# one-line renderer notes per transition code — the /debug/pod legend.
# tools/check.py `obs_coverage` asserts this covers EVERY event in
# EVENTS: a new journey transition cannot land without its rendering.
EVENT_NOTES = {
    "enqueue": "first add to the scheduling queue",
    "gate": "PreEnqueue gated (detail = gating plugin)",
    "ungate": "gate cleared (quorum met / gate removed)",
    "pop": "popped off the activeQ into a scheduling attempt",
    "drain": "entered device drain N (detail = path)",
    "assign": "node chosen (detail = node name)",
    "fit_error": "unschedulable (detail = rejector plugins)",
    "requeue": "re-entered the queue (detail = cause)",
    "bind_enqueue": "bind handed to the API dispatcher",
    "bind_flush": "dispatcher flushed the bind to the API server",
    "bind_confirm": "bind echo confirmed through the watch stream",
    "park": "peer shard's pod parked warm (detail = why)",
    "adopt": "parked pod adopted into the queue (rebalance/steal)",
    "evict": "queued pod evicted to the parked set (handoff)",
    "steal": "shard slice stolen by another instance",
    "transfer": "cooperative shard transfer (split/merge/rebalance)",
}

# one-line renderer notes per requeue cause — also obs_coverage-gated
CAUSE_NOTES = {
    "preemption": "failure nominated a node; waiting on victim eviction",
    "fence_unwind": "write fenced (deposed/stolen lease); assumed undone",
    "breaker_fallback": "device tier breaker open; host-path retry",
    "gang_split": "gang member unwound with its group",
    "resync": "queue rebuilt from a fresh LIST (watch loss)",
    "bind_error": "API bind failed; forgotten and backed off",
    "unschedulable": "no feasible node this attempt",
}


class JourneyStitcher:
    """Merge N instances' journey ledgers into per-pod fleet timelines.

    `members` are ShardScheduler / StandbyScheduler / Scheduler-shaped
    objects: anything with a `.scheduler` (or itself Scheduler-shaped)
    exposing `.journey`."""

    def __init__(self, members=()):
        self._members = list(members)

    def add(self, member) -> None:
        self._members.append(member)

    def ledgers(self):
        """Yield (instance name, JourneyLedger) per member."""
        for i, m in enumerate(self._members):
            sched = getattr(m, "scheduler", m)
            ledger = getattr(sched, "journey", None)
            if ledger is None:
                continue
            name = (ledger.instance or getattr(m, "identity", "")
                    or f"instance-{i}")
            yield name, ledger

    # -- query (cold path: /debug/pod on the manager) -------------------------

    def pod(self, uid: str) -> dict:
        """One stitched causal timeline for a pod across every instance
        that saw it."""
        merged: list = []
        instances: list = []
        first = None
        for name, ledger in self.ledgers():
            view = ledger.pod(uid)
            if not view["transitions"] and view["firstEnqueue"] is None:
                continue
            instances.append(name)
            if view["firstEnqueue"] is not None:
                first = (view["firstEnqueue"] if first is None
                         else min(first, view["firstEnqueue"]))
            for tr in view["transitions"]:
                tr["instance"] = name
                merged.append(tr)
        merged.sort(key=lambda tr: tr["t"])   # stable: ties keep member order
        if first is None and merged:
            first = merged[0]["t"]
        fences = list(dict.fromkeys(tr["fence"] for tr in merged
                                    if tr["fence"]))
        present = {tr["event"] for tr in merged}
        return {
            "uid": uid,
            "firstEnqueue": first,
            "instances": instances,
            "fences": fences,
            "transitions": merged,
            "segments": JourneyLedger._segments(merged),
            "notes": {ev: EVENT_NOTES[ev] for ev in EVENTS
                      if ev in present},
            "causes": {c: CAUSE_NOTES[c] for c in CAUSES
                       if any(tr["event"] == "requeue"
                              and tr["detail"].split(":")[0] == c
                              for tr in merged)},
        }

    def coverage(self, uids) -> dict:
        """The stitch proof over a pod population: `stitched` counts
        pods whose MERGED timeline reaches bind_confirm; `orphaned`
        counts per-instance fragments belonging to pods that never
        stitched to a confirmed bind (dangling lifecycle shards). For a
        fully bound population, stitched == len(uids), orphaned == 0."""
        stitched = orphaned = fragments = 0
        for uid in uids:
            view = self.pod(uid)
            n = len(view["instances"])
            fragments += n
            if any(tr["event"] == "bind_confirm"
                   for tr in view["transitions"]):
                stitched += 1
            else:
                orphaned += n
        return {"pods": len(uids), "stitched": stitched,
                "fragments": fragments, "orphaned": orphaned}

    # -- fleet Chrome trace ---------------------------------------------------

    def chrome_trace(self) -> dict:
        """All instances' span histories merged onto one clock with a
        per-shard process track (utils/tracing.py fleet_chrome_trace)."""
        from ..utils.tracing import fleet_chrome_trace
        pairs = []
        for i, m in enumerate(self._members):
            sched = getattr(m, "scheduler", m)
            tracer = getattr(sched, "tracer", None)
            if tracer is None:
                continue
            ledger = getattr(sched, "journey", None)
            name = ((ledger.instance if ledger is not None else "")
                    or getattr(m, "identity", "") or f"instance-{i}")
            pairs.append((name, tracer))
        return fleet_chrome_trace(pairs)
