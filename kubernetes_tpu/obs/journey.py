"""Pod-journey tracing: a columnar per-pod lifecycle ledger.

Every observability layer before this one (flight recorder, profiler/
ledger, shadow audit, SLO engine) sees the world one *drain* at a time;
none can answer "where did pod X spend its 40ms between enqueue and
bind". The JourneyLedger records every pod state transition with
monotonic timestamps into a ring of parallel columns — first enqueue,
PreEnqueue gate/ungate (incl. gang quorum waits), pop into drain N,
assignment or FitError, dispatcher enqueue/flush, bind-echo confirm,
and every requeue with its *cause* (preemption nomination, FencedWrite
unwind, breaker fallback, gang split, resync) — so `/debug/pod?uid=`
renders a full causal timeline and queue→bind e2e latency decomposes
into the `scheduler_e2e_segment_seconds{segment=...}` families.

Hot-path contract: NO per-pod dict/object churn for transitions — the
ring is five parallel Python lists extended in bulk (one `extend` per
column per drain, not per pod) and trimmed amortized. The only per-pod
dict state is two flat clocks the e2e SLI itself needs:

  * `_first_seen` — the pod's FIRST enqueue time. This is the e2e SLI
    clock's source of truth: it survives requeues, bind-error unwinds
    (which mint a fresh QueuedPodInfo) and `resync()` (which rebuilds
    the whole queue from a LIST). It is maintained even with the
    `PodJourneyTracing` gate off, because the SLI bugfix must hold
    regardless of whether tracing is on.
  * `_bind_enq` — dispatcher-enqueue time, popped at bind-echo confirm
    to produce the `commit_backlog` segment.

Both are dropped at bind-echo confirm / pod delete, so they are bounded
by the in-flight pod population, not pod history.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

# transition codes — index into EVENTS (column `_ev` stores the int)
EV_ENQUEUE = 0       # first add to the scheduling queue
EV_GATE = 1          # PreEnqueue gated (detail = gating plugin)
EV_UNGATE = 2        # gate cleared (gang quorum met / gate removed)
EV_POP = 3           # popped off the activeQ into a scheduling attempt
EV_DRAIN = 4         # entered device drain N (detail = path)
EV_ASSIGN = 5        # node chosen (detail = node name)
EV_FIT_ERROR = 6     # unschedulable (detail = rejector plugins)
EV_REQUEUE = 7       # re-entered the queue (detail = cause)
EV_BIND_ENQUEUE = 8  # bind handed to the API dispatcher
EV_BIND_FLUSH = 9    # dispatcher flushed the bind to the API server
EV_BIND_CONFIRM = 10  # bind echo confirmed through the watch stream
# shard lifecycle (ha/shards.py, ISSUE 19): parked for a peer shard,
# warm adoption out of the parked set, eviction back into it, and the
# manager-driven steal/transfer handoffs — first-class transitions so
# the fleet stitcher can merge per-instance ledgers into one causal
# cross-shard timeline
EV_PARK = 11         # peer shard's pod parked (detail = why)
EV_ADOPT = 12        # parked pod adopted into the queue (rebalance/steal)
EV_EVICT = 13        # queued pod evicted to the parked set (handoff)
EV_STEAL = 14        # shard slice stolen by another instance
EV_TRANSFER = 15     # cooperative shard transfer (split/merge/rebalance)

EVENTS = ("enqueue", "gate", "ungate", "pop", "drain", "assign",
          "fit_error", "requeue", "bind_enqueue", "bind_flush",
          "bind_confirm", "park", "adopt", "evict", "steal", "transfer")

# requeue causes (the `cause` label set of scheduler_pod_requeues_total;
# exposition-lint asserts this exact set)
CAUSES = ("preemption", "fence_unwind", "breaker_fallback", "gang_split",
          "resync", "bind_error", "unschedulable")

# e2e decomposition segments (the `segment` label set of
# scheduler_e2e_segment_seconds; exposition-lint asserts this exact set)
SEGMENTS = ("queue_wait", "gate_wait", "drain", "commit_backlog")


class JourneyLedger:
    """Ring-buffered columnar transition log + the e2e SLI clocks."""

    def __init__(self, capacity: int = 1 << 16,
                 clock: Callable[[], float] = _time.monotonic,
                 metrics=None, enabled: bool = True):
        self.capacity = capacity
        self.clock = clock
        self.metrics = metrics
        self.timeline = None   # obs/timeline.py ring, attached by the owner
        self.enabled = enabled
        # writer identity (ha/shards.py sets the instance name): the
        # stitching key — a transition's provenance when N instances'
        # ledgers merge into one cross-shard timeline (obs/stitch.py)
        self.instance = ""
        # fence-stamp provider: () -> str naming the writer's held
        # (lease, generation) set at record time ("" = unfenced). Wired
        # by ShardScheduler so every transition carries proof of WHICH
        # fencing epoch wrote it — a zombie's post-depose transitions are
        # distinguishable from the new owner's in the stitched timeline.
        self.fence_stamp: Optional[Callable[[], str]] = None
        # parallel columns (the ring): object ref, event code, timestamp,
        # detail string, drain id, writer fence stamp
        self._uid: list = []
        self._ev: list = []
        self._ts: list = []
        self._detail: list = []
        self._drain: list = []
        self._fence: list = []
        # e2e SLI clock: uid → first-enqueue time (see module docstring —
        # maintained even when transition recording is disabled)
        self._first_seen: dict[str, float] = {}
        # uid → dispatcher-enqueue time (commit_backlog segment)
        self._bind_enq: dict[str, float] = {}

    # -- e2e SLI clock --------------------------------------------------------

    def first_enqueue(self, uid: str, now: float) -> bool:
        """Record the pod's first-enqueue time; True iff this was the
        first sighting (a requeue/re-add of a known pod returns False and
        leaves the original clock untouched)."""
        if uid in self._first_seen:
            return False
        self._first_seen[uid] = now
        return True

    def e2e_start(self, uid: str, default: Optional[float] = None):
        """The pod's FIRST enqueue time (the e2e SLI clock start), or
        `default` when the pod was never seen (e.g. ledger restarted)."""
        return self._first_seen.get(uid, default)

    def forget(self, uid: str) -> None:
        """Drop the per-pod clocks (bind confirmed or pod deleted)."""
        self._first_seen.pop(uid, None)
        self._bind_enq.pop(uid, None)

    # -- recording ------------------------------------------------------------

    def record(self, uid: str, ev: int, now: float, detail: str = "",
               drain: int = 0) -> None:
        if not self.enabled:
            return
        self._uid.append(uid)
        self._ev.append(ev)
        self._ts.append(now)
        self._detail.append(detail)
        self._drain.append(drain)
        self._fence.append(self.fence_stamp() if self.fence_stamp
                           is not None else "")
        if self.metrics is not None:
            self.metrics.journey_transitions.inc(EVENTS[ev])
        if len(self._uid) >= self.capacity * 2:
            self._trim()

    def record_bulk(self, uids: list, ev: int, now: float,
                    detail="", drain: int = 0) -> None:
        """Bulk transition append: one extend per column for the whole
        batch. `detail` is a shared string or a per-pod list aligned
        with `uids`."""
        if not self.enabled or not uids:
            return
        n = len(uids)
        self._uid.extend(uids)
        self._ev.extend([ev] * n)
        self._ts.extend([now] * n)
        self._detail.extend(detail if isinstance(detail, list)
                            else [detail] * n)
        self._drain.extend([drain] * n)
        # one stamp per batch: every member was written under the same
        # fencing epoch (the batch is one critical section)
        self._fence.extend([self.fence_stamp() if self.fence_stamp
                            is not None else ""] * n)
        if self.metrics is not None:
            self.metrics.journey_transitions.inc(EVENTS[ev], by=n)
        if len(self._uid) >= self.capacity * 2:
            self._trim()

    def _trim(self) -> None:
        """Amortized ring behavior: let the columns grow to 2× capacity,
        then cut back to capacity in one slice-delete per column."""
        cut = len(self._uid) - self.capacity
        if cut <= 0:
            return
        del self._uid[:cut]
        del self._ev[:cut]
        del self._ts[:cut]
        del self._detail[:cut]
        del self._drain[:cut]
        del self._fence[:cut]

    def popped(self, qpis: list, now: float) -> None:
        """Pods popped off the activeQ into a scheduling attempt: EV_POP
        plus the queue_wait segment (time since the last ready-enqueue,
        which `qpi.timestamp` tracks across requeues)."""
        if not self.enabled or not qpis:
            return
        waits = [max(now - q.timestamp, 0.0) for q in qpis]
        if self.metrics is not None:
            self.metrics.e2e_segment.observe_array(waits, "queue_wait")
        if self.timeline is not None:
            self.timeline.segment(now, "queue_wait", sum(waits), len(waits))
            self.timeline.bump(now, "pops", len(waits))
        self.record_bulk([q.pod.uid for q in qpis], EV_POP, now)

    # -- dispatcher / commit hooks -------------------------------------------

    def bind_enqueued(self, uids: list, now: float) -> None:
        """Binds handed to the API dispatcher: transition + the
        commit_backlog clock start (per-pod, popped at confirm)."""
        if not self.enabled:
            return
        enq = self._bind_enq
        for uid in uids:
            enq[uid] = now
        self.record_bulk(uids, EV_BIND_ENQUEUE, now)

    def bind_confirmed(self, uids: list, now: float) -> list:
        """Bind-echo confirms: transition + commit_backlog segment
        durations (dispatcher enqueue → echo) for the pods that had a
        recorded enqueue. Drops the per-pod clocks."""
        enq_pop = self._bind_enq.pop
        first_pop = self._first_seen.pop
        waits: list = []
        for uid in uids:
            t0 = enq_pop(uid, None)
            if t0 is not None:
                waits.append(max(now - t0, 0.0))
            first_pop(uid, None)
        self.record_bulk(uids, EV_BIND_CONFIRM, now)
        return waits

    # -- query (cold path: /debug/pod) ---------------------------------------

    def pod(self, uid: str) -> dict:
        """Full causal timeline for one pod: every ring transition (in
        order) plus the derived per-segment decomposition."""
        transitions = [
            {"t": self._ts[i], "event": EVENTS[self._ev[i]],
             "detail": self._detail[i], "drain": self._drain[i],
             "fence": self._fence[i]}
            for i in range(len(self._uid)) if self._uid[i] == uid
        ]
        return {
            "uid": uid,
            "instance": self.instance,
            "firstEnqueue": self._first_seen.get(uid),
            "transitions": transitions,
            "segments": self._segments(transitions),
        }

    @staticmethod
    def _segments(transitions: list) -> dict:
        """Decompose a transition list into the e2e segment sums (the
        per-pod analog of scheduler_e2e_segment_seconds)."""
        seg = {name: 0.0 for name in SEGMENTS}
        ready_at = None      # last enqueue/ungate/requeue time
        gated_at = None
        drained_at = None
        bind_enq_at = None
        for tr in transitions:
            ev, t = tr["event"], tr["t"]
            if ev in ("enqueue", "requeue"):
                ready_at = t
            elif ev == "gate":
                gated_at = t
            elif ev == "ungate":
                if gated_at is not None:
                    seg["gate_wait"] += max(t - gated_at, 0.0)
                    gated_at = None
                ready_at = t
            elif ev == "pop":
                if ready_at is not None:
                    seg["queue_wait"] += max(t - ready_at, 0.0)
                    ready_at = None
            elif ev == "drain":
                drained_at = t
            elif ev in ("assign", "fit_error"):
                if drained_at is not None:
                    seg["drain"] += max(t - drained_at, 0.0)
                    drained_at = None
            elif ev == "bind_enqueue":
                bind_enq_at = t
            elif ev == "bind_confirm":
                if bind_enq_at is not None:
                    seg["commit_backlog"] += max(t - bind_enq_at, 0.0)
                    bind_enq_at = None
        return seg

    def stats(self) -> dict:
        return {"transitions": len(self._uid),
                "capacity": self.capacity,
                "trackedPods": len(self._first_seen),
                "enabled": self.enabled}
