"""Decision provenance: per-bind plugin-level score decomposition.

`diagnose_row` (PR 4) answers "why was this pod rejected everywhere";
this module answers the complement for PLACED pods — "why did pod X land
on node Y instead of Z" — via the `explain_row` kernel
(ops/program.py): the winning node and the top-k runners-up with each
plugin's weighted score contribution and the win margin.

Two modes, served as /debug/explain?pod=<uid>:

- **exact** — the pod's drain is in the shadow-audit ledger
  (obs/audit.py): the drain PREFIX up to the pod replays through
  `run_batch` from the captured pre-drain carry, reconstructing the
  exact per-step state its decision was made against; the reported
  winner is bit-identical to the committed bind (run_batch ≡ the
  dispatched program is the fuzzed system invariant, and exactly what
  the audit watches). This is what makes every SAMPLED bind attributable
  to a plugin-level score delta at any time after the fact.
- **current_state** — the drain has left the ledger (or was never
  sampled): the decomposition evaluates against the live post-commit
  state with the pod's own RESOURCES removed from its bound node (group
  counters and port bookkeeping are not unwound — flagged in the
  output), the same trade `kubectl describe`-style tooling makes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops.program import (EXPLAIN_COLUMNS, PodXs, explain_row,
                           initial_carry, run_batch)

# short column headers for the rendered table, EXPLAIN_COLUMNS order
_HEADERS = ("Fit", "Balanced", "Taint", "NodeAffinity", "Image", "Groups")


def _copy_carry(carry):
    import jax
    return jax.tree_util.tree_map(
        lambda x: x.copy() if hasattr(x, "copy") else x, carry)


def _assemble(uid: str, mode: str, names, idx, totals, cols, n_feasible,
              bound: Optional[str], extra: dict, k: int) -> dict:
    idx = np.asarray(idx)
    totals = np.asarray(totals)
    cols = np.asarray(cols)
    ranked = []
    for r in range(min(k, len(idx))):
        if totals[r] < 0:
            break
        node_i = int(idx[r])
        ranked.append({
            "node": names[node_i] if node_i < len(names) else f"#{node_i}",
            "total": int(totals[r]),
            "columns": {name: int(cols[r, c])
                        for c, name in enumerate(EXPLAIN_COLUMNS)},
        })
    margin = (int(totals[0] - totals[1])
              if len(ranked) >= 2 else None)
    out = {
        "pod": uid, "mode": mode, "boundNode": bound,
        "feasibleNodes": int(n_feasible),
        "winner": ranked[0] if ranked else None,
        "margin": margin,
        "runnersUp": ranked[1:],
        **extra,
    }
    out["rendered"] = _render(out)
    return out


def _render(d: dict) -> str:
    """Reference-format text table (the /debug/explain human form)."""
    lines = [f"pod {d['pod']}: "
             + (f"bound to {d['boundNode']}" if d["boundNode"]
                else "not bound")
             + f" [{d['mode']}]"]
    winner = d.get("winner")
    if winner is None:
        lines.append(f"  no feasible node "
                     f"({d['feasibleNodes']} feasible)")
        return "\n".join(lines)
    margin = d.get("margin")
    lines.append(
        f"  top {1 + len(d['runnersUp'])} of {d['feasibleNodes']} "
        "feasible nodes"
        + (f", win margin +{margin}" if margin is not None else ""))
    width = max(len(winner["node"]),
                *(len(r["node"]) for r in d["runnersUp"])) \
        if d["runnersUp"] else len(winner["node"])
    width = max(width, 4)
    header = ("  #  " + "node".ljust(width) + "  total  "
              + "  ".join(h.rjust(len(h)) for h in _HEADERS))
    lines.append(header)
    for rank, row in enumerate([winner] + d["runnersUp"], start=1):
        cells = "  ".join(
            str(row["columns"][name]).rjust(len(h))
            for name, h in zip(EXPLAIN_COLUMNS, _HEADERS))
        lines.append(f"  {rank}  " + row["node"].ljust(width)
                     + f"  {str(row['total']).rjust(5)}  " + cells)
    return "\n".join(lines)


def _prefix_carry(ctx, i: int, carry):
    """Carry after the drain's first `i` pods: one run_batch dispatch
    over the prefix (the donated input is the caller's throwaway copy)."""
    from ..state.tensorize import pow2_at_least
    bucket = pow2_at_least(i)
    valid = np.zeros((bucket,), bool)
    valid[:i] = True
    sig = np.full((bucket,), ctx.sig[i - 1], np.int32)
    sig[:i] = ctx.sig[:i]
    tidx = np.full((bucket,), ctx.tidx[i - 1], np.int32)
    tidx[:i] = ctx.tidx[:i]
    xs = PodXs(valid=valid, sig=sig, tidx=tidx)
    return run_batch(ctx.cfg, ctx.na, carry, xs, ctx.table,
                     groups=ctx.gd, fam=ctx.fam)[0]


def _explain_exact(rec, uid: str, k: int) -> dict:
    """Replay the audited drain's prefix and decompose the pod's step."""
    ctx = rec.explain_ctx
    i = ctx.uids.index(uid)
    carry = _copy_carry(ctx.carry0)
    if i > 0:
        carry = _prefix_carry(ctx, i, carry)
    idx, totals, cols, n_feas = explain_row(
        ctx.cfg, ctx.na, carry, ctx.table, int(ctx.tidx[i]), k=k,
        gd=ctx.gd, fam=ctx.fam)
    actual = int(ctx.assignments[i]) if ctx.assignments is not None else -1
    bound = ctx.names[actual] if 0 <= actual < len(ctx.names) else None
    winner_i = int(np.asarray(idx)[0])
    matches = (actual >= 0 and winner_i == actual
               and int(np.asarray(totals)[0]) >= 0) \
        or (actual < 0 and int(np.asarray(totals)[0]) < 0)
    return _assemble(uid, "exact", ctx.names, idx, totals, cols, n_feas,
                     bound,
                     {"drainId": rec.drain_id, "drainIndex": i,
                      "matchesBind": bool(matches),
                      "ledgerHash": rec.hash}, k)


def _explain_current(scheduler, pod, uid: str, k: int) -> dict:
    """Decompose against the live post-commit state, the pod's own
    resources removed from its bound node."""
    import jax.numpy as jnp
    from ..framework.types import PodInfo
    from ..ops.groups import to_device
    from ..ops.program import PodTableDev
    scheduler._drain_pending()
    scheduler.cache.update_snapshot(scheduler.snapshot)
    scheduler.state.apply_snapshot(scheduler.snapshot)
    scheduler.state.ensure_arrays()
    ent = scheduler.builder._lookup(pod)
    if ent[0] != "row":
        return {"pod": uid, "error": "pod signature has no tensor form "
                                     "(host-fallback pod); explain "
                                     "unavailable"}
    tidx = ent[2]
    builder = scheduler.builder
    na = scheduler.state.device_arrays()
    table = PodTableDev(*(jnp.asarray(getattr(builder.table, f))
                          for f in PodTableDev._fields))
    gd = fam = gcarry = None
    groups_needed = (
        builder.groups.any_groups()
        or bool(scheduler.snapshot.have_pods_with_affinity_list)
        or bool(scheduler.snapshot
                .have_pods_with_required_anti_affinity_list))
    if groups_needed:
        gd_np, gc_np = builder.groups.build_dev(scheduler.snapshot)
        gd, gcarry = to_device(gd_np), to_device(gc_np)
        fam = builder.groups.families(scheduler.snapshot)
    carry = initial_carry(na, gcarry)
    bound = pod.spec.node_name or None
    self_excluded = False
    if bound:
        b = scheduler.state.node_index.get(bound)
        if b is not None:
            pi = PodInfo.of(pod)
            req = scheduler.state.rtable.vector(pi.requests)
            vec = np.zeros((int(carry.used.shape[1]),), np.int64)
            vec[:len(req)] = req
            carry = carry._replace(
                used=carry.used.at[b].add(-jnp.asarray(vec)),
                nonzero_used=carry.nonzero_used.at[b].add(
                    -jnp.asarray([pi.cpu_nonzero, pi.mem_nonzero],
                                 dtype=carry.nonzero_used.dtype)),
                npods=carry.npods.at[b].add(-1))
            self_excluded = True
    cfg = scheduler.profiles[pod.spec.scheduler_name].score_config \
        if pod.spec.scheduler_name in scheduler.profiles \
        else next(iter(scheduler.profiles.values())).score_config
    idx, totals, cols, n_feas = explain_row(cfg, na, carry, table, tidx,
                                            k=k, gd=gd, fam=fam)
    names = scheduler.state.node_names
    return _assemble(uid, "current_state", names, idx, totals, cols,
                     n_feas, bound,
                     {"selfExcluded": {"resources": self_excluded,
                                       "groups": False, "ports": False}},
                     k)


def explain_pod(scheduler, uid: str, k: int = 5) -> dict:
    """The /debug/explain entry: exact replay when the pod's drain is in
    the audit ledger, current-state decomposition otherwise."""
    k = max(1, min(int(k), 16))
    pod = None
    ps = scheduler.cache.pod_states.get(uid)
    if ps is not None:
        pod = ps.pod
    if pod is None:
        pod = getattr(scheduler.client, "pods", {}).get(uid)
    if pod is None:
        return {"pod": uid, "error": "pod not found"}
    audit = getattr(scheduler, "audit", None)
    if audit is not None:
        rec = audit.ledger.find_pod(uid)
        if (rec is not None and rec.explain_ctx is not None
                and rec.explain_ctx.assignments is not None):
            return _explain_exact(rec, uid, k)
    return _explain_current(scheduler, pod, uid, k)
