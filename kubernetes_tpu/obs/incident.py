"""Incident forensics: auto-captured evidence bundles for fleet breaches.

Every rail so far OBSERVES; nothing captures. When something goes wrong
across a shard handoff — a fenced-write storm during a steal, a shadow-
oracle divergence, an SLO ladder trip, a stalled pipeline — the evidence
is spread over N instances' ring buffers and ages out of them within
seconds. The IncidentWatchdog polls the fleet-level signals and, on a
breach, captures a BOUNDED evidence bundle to `incidentDir`:

- the federated SLO snapshot + the fleet view (per-member role/probe),
- each instance's flight-recorder window (last K drains),
- the stitched journeys of the implicated pods (cross-shard timelines),
- the kernel-observatory snapshot,
- each instance's audit-ledger slice WITH its hash-chain head and the
  handoff annex (chain heads across shard handoffs) — offline
  verifiable by `tools/incident_dump.py`, which exits 2 on any broken
  chain,
- the ShardMap version history (who owned what, when),
- per-instance pipeline occupancy stats.

Triggers are edge-detected (a persisting breach captures once, a new
breach signature captures again) and every capture increments
`scheduler_incidents_total{trigger}`. Retention is bounded: the oldest
bundles beyond `max_bundles` are deleted, so a flapping trigger cannot
fill the disk.
"""

from __future__ import annotations

import json
import os
import time as _time
from typing import Optional

from .journey import EV_PARK, EV_REQUEUE, EV_STEAL

# trigger label set of scheduler_incidents_total (pre-seeded; the
# exposition lint asserts this exact set)
TRIGGERS = ("slo_breach", "divergence", "fence_storm", "pipeline_stall")

BUNDLE_SCHEMA = "tpu-scheduler-incident/v1"


class IncidentWatchdog:
    """Poll fleet signals; capture evidence bundles on breach."""

    def __init__(self, fleet, stitcher, dirpath: str = "",
                 clock=None, metrics=None, manager=None,
                 max_bundles: int = 8, flight_limit: int = 64,
                 journey_limit: int = 32, audit_limit: int = 16,
                 fence_storm_threshold: int = 16,
                 stall_budget_s: float = 30.0):
        self.fleet = fleet
        self.stitcher = stitcher
        self.dirpath = dirpath
        self.clock = clock or _time.monotonic
        self.metrics = metrics
        self.manager = manager
        self.max_bundles = int(max_bundles)
        self.flight_limit = int(flight_limit)
        self.journey_limit = int(journey_limit)
        self.audit_limit = int(audit_limit)
        self.fence_storm_threshold = int(fence_storm_threshold)
        self.stall_budget_s = float(stall_budget_s)
        self.sequence = 0
        self.bundles: list[dict] = []      # in-memory ring (last capture)
        self._last_bundle: Optional[dict] = None   # full last bundle
        # edge-detection state
        self._seen_divergence = 0.0
        self._seen_fenced = 0.0
        self._breach_sig: frozenset = frozenset()
        self._stalled: set = set()

    # -- signal sampling ------------------------------------------------------

    def _sum_counter(self, attr: str) -> float:
        total = 0.0
        for name, role, sched in self.fleet._actives():
            metric = getattr(sched.metrics, attr, None)
            if metric is not None:
                total += sum(metric._values.values())
        return total

    def check(self) -> list[dict]:
        """Sample every trigger signal once; capture a bundle per newly
        breached trigger. Returns the captured bundle summaries."""
        captured = []
        # 1. federated SLO ladder trip (new breach signature only)
        breaches = self.fleet.federated_slo().breaches()
        sig = frozenset((b["sli"], b["window"]) for b in breaches)
        if sig and sig != self._breach_sig:
            captured.append(self.capture("slo_breach",
                                         {"breaches": breaches}))
        self._breach_sig = sig
        # 2. shadow-oracle divergence (any growth)
        div = self._sum_counter("oracle_divergence")
        if div > self._seen_divergence:
            captured.append(self.capture(
                "divergence", {"divergenceTotal": div,
                               "delta": div - self._seen_divergence}))
        self._seen_divergence = div
        # 3. fenced-write storm (threshold-many rejections since last check)
        fenced = self._sum_counter("fenced_writes_rejected")
        if fenced - self._seen_fenced >= self.fence_storm_threshold:
            captured.append(self.capture(
                "fence_storm", {"fencedTotal": fenced,
                                "delta": fenced - self._seen_fenced}))
        self._seen_fenced = fenced
        # 4. pipeline stall beyond budget (once per continuous stall)
        stalled_now = set()
        for name, role, sched in self.fleet._actives():
            pipe = getattr(sched, "pipeline", None)
            stall = pipe.stall_seconds() if pipe is not None else 0.0
            if stall > self.stall_budget_s:
                stalled_now.add(name)
                if name not in self._stalled:
                    captured.append(self.capture(
                        "pipeline_stall",
                        {"instance": name, "stallSeconds": stall}))
        self._stalled = stalled_now
        return captured

    # -- implicated pods ------------------------------------------------------

    def _implicated(self) -> list:
        """Bounded uid set for the journey slice: pods whose recent
        transitions are the kind incidents are made of — requeues
        (fence unwinds, bind errors), parks and steals — newest first
        across every instance's ring."""
        uids: dict = {}
        wanted = (EV_REQUEUE, EV_PARK, EV_STEAL)
        for name, ledger in self.stitcher.ledgers():
            if len(uids) >= self.journey_limit:
                break
            evs, ids = ledger._ev, ledger._uid
            for i in range(len(evs) - 1, -1, -1):
                if evs[i] in wanted and ids[i] not in uids:
                    uids[ids[i]] = True
                    if len(uids) >= self.journey_limit:
                        break
        return list(uids)

    # -- capture --------------------------------------------------------------

    def capture(self, trigger: str, signals: Optional[dict] = None) -> dict:
        """Capture one bounded evidence bundle for `trigger`; write it to
        incidentDir (when set), enforce retention, bump the counter.
        Returns the bundle summary {trigger, sequence, path}."""
        self.sequence += 1
        flight = {}
        audit = {}
        pipeline = {}
        for name, role, sched in self.fleet._resolve():
            rec = getattr(sched, "flight", None)
            if rec is not None:
                flight[name] = rec.dump(limit=self.flight_limit)
            aud = getattr(sched, "audit", None)
            ledger = getattr(aud, "ledger", None)
            if ledger is not None:
                audit[name] = {
                    "dump": ledger.dump(limit=self.audit_limit),
                    "handoffs": [dict(e) for e in ledger.handoffs],
                    "handoffHead": ledger.handoff_head,
                    "handoffsValid": ledger.verify_handoffs(),
                }
            pipe = getattr(sched, "pipeline", None)
            if pipe is not None:
                pipeline[name] = pipe.stats()
        uids = self._implicated()
        observatory = None
        for name, role, sched in self.fleet._actives():
            obs = getattr(sched, "observatory", None)
            if obs is not None and getattr(obs, "enabled", False):
                try:
                    observatory = obs.snapshot()
                except Exception:
                    observatory = None
            break
        shard_map = None
        if self.manager is not None:
            client = getattr(self.manager, "client", None)
            if client is not None and hasattr(client, "get_shard_map"):
                cur = client.get_shard_map()
                shard_map = {
                    "current": {"numShards": cur.num_shards,
                                "version": cur.version,
                                "assignments": dict(cur.assignments)},
                    "history": list(getattr(client, "shard_map_history",
                                            ())),
                }
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "trigger": trigger,
            "sequence": self.sequence,
            "capturedAt": round(self.clock(), 6),
            "signals": signals or {},
            "slo": self.fleet.slo_snapshot(),
            "fleet": self.fleet.fleet_view(),
            "flight": flight,
            "journeys": {uid: self.stitcher.pod(uid) for uid in uids},
            "observatory": observatory,
            "audit": audit,
            "shardMap": shard_map,
            "pipeline": pipeline,
        }
        summary = {"trigger": trigger, "sequence": self.sequence,
                   "path": self._write(bundle)}
        self.bundles.append(summary)
        del self.bundles[:-self.max_bundles]
        if self.metrics is not None:
            self.metrics.incidents.inc(trigger)
        return summary

    def _write(self, bundle: dict) -> str:
        if not self.dirpath:
            # in-memory only: keep the full bundle reachable for tests
            bundle_path = ""
            self._last_bundle = bundle
            return bundle_path
        os.makedirs(self.dirpath, exist_ok=True)
        name = f"incident-{bundle['sequence']:06d}-{bundle['trigger']}.json"
        path = os.path.join(self.dirpath, name)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=1, sort_keys=True, default=str)
        self._last_bundle = bundle
        # retention: bounded bundle count, oldest deleted first
        kept = sorted(fn for fn in os.listdir(self.dirpath)
                      if fn.startswith("incident-")
                      and fn.endswith(".json"))
        for fn in kept[:-self.max_bundles]:
            try:
                os.remove(os.path.join(self.dirpath, fn))
            except OSError:
                pass
        return path

    def debug(self) -> dict:
        return {"sequence": self.sequence,
                "dir": self.dirpath,
                "maxBundles": self.max_bundles,
                "recent": list(self.bundles),
                "stallBudgetSeconds": self.stall_budget_s,
                "fenceStormThreshold": self.fence_storm_threshold}
