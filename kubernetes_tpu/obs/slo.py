"""SLO engine: multi-window burn-rate tracking over the scheduler's SLIs.

Classic SRE burn-rate alerting (error budget consumption rate over
several look-back windows) applied to the drain pipeline. Each SLI is an
error RATIO stream — good/bad event counts fed from the scheduler's
existing observation sites:

  attempt_latency   drain attempts slower than the latency objective
  e2e_latency       queue→bind SLI durations beyond the e2e objective
  device_fallback   drains degraded off the device tier (faults, breaker)
  divergence        shadow-oracle audits that found ANY divergence
  gang_quorum_wait  gang quorum waits beyond the wait objective
  failover          HA takeovers slower than the failover objective

Events land in fixed-resolution time buckets (one shared ring per SLI);
each window's error rate is the bucket sum over its look-back, and

  burn_rate(sli, window) = error_rate / (1 - objective)

i.e. 1.0 = consuming exactly the error budget, >1 = burning it down.
Breach thresholds follow the standard multi-window ladder (fast burn on
the short window, slow burn on the long one); `breaches()` is what
`tools/bench_compare.py --slo` gates on at bench end.

Written by the scheduling thread and the audit worker, read by the
metrics scrape (`scheduler_slo_burn_rate{sli,window}` callback gauge)
and /debug/slo — one lock covers the rings.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

# (seconds, label) — the reference multi-window ladder
WINDOWS = ((300, "5m"), (3600, "1h"), (21600, "6h"))

# default breach thresholds per window (Google SRE workbook fast/slow
# burn ladder: 14.4x on the short window pages, 1x on the long window
# means the budget is exactly exhausted at period end)
DEFAULT_MAX_BURN = {"5m": 14.4, "1h": 6.0, "6h": 1.0}


@dataclass(frozen=True)
class Objective:
    """One SLI's objective: target good-fraction + latency bound."""

    objective: float                 # e.g. 0.99 → 1% error budget
    threshold_s: float = 0.0         # latency SLIs: bad when > threshold
    max_burn: dict = field(default_factory=lambda: dict(DEFAULT_MAX_BURN))

    @property
    def budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


DEFAULT_OBJECTIVES = {
    "attempt_latency": Objective(0.99, threshold_s=1.0),
    "e2e_latency": Objective(0.99, threshold_s=5.0),
    "device_fallback": Objective(0.999),
    "divergence": Objective(0.9999),
    "gang_quorum_wait": Objective(0.99, threshold_s=30.0),
    # HA takeover duration (ha/standby.py): a failover slower than the
    # threshold burns budget — the warm-standby contract is that takeover
    # costs a delta resync, not a cold LIST + tensorize + JIT warm-up
    "failover": Objective(0.99, threshold_s=30.0),
}


def parse_objectives(overrides: Optional[dict]) -> dict:
    """Config `sloObjectives` overrides → {sli: Objective}; unknown sli
    names and out-of-range objectives are rejected (config validation)."""
    out = dict(DEFAULT_OBJECTIVES)
    for sli, spec in (overrides or {}).items():
        base = out.get(sli)
        if base is None:
            raise ValueError(
                f"unknown SLI {sli!r} in sloObjectives (known: "
                f"{sorted(out)})")
        obj = float(spec.get("objective", base.objective))
        if not 0.0 < obj < 1.0:
            raise ValueError(f"sloObjectives[{sli!r}].objective must be "
                             "in (0, 1)")
        burn = dict(base.max_burn)
        for w, v in (spec.get("maxBurn") or {}).items():
            if w not in burn:
                raise ValueError(f"unknown burn window {w!r} (known: "
                                 f"{sorted(burn)})")
            burn[w] = float(v)
        out[sli] = Objective(
            objective=obj,
            threshold_s=float(spec.get("thresholdSeconds",
                                       base.threshold_s)),
            max_burn=burn)
    return out


def validate_objectives(overrides: Optional[dict]) -> None:
    parse_objectives(overrides)


class SLOEngine:
    """Per-SLI good/bad bucket rings + burn-rate evaluation."""

    BUCKET_S = 10.0

    def __init__(self, clock: Callable[[], float] = _time.monotonic,
                 objectives: Optional[dict] = None):
        self.clock = clock
        self.objectives = parse_objectives(objectives)
        self._lock = threading.Lock()
        # sli → list of [bucket_epoch, good, bad], oldest first, pruned
        # to the longest window on write
        self._buckets: dict[str, list] = {}   # guarded_by: _lock
        self._totals: dict[str, list] = {     # guarded_by: _lock
            sli: [0, 0] for sli in self.objectives}

    def threshold(self, sli: str) -> float:
        return self.objectives[sli].threshold_s

    # -- recording ------------------------------------------------------------

    def observe(self, sli: str, good: int = 0, bad: int = 0) -> None:
        if not good and not bad:
            return
        epoch = int(self.clock() / self.BUCKET_S)
        horizon = epoch - int(WINDOWS[-1][0] / self.BUCKET_S) - 1
        with self._lock:
            ring = self._buckets.setdefault(sli, [])
            if ring and ring[-1][0] == epoch:
                ring[-1][1] += good
                ring[-1][2] += bad
            else:
                ring.append([epoch, good, bad])
                while ring and ring[0][0] < horizon:
                    ring.pop(0)
            tot = self._totals.setdefault(sli, [0, 0])
            tot[0] += good
            tot[1] += bad

    # -- evaluation -----------------------------------------------------------

    def _rates(self) -> dict:
        """sli → {window: (good, bad)} over each look-back window."""
        now_epoch = int(self.clock() / self.BUCKET_S)
        with self._lock:
            rings = {sli: [tuple(b) for b in ring]
                     for sli, ring in self._buckets.items()}
        out: dict = {}
        for sli in self.objectives:
            ring = rings.get(sli, [])
            per = {}
            for secs, label in WINDOWS:
                lo = now_epoch - int(secs / self.BUCKET_S)
                good = bad = 0
                for epoch, g, b in ring:
                    if epoch > lo:
                        good += g
                        bad += b
                per[label] = (good, bad)
            out[sli] = per
        return out

    def burn_rates(self) -> dict:
        """sli → {window: burn rate} (0.0 with no traffic)."""
        out: dict = {}
        for sli, per in self._rates().items():
            budget = self.objectives[sli].budget
            out[sli] = {}
            for label, (good, bad) in per.items():
                total = good + bad
                rate = (bad / total) if total else 0.0
                out[sli][label] = rate / budget
        return out

    def breaches(self) -> list:
        """Every (sli, window) whose burn rate exceeds its configured
        threshold — the bench/alerting gate."""
        out = []
        for sli, per in self.burn_rates().items():
            burn_cfg = self.objectives[sli].max_burn
            for label, burn in per.items():
                if burn > burn_cfg.get(label, float("inf")):
                    out.append({"sli": sli, "window": label,
                                "burn": round(burn, 3),
                                "threshold": burn_cfg[label]})
        return out

    def gauge_callback(self) -> dict:
        """scheduler_slo_burn_rate{sli,window} values at scrape time."""
        return {(sli, label): burn
                for sli, per in self.burn_rates().items()
                for label, burn in per.items()}

    def snapshot(self, compact: bool = False) -> dict:
        """/debug/slo payload; `compact` is the bench-extras form."""
        with self._lock:
            totals = {sli: {"good": t[0], "bad": t[1]}
                      for sli, t in self._totals.items()}
        burns = self.burn_rates()
        breaches = self.breaches()
        if compact:
            return {
                "breaches": breaches,
                "divergence_bad": totals.get("divergence",
                                             {"bad": 0})["bad"],
                "max_burn": round(max((b for per in burns.values()
                                       for b in per.values()),
                                      default=0.0), 3),
            }
        return {
            "objectives": {
                sli: {"objective": o.objective,
                      "thresholdSeconds": o.threshold_s,
                      "maxBurn": dict(o.max_burn)}
                for sli, o in self.objectives.items()},
            "totals": totals,
            "burnRates": {sli: {w: round(b, 4) for w, b in per.items()}
                          for sli, per in burns.items()},
            "breaches": breaches,
        }
