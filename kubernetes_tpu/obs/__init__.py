"""Always-on verification & explainability layer (ISSUE 10).

Three parts, all served by the SchedulerServer's /debug endpoints:

- `audit.py` — shadow-oracle audit: a sampler captures deterministic
  replay records per drain into a hash-chained ledger, re-executes them
  through the host oracle on a background worker, and diffs assignments
  + FailedScheduling reason histograms (`oracle_divergence_total`).
- `explain.py` — decision provenance: per-bind plugin-level score
  decomposition (winner + top-k runners-up) via the `explain_row`
  device kernel, exact when the drain is in the audit ledger.
- `slo.py` — SLI streams through multi-window (5m/1h/6h) burn-rate
  tracking with configurable objectives (`scheduler_slo_burn_rate`),
  evaluated at bench end so `tools/bench_compare.py --slo` gates on
  breaches, not just throughput medians.
"""

from .federation import FleetAggregator  # noqa: F401
from .incident import IncidentWatchdog  # noqa: F401
from .slo import SLOEngine, validate_objectives  # noqa: F401
from .stitch import JourneyStitcher  # noqa: F401
