"""Telemetry federation: one cluster-level view over N scheduler shards.

PR 17 sharded the control plane; every rail (metrics, SLO burn, cluster
probe) stayed per-instance. The FleetAggregator pulls each member's
exposition / live metric objects — in-process today, but shaped exactly
like an HTTP scrape (text exposition in, labels injected) so the
cross-process step only swaps the transport — and merges them:

- **series**: every per-instance sample re-labeled with `shard` (the
  instance identity) and `role` (active/standby), concatenated into one
  fleet exposition. Histograms stay log2-bucketed, so the per-shard
  series merge losslessly via `Histogram.merged_counts` into
  cluster-level series.
- **SLO**: the fleet burns ONE error budget per SLI. Active members'
  burn-bucket rings merge epoch-wise into a federated SLOEngine, so
  `bench_compare --slo` gates the cluster's budget, not N private ones.
  Standby members are EXCLUDED: a warm standby tails the active's drain
  ledger, so its mirrored SLI streams would double-count every event
  (the ISSUE 19 bugfix — standbys still appear in the series view, with
  `role="standby"`, they just never contribute to the cluster burn).
- **probe**: the latest per-shard `cluster_probe` snapshots merge
  capacity-weighted (by each slice's valid-node count) into fleet-level
  fragmentation / stranded / imbalance indices — the trigger signal the
  defragmentation policy (ROADMAP item 3) will read — at /debug/fleet.
"""

from __future__ import annotations

import time as _time


class FleetAggregator:
    """Merge N instances' telemetry into one cluster view.

    `members` are ShardScheduler / StandbyScheduler / Scheduler-shaped:
    anything with `.scheduler` (or itself Scheduler-shaped) exposing
    `.metrics`, `.slo`, `.ha_role` and `._last_probe`."""

    def __init__(self, members=()):
        self._members = list(members)

    def add(self, member) -> None:
        self._members.append(member)

    def _resolve(self):
        """Yield (name, role, scheduler) per member. Role comes from the
        scheduler's HA lifecycle: a StandbyScheduler's inner Scheduler
        reports "standby" until promoted."""
        for i, m in enumerate(self._members):
            sched = getattr(m, "scheduler", m)
            if getattr(sched, "metrics", None) is None:
                continue
            ledger = getattr(sched, "journey", None)
            name = ((ledger.instance if ledger is not None else "")
                    or getattr(m, "identity", "") or f"instance-{i}")
            yield name, getattr(sched, "ha_role", "active"), sched

    def _actives(self):
        return [(n, r, s) for n, r, s in self._resolve() if r != "standby"]

    # -- federated series (scrape-shaped) -------------------------------------

    @staticmethod
    def _inject_labels(text: str, extra: str, samples: list,
                       headers: dict) -> None:
        """Re-label one instance's exposition text: every sample line
        gains the `extra` labels; HELP/TYPE headers are collected once
        per family. This is the scrape-side half of federation — the
        cross-process step feeds the same function from HTTP bodies."""
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("#"):
                # "# HELP name ..." / "# TYPE name ..."
                parts = line.split(None, 3)
                if len(parts) >= 3:
                    headers.setdefault((parts[2], parts[1]), line)
                continue
            name, brace, rest = line.partition("{")
            if brace:
                samples.append(f"{name}{{{extra},{rest}")
            else:
                metric, _, value = line.partition(" ")
                samples.append(f"{metric}{{{extra}}} {value}")

    def exposition(self) -> str:
        """One fleet exposition: every member's samples with shard/role
        labels injected, HELP/TYPE emitted once per family. Standby
        members ARE included here (labeled role="standby") — exclusion
        only applies to the cluster SLO burn and cluster-level merges,
        where a mirrored series would double-count."""
        samples: list = []
        headers: dict = {}
        for name, role, sched in self._resolve():
            self._inject_labels(
                sched.metrics.exposition(),
                f'shard="{name}",role="{role}"', samples, headers)
        return "\n".join(list(headers.values()) + samples) + "\n"

    def cluster_series(self) -> dict:
        """Cluster-level merged series over ACTIVE members: counters and
        gauges sum per label set; histograms merge each instance's
        log2-bucket counts via `Histogram.merged_counts` (identical
        bucket layout per family by construction — same registry code)."""
        from ..metrics import Counter, Gauge, Histogram
        counters: dict = {}
        histograms: dict = {}
        for name, role, sched in self._actives():
            sched.metrics.sync_compile_ledger()
            sched.metrics.sync_observatory()
            for fam, metric in sched.metrics.registry._metrics.items():
                if isinstance(metric, Histogram):
                    agg = histograms.setdefault(fam, {
                        "buckets": list(metric.buckets),
                        "counts": [0] * (len(metric.buckets) + 1),
                        "sum": 0.0, "count": 0, "shards": 0})
                    for i, c in enumerate(metric.merged_counts()):
                        agg["counts"][i] += c
                    agg["sum"] += sum(metric._sums.values())
                    agg["count"] += sum(metric._totals.values())
                    agg["shards"] += 1
                elif isinstance(metric, (Counter, Gauge)):
                    values = (metric.callback()
                              if getattr(metric, "callback", None)
                              is not None else metric._values)
                    dst = counters.setdefault(fam, {})
                    for key, v in values.items():
                        dst[key] = dst.get(key, 0.0) + v
        return {"counters": counters, "histograms": histograms}

    # -- federated SLO burn ---------------------------------------------------

    def federated_slo(self):
        """ONE SLOEngine over the fleet: active members' burn-bucket
        rings merged epoch-wise (all in-process engines share a clock,
        so epochs align; the cross-process step aligns scrape clocks).
        Standbys are excluded — their SLI streams mirror the active's."""
        from .slo import SLOEngine
        actives = self._actives()
        base = actives[0][2] if actives else None
        eng = SLOEngine(clock=(base.slo.clock if base is not None
                               else _time.monotonic))
        if base is not None:
            eng.objectives = dict(base.slo.objectives)
            eng._totals = {sli: [0, 0] for sli in eng.objectives}
        merged: dict = {}
        for name, role, sched in actives:
            with sched.slo._lock:
                rings = {sli: [tuple(b) for b in ring]
                         for sli, ring in sched.slo._buckets.items()}
                totals = {sli: tuple(t)
                          for sli, t in sched.slo._totals.items()}
            for sli, ring in rings.items():
                dst = merged.setdefault(sli, {})
                for epoch, good, bad in ring:
                    cell = dst.setdefault(epoch, [epoch, 0, 0])
                    cell[1] += good
                    cell[2] += bad
            for sli, (good, bad) in totals.items():
                tot = eng._totals.setdefault(sli, [0, 0])
                tot[0] += good
                tot[1] += bad
        eng._buckets = {sli: [dst[e] for e in sorted(dst)]
                        for sli, dst in merged.items()}
        return eng

    def slo_snapshot(self, compact: bool = False) -> dict:
        return self.federated_slo().snapshot(compact=compact)

    # -- federated cluster probe ----------------------------------------------

    def fleet_probe(self) -> dict:
        """Capacity-weighted merge of the latest per-shard cluster_probe
        snapshots: fleet frag/stranded/utilization indices weighted by
        each slice's valid-node count, domain imbalance likewise."""
        shards: dict = {}
        res_acc: dict = {}
        dom_acc: dict = {}
        total_w = 0
        for name, role, sched in self._actives():
            probe = getattr(sched, "_last_probe", None)
            if not probe:
                continue
            w = int(probe.get("validNodes", 0)) or 1
            shards[name] = probe
            total_w += w
            for rname, stats in (probe.get("resources") or {}).items():
                dst = res_acc.setdefault(rname, {})
                for stat, v in stats.items():
                    dst[stat] = dst.get(stat, 0.0) + w * float(v)
            for stat, v in (probe.get("domains") or {}).items():
                dom_acc[stat] = dom_acc.get(stat, 0.0) + w * float(v)
        if not total_w:
            return {"validNodes": 0, "shards": {}}
        return {
            "validNodes": total_w,
            "resources": {rname: {stat: round(v / total_w, 6)
                                  for stat, v in stats.items()}
                          for rname, stats in res_acc.items()},
            "domains": {stat: round(v / total_w, 6)
                        for stat, v in dom_acc.items()},
            "shards": shards,
        }

    # -- /debug/fleet ---------------------------------------------------------

    def fleet_view(self) -> dict:
        members = {}
        for name, role, sched in self._resolve():
            members[name] = {
                "role": role,
                "journey": sched.journey.stats(),
                "slo": sched.slo.snapshot(compact=True),
                "probe": getattr(sched, "_last_probe", None),
            }
        return {
            "members": members,
            "slo": self.slo_snapshot(compact=True),
            "probe": self.fleet_probe(),
        }
