"""Fluent test builders (reference: pkg/scheduler/testing/wrappers.go
`st.MakePod()` / `st.MakeNode()`)."""

from __future__ import annotations

import itertools
from typing import Optional

from ..api import resources as res
from ..api.types import (Affinity, Container, ContainerPort, LabelSelector,
                         LabelSelectorRequirement, Node, NodeAffinity,
                         NodeSelector, NodeSelectorTerm, NodeSpec, NodeStatus,
                         ObjectMeta, Pod, PodAffinity, PodAffinityTerm,
                         PodAntiAffinity, PodSchedulingGate, PodSpec,
                         PodStatus, PreferredSchedulingTerm, Taint,
                         Toleration, TopologySpreadConstraint,
                         WeightedPodAffinityTerm)

_counter = itertools.count()


class PodWrapper:
    def __init__(self, name: str = "", namespace: str = "default"):
        idx = next(_counter)
        self.pod = Pod(
            metadata=ObjectMeta(name=name or f"pod-{idx}", namespace=namespace,
                                creation_index=idx),
            spec=PodSpec(containers=[Container(name="c0")]),
            status=PodStatus(),
        )

    def obj(self) -> Pod:
        return self.pod

    def name(self, n: str) -> "PodWrapper":
        self.pod.metadata.name = n
        self.pod.metadata.uid = f"{self.pod.metadata.namespace}/{n}"
        return self

    def namespace(self, ns: str) -> "PodWrapper":
        self.pod.metadata.namespace = ns
        self.pod.metadata.uid = f"{ns}/{self.pod.metadata.name}"
        return self

    def uid(self, uid: str) -> "PodWrapper":
        self.pod.metadata.uid = uid
        return self

    def label(self, k: str, v: str) -> "PodWrapper":
        self.pod.metadata.labels[k] = v
        return self

    def labels(self, d: dict[str, str]) -> "PodWrapper":
        self.pod.metadata.labels.update(d)
        return self

    def req(self, requests: dict[str, str | int]) -> "PodWrapper":
        """st.MakePod().Req(...): sets container 0 requests."""
        self.pod.spec.containers[0].requests = res.parse_resource_dict(requests)
        return self

    def container(self, requests: dict[str, str | int], image: str = "") -> "PodWrapper":
        self.pod.spec.containers.append(
            Container(name=f"c{len(self.pod.spec.containers)}",
                      requests=res.parse_resource_dict(requests), image=image))
        return self

    def init_req(self, requests: dict[str, str | int]) -> "PodWrapper":
        self.pod.spec.init_containers.append(
            Container(name=f"init{len(self.pod.spec.init_containers)}",
                      requests=res.parse_resource_dict(requests)))
        return self

    def overhead(self, requests: dict[str, str | int]) -> "PodWrapper":
        self.pod.spec.overhead = res.parse_resource_dict(requests)
        return self

    def node(self, node_name: str) -> "PodWrapper":
        self.pod.spec.node_name = node_name
        return self

    def priority(self, p: int) -> "PodWrapper":
        self.pod.spec.priority = p
        return self

    def scheduler_name(self, n: str) -> "PodWrapper":
        self.pod.spec.scheduler_name = n
        return self

    def node_selector(self, sel: dict[str, str]) -> "PodWrapper":
        self.pod.spec.node_selector = dict(sel)
        return self

    def toleration(self, key: str = "", operator: str = "Equal", value: str = "",
                   effect: str = "") -> "PodWrapper":
        self.pod.spec.tolerations.append(
            Toleration(key=key, operator=operator, value=value, effect=effect))
        return self

    def host_port(self, port: int, protocol: str = "TCP", ip: str = "") -> "PodWrapper":
        c = self.pod.spec.containers[0]
        self.pod.spec.containers[0] = Container(
            name=c.name, requests=c.requests, limits=c.limits, image=c.image,
            ports=c.ports + (ContainerPort(host_port=port, protocol=protocol, host_ip=ip),))
        return self

    def scheduling_gate(self, name: str) -> "PodWrapper":
        self.pod.spec.scheduling_gates.append(PodSchedulingGate(name))
        return self

    def pvc(self, claim_name: str, volume_name: str = "") -> "PodWrapper":
        from ..api.types import Volume
        self.pod.spec.volumes.append(Volume(
            name=volume_name or f"vol-{len(self.pod.spec.volumes)}",
            claim_name=claim_name))
        return self

    def csi_volume(self, driver: str) -> "PodWrapper":
        from ..api.types import Volume
        self.pod.spec.volumes.append(Volume(
            name=f"vol-{len(self.pod.spec.volumes)}", csi_driver=driver))
        return self

    def require_features(self, *features: str) -> "PodWrapper":
        self.pod.spec.required_node_features = tuple(features)
        return self

    def claim(self, *names: str) -> "PodWrapper":
        """DRA: reference ResourceClaims by name (same namespace)."""
        self.pod.spec.resource_claims = self.pod.spec.resource_claims + names
        return self

    def workload(self, ref: str) -> "PodWrapper":
        self.pod.spec.workload_ref = ref
        return self

    def _ensure_affinity(self) -> Affinity:
        if self.pod.spec.affinity is None:
            self.pod.spec.affinity = Affinity()
        return self.pod.spec.affinity

    def node_affinity_in(self, key: str, values: list[str]) -> "PodWrapper":
        aff = self._ensure_affinity()
        term = NodeSelectorTerm(match_expressions=(
            LabelSelectorRequirement(key, "In", tuple(values)),))
        na = aff.node_affinity or NodeAffinity()
        existing = na.required.terms if na.required else ()
        self.pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(required=NodeSelector(existing + (term,)),
                                       preferred=na.preferred),
            pod_affinity=aff.pod_affinity, pod_anti_affinity=aff.pod_anti_affinity)
        return self

    def preferred_node_affinity_in(self, key: str, values: list[str], weight: int) -> "PodWrapper":
        aff = self._ensure_affinity()
        term = PreferredSchedulingTerm(weight, NodeSelectorTerm(match_expressions=(
            LabelSelectorRequirement(key, "In", tuple(values)),)))
        na = aff.node_affinity or NodeAffinity()
        self.pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(required=na.required,
                                       preferred=na.preferred + (term,)),
            pod_affinity=aff.pod_affinity, pod_anti_affinity=aff.pod_anti_affinity)
        return self

    def pod_affinity(self, topology_key: str, labels: dict[str, str],
                     anti: bool = False, namespaces: tuple[str, ...] = ()) -> "PodWrapper":
        aff = self._ensure_affinity()
        term = PodAffinityTerm(topology_key=topology_key,
                               label_selector=LabelSelector.of(labels),
                               namespaces=namespaces)
        if anti:
            pa = aff.pod_anti_affinity or PodAntiAffinity()
            new = PodAntiAffinity(required=pa.required + (term,), preferred=pa.preferred)
            self.pod.spec.affinity = Affinity(aff.node_affinity, aff.pod_affinity, new)
        else:
            pa = aff.pod_affinity or PodAffinity()
            new = PodAffinity(required=pa.required + (term,), preferred=pa.preferred)
            self.pod.spec.affinity = Affinity(aff.node_affinity, new, aff.pod_anti_affinity)
        return self

    def preferred_pod_affinity(self, topology_key: str, labels: dict[str, str],
                               weight: int, anti: bool = False) -> "PodWrapper":
        aff = self._ensure_affinity()
        wterm = WeightedPodAffinityTerm(weight, PodAffinityTerm(
            topology_key=topology_key, label_selector=LabelSelector.of(labels)))
        if anti:
            pa = aff.pod_anti_affinity or PodAntiAffinity()
            new = PodAntiAffinity(required=pa.required, preferred=pa.preferred + (wterm,))
            self.pod.spec.affinity = Affinity(aff.node_affinity, aff.pod_affinity, new)
        else:
            pa = aff.pod_affinity or PodAffinity()
            new = PodAffinity(required=pa.required, preferred=pa.preferred + (wterm,))
            self.pod.spec.affinity = Affinity(aff.node_affinity, new, aff.pod_anti_affinity)
        return self

    def spread_constraint(self, max_skew: int, topology_key: str,
                          when_unsatisfiable: str, labels: dict[str, str],
                          min_domains: Optional[int] = None) -> "PodWrapper":
        self.pod.spec.topology_spread_constraints.append(TopologySpreadConstraint(
            max_skew=max_skew, topology_key=topology_key,
            when_unsatisfiable=when_unsatisfiable,
            label_selector=LabelSelector.of(labels), min_domains=min_domains))
        return self


class NodeWrapper:
    def __init__(self, name: str = ""):
        idx = next(_counter)
        self.node_obj = Node(metadata=ObjectMeta(name=name or f"node-{idx}",
                                                 creation_index=idx))
        self.capacity({"cpu": "32", "memory": "64Gi", "pods": 110,
                       "ephemeral-storage": "100Gi"})

    def obj(self) -> Node:
        return self.node_obj

    def name(self, n: str) -> "NodeWrapper":
        self.node_obj.metadata.name = n
        self.node_obj.metadata.uid = f"/{n}"
        return self

    def label(self, k: str, v: str) -> "NodeWrapper":
        self.node_obj.metadata.labels[k] = v
        return self

    def capacity(self, caps: dict[str, str | int]) -> "NodeWrapper":
        parsed = res.parse_resource_dict(caps)
        self.node_obj.status.capacity.update(parsed)
        self.node_obj.status.allocatable.update(parsed)
        return self

    def allocatable(self, caps: dict[str, str | int]) -> "NodeWrapper":
        self.node_obj.status.allocatable.update(res.parse_resource_dict(caps))
        return self

    def taint(self, key: str, value: str = "", effect: str = "NoSchedule") -> "NodeWrapper":
        self.node_obj.spec.taints.append(Taint(key=key, value=value, effect=effect))
        return self

    def declare_features(self, *features: str) -> "NodeWrapper":
        self.node_obj.status.declared_features = tuple(features)
        return self

    def unschedulable(self, v: bool = True) -> "NodeWrapper":
        self.node_obj.spec.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "NodeWrapper":
        from ..api.types import ContainerImage
        self.node_obj.status.images.append(
            ContainerImage(names=(name,), size_bytes=size_bytes))
        return self

    def zone(self, zone: str) -> "NodeWrapper":
        return self.label("topology.kubernetes.io/zone", zone)


def make_pod(name: str = "", namespace: str = "default") -> PodWrapper:
    return PodWrapper(name, namespace)


def make_node(name: str = "") -> NodeWrapper:
    return NodeWrapper(name)
