from .chaos import ChaosAPIServer, ChaosConfig  # noqa: F401
