"""Seeded fault injection around the in-memory API server.

`ChaosAPIServer` wraps `backend/apiserver.APIServer` and injects, from a
seeded RNG with per-verb probabilities, the failure modes the reference
tolerates every day (and the resilient commit pipeline must absorb):

- transient errors (`ServerTimeout` / `TooManyRequests`) raised BEFORE the
  call takes effect — the retriable class the dispatcher must retry;
- Conflict storms on bind — the terminal class that must route through
  the forget/requeue path;
- added latency (via an injectable `sleep`, a no-op by default so tests
  stay fast while the injected total is still recorded);
- dropped / duplicated watch events on the pod and node streams — the
  watch-loss scenario `Scheduler.resync()` recovers from;
- node flaps: a random node deleted and immediately re-created between
  API calls (delete + add events both fan out), mid-batch from the
  scheduler's point of view;
- lease chaos (ISSUE 12): expired-lease storms (the held lease's
  renewTime is aged so any candidate's next acquire wins), stolen leases
  mid-renew (holder swapped to a chaos thief between the elector's read
  and its renew — the Conflict path), renew latency spikes (injected via
  `sleep`, so a FakeClock-wired sleep pushes the elector past its renew
  deadline) and a clock-skew knob added to the timestamp the API server
  sees, so the election loop is chaos-covered like every other verb.

- shard-aware targeting (ISSUE 17): `target_leases`/`target_identities`
  scope the lease chaos to one shard while peers stay healthy,
  `lease_storm()` fires a deterministic expiry/steal strike across all N
  shard leases, and `for_identity()` returns a per-client view applying
  asymmetric latency to one shard scheduler's verbs
  (`identity_latency`), with per-lease/per-identity counters exported.

Determinism: every injection draws from ONE `random.Random(seed)`, so a
given (seed, workload, call sequence) replays the same fault script —
that's what makes the chaos parity soak a correctness gate instead of a
flaky stress test. Injection counters (`injected_errors`,
`injected_conflicts`, `dropped_events`, `duplicated_events`,
`node_flaps`, `injected_latency_total`) let tests assert faults actually
fired.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..backend.apiserver import (APIServer, Conflict, LEASE_NAME,
                                 ServerTimeout, TooManyRequests,
                                 WatchHandlers)

# verbs accepted in ChaosConfig.error_rates
VERBS = ("create", "update", "bind", "patch", "delete",
         "lease_acquire", "lease_renew", "lease_release")


@dataclass
class ChaosConfig:
    seed: int = 0
    # per-verb transient-error probability (ServerTimeout/TooManyRequests,
    # raised before the call takes effect): {"bind": 0.05, ...}
    error_rates: dict[str, float] = field(default_factory=dict)
    # Conflict storm probability on bind (terminal: forget/requeue path)
    conflict_rate: float = 0.0
    # added latency: probability per call, and the delay range drawn
    latency_rate: float = 0.0
    latency_seconds: tuple[float, float] = (0.001, 0.01)
    # watch-stream chaos on pod/node events
    drop_watch_rate: float = 0.0
    dup_watch_rate: float = 0.0
    # per-API-call probability of a node flap (delete + re-create)
    node_flap_rate: float = 0.0
    # lease chaos (ISSUE 12): probability per acquire/renew that the held
    # lease's renewTime is aged past its duration (expired-lease storm)
    lease_expire_rate: float = 0.0
    # probability per renew that the lease is stolen mid-renew (holder
    # swapped under the elector → Conflict on its renew)
    lease_steal_rate: float = 0.0
    # renew latency spikes: probability + delay range, injected via the
    # facade's `sleep` (wire it to a FakeClock to push an elector past
    # its renew deadline deterministically)
    renew_latency_rate: float = 0.0
    renew_latency_seconds: tuple[float, float] = (0.0, 0.0)
    # shard-aware targeting (ISSUE 17): scope the lease expire/steal
    # chaos to these lease names and/or holder identities (empty = all),
    # so the matrix can aim a storm at ONE shard while peers stay healthy
    target_leases: tuple = ()
    target_identities: tuple = ()
    # asymmetric per-client latency: identity -> (rate, lo_s, hi_s),
    # applied through for_identity() views — one slow shard client while
    # the rest of the fleet sees the base fault script
    identity_latency: dict[str, tuple] = field(default_factory=dict)
    # constant skew added to the timestamp the HOLDER's renews record
    # (fresh acquires use the candidate's true clock): a negative skew
    # models a leader whose clock lags — its renewTimes land in the
    # past, so candidates see the lease expire early. The two-clocks
    # problem leases exist to tolerate; skewing every verb identically
    # would cancel out.
    clock_skew_s: float = 0.0

    def validate(self) -> None:
        unknown = set(self.error_rates) - set(VERBS)
        if unknown:
            raise ValueError(f"unknown chaos verbs {sorted(unknown)} "
                             f"(known: {list(VERBS)})")


class ChaosAPIServer:
    """Fault-injecting facade; every attribute not overridden here
    forwards to the wrapped server, so the scheduler (and the cache
    debugger) sees the same surface."""

    def __init__(self, inner: Optional[APIServer] = None,
                 config: Optional[ChaosConfig] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        self.inner = inner if inner is not None else APIServer()
        self.cfg = config or ChaosConfig()
        self.cfg.validate()
        self.rng = random.Random(self.cfg.seed)
        # default sleep is a no-op: tests measure the injected total
        # instead of burning wall clock; pass time.sleep for realism
        self.sleep = sleep or (lambda _s: None)
        self.injected_errors: dict[str, int] = {v: 0 for v in VERBS}
        self.injected_conflicts = 0
        self.dropped_events = 0
        self.duplicated_events = 0
        self.node_flaps = 0
        self.injected_latency_total = 0.0
        self.lease_expirations = 0
        self.lease_steals = 0
        self.renew_latency_spikes = 0
        # shard-aware counters (ISSUE 17)
        self.lease_events_by_name: dict[str, int] = {}
        self.identity_latency_total: dict[str, float] = {}

    def __getattr__(self, name: str):
        return getattr(self.inner, name)

    # -- injection core -------------------------------------------------------

    def _maybe_flap(self) -> None:
        cfg = self.cfg
        if (cfg.node_flap_rate and self.inner.nodes
                and self.rng.random() < cfg.node_flap_rate):
            self.flap_node(self.rng.choice(sorted(self.inner.nodes)))

    def flap_node(self, name: str) -> None:
        """Delete + immediately re-create a node: both watch events fan
        out (cache remove + add, device-state invalidation) but the store
        is consistent again before the next verb executes."""
        node = self.inner.nodes[name]
        self.inner.delete_node(name)
        self.inner.create_node(node)
        self.node_flaps += 1

    def _inject(self, verb: str) -> None:
        """Run the fault script for one API call; raises the injected
        error (before the call takes effect) or returns."""
        cfg = self.cfg
        self._maybe_flap()
        if cfg.latency_rate and self.rng.random() < cfg.latency_rate:
            lo, hi = cfg.latency_seconds
            d = lo + (hi - lo) * self.rng.random()
            self.injected_latency_total += d
            self.sleep(d)
        p = cfg.error_rates.get(verb, 0.0)
        if p and self.rng.random() < p:
            self.injected_errors[verb] += 1
            cls = ServerTimeout if self.rng.random() < 0.5 else TooManyRequests
            raise cls(f"chaos: injected transient error on {verb}")
        if verb == "bind" and cfg.conflict_rate \
                and self.rng.random() < cfg.conflict_rate:
            self.injected_conflicts += 1
            raise Conflict("chaos: injected conflict storm")

    # -- watch chaos ----------------------------------------------------------

    def _wrap_handlers(self, h: WatchHandlers) -> WatchHandlers:
        cfg = self.cfg
        if not cfg.drop_watch_rate and not cfg.dup_watch_rate:
            return h

        def mk(cb):
            if cb is None:
                return None

            def chaotic(*args):
                if cfg.drop_watch_rate \
                        and self.rng.random() < cfg.drop_watch_rate:
                    self.dropped_events += 1
                    return
                cb(*args)
                if cfg.dup_watch_rate \
                        and self.rng.random() < cfg.dup_watch_rate:
                    self.duplicated_events += 1
                    cb(*args)
            return chaotic

        # bulk adds stay intact: they are the ingest fast path, and the
        # per-pod stream already gives drop/dup coverage
        return WatchHandlers(on_add=mk(h.on_add), on_update=mk(h.on_update),
                             on_delete=mk(h.on_delete),
                             on_add_bulk=h.on_add_bulk)

    def watch_pods(self, h: WatchHandlers) -> None:
        self.inner.watch_pods(self._wrap_handlers(h))

    def watch_nodes(self, h: WatchHandlers) -> None:
        self.inner.watch_nodes(self._wrap_handlers(h))

    # -- injected verbs -------------------------------------------------------

    def create_pod(self, pod):
        self._inject("create")
        return self.inner.create_pod(pod)

    def create_pods(self, pods):
        self._inject("create")
        return self.inner.create_pods(pods)

    def update_pod(self, pod):
        self._inject("update")
        return self.inner.update_pod(pod)

    def delete_pod(self, uid: str, fence_token=None):
        self._inject("delete")
        return self.inner.delete_pod(uid, fence_token=fence_token)

    def bind(self, pod, node_name: str, fence_token=None):
        self._inject("bind")
        return self.inner.bind(pod, node_name, fence_token=fence_token)

    def bind_all(self, pairs, fence_token=None):
        """Per-pair injection: the injected subset fails (transient or
        conflict), the rest passes through to the real bulk bind."""
        self._maybe_flap()
        cfg = self.cfg
        failures = []
        pass_through = []
        for pair in pairs:
            p = cfg.error_rates.get("bind", 0.0)
            if p and self.rng.random() < p:
                self.injected_errors["bind"] += 1
                cls = (ServerTimeout if self.rng.random() < 0.5
                       else TooManyRequests)
                failures.append((pair[0], cls(
                    "chaos: injected transient error on bind")))
            elif cfg.conflict_rate \
                    and self.rng.random() < cfg.conflict_rate:
                self.injected_conflicts += 1
                failures.append((pair[0], Conflict(
                    "chaos: injected conflict storm")))
            else:
                pass_through.append(pair)
        if pass_through:
            failures.extend(self.inner.bind_all(pass_through,
                                                fence_token=fence_token))
        return failures

    def patch_pod_status(self, pod, condition, nominated_node_name=None,
                         fence_token=None):
        self._inject("patch")
        return self.inner.patch_pod_status(pod, condition,
                                           nominated_node_name,
                                           fence_token=fence_token)

    # -- lease chaos (ISSUE 12) -----------------------------------------------

    def _lease_chaos(self, name: str, renewing: bool = False) -> None:
        """Age or steal the held lease between the elector's read and
        its write — the races a real coordination API exposes."""
        cfg = self.cfg
        lease = self.inner.get_lease(name)
        if lease is None or not lease.holder_identity:
            return
        if not self._targeted(name, lease.holder_identity):
            return
        if cfg.lease_expire_rate \
                and self.rng.random() < cfg.lease_expire_rate:
            lease.renew_time -= lease.lease_duration_s + 1.0
            self.lease_expirations += 1
            self._count_lease_event(name)
        if renewing and cfg.lease_steal_rate \
                and self.rng.random() < cfg.lease_steal_rate:
            # a rogue holder claimed the lease mid-renew: the elector's
            # renew hits Conflict; the thief never renews, so the real
            # candidates recover after expiry (and the generation bump
            # fences any write stamped before the steal)
            self.lease_steals += 1
            lease.lease_transitions += 1
            lease.generation += 1
            lease.holder_identity = f"chaos-thief-{self.lease_steals}"
            self._count_lease_event(name)

    # -- shard-aware targeting (ISSUE 17) -------------------------------------

    def _targeted(self, name: str, identity: str) -> bool:
        cfg = self.cfg
        if cfg.target_leases and name not in cfg.target_leases:
            return False
        if cfg.target_identities and identity not in cfg.target_identities:
            return False
        return True

    def _count_lease_event(self, name: str) -> None:
        self.lease_events_by_name[name] = \
            self.lease_events_by_name.get(name, 0) + 1

    def lease_storm(self, names=None, steal: bool = False) -> int:
        """Deterministically expire (or steal) leases NOW — a seeded
        storm across all N shard leases, honoring the targeting config.
        Returns how many leases were hit. The per-call rate knobs model
        background weather; this is the directed lightning strike the
        shard-lifecycle matrix schedules between phases."""
        hit = 0
        pool = sorted(names if names is not None else self.inner.leases)
        for name in pool:
            lease = self.inner.get_lease(name)
            if lease is None or not lease.holder_identity:
                continue
            if not self._targeted(name, lease.holder_identity):
                continue
            if steal:
                self.lease_steals += 1
                lease.lease_transitions += 1
                lease.generation += 1
                lease.holder_identity = f"chaos-thief-{self.lease_steals}"
            else:
                lease.renew_time -= lease.lease_duration_s + 1.0
                self.lease_expirations += 1
            self._count_lease_event(name)
            hit += 1
        return hit

    def _identity_latency(self, identity: str) -> None:
        spec = self.cfg.identity_latency.get(identity)
        if not spec:
            return
        rate, lo, hi = spec
        if rate and self.rng.random() < rate:
            d = lo + (hi - lo) * self.rng.random()
            self.identity_latency_total[identity] = \
                self.identity_latency_total.get(identity, 0.0) + d
            self.injected_latency_total += d
            self.sleep(d)

    def for_identity(self, identity: str) -> "ChaosClientView":
        """A per-client view of this facade: same seeded fault script,
        plus the asymmetric latency configured for `identity`. Hand each
        shard scheduler its own view to model one slow shard client."""
        return ChaosClientView(self, identity)

    def _renew_spike(self) -> None:
        cfg = self.cfg
        if cfg.renew_latency_rate \
                and self.rng.random() < cfg.renew_latency_rate:
            lo, hi = cfg.renew_latency_seconds
            d = lo + (hi - lo) * self.rng.random()
            self.renew_latency_spikes += 1
            self.injected_latency_total += d
            self.sleep(d)

    def get_lease(self, name: str = LEASE_NAME):
        return self.inner.get_lease(name)

    def acquire_lease(self, name, identity, now, lease_duration_s=15.0):
        # the elector renews through acquire (same-identity fast path),
        # so a renew-shaped acquire gets the renew chaos: latency spikes
        # and mid-renew steals, not just acquire-time errors
        lease = self.inner.get_lease(name)
        renewing = lease is not None and lease.holder_identity == identity
        if renewing:
            self._renew_spike()
        self._inject("lease_renew" if renewing else "lease_acquire")
        self._lease_chaos(name, renewing=renewing)
        skew = self.cfg.clock_skew_s if renewing else 0.0
        return self.inner.acquire_lease(
            name, identity, now + skew,
            lease_duration_s=lease_duration_s)

    def renew_lease(self, name, identity, now):
        self._renew_spike()
        self._inject("lease_renew")
        self._lease_chaos(name, renewing=True)
        return self.inner.renew_lease(name, identity,
                                      now + self.cfg.clock_skew_s)

    def release_lease(self, name, identity):
        self._inject("lease_release")
        return self.inner.release_lease(name, identity)


class ChaosClientView:
    """One client identity's window onto a shared ChaosAPIServer: every
    mutating verb first pays that identity's asymmetric latency (config
    identity_latency), then runs the shared seeded fault script. Reads,
    watch registration, and every other attribute forward untouched — a
    scheduler constructed against a view sees the full client surface."""

    _LATENCY_VERBS = frozenset((
        "create_pod", "create_pods", "update_pod", "delete_pod",
        "bind", "bind_all", "patch_pod_status",
        "acquire_lease", "renew_lease", "release_lease"))

    def __init__(self, chaos: ChaosAPIServer, identity: str):
        # avoid __setattr__/__getattr__ recursion via object.__setattr__
        object.__setattr__(self, "chaos", chaos)
        object.__setattr__(self, "identity", identity)

    def __getattr__(self, name: str):
        attr = getattr(self.chaos, name)
        if name in self._LATENCY_VERBS:
            chaos, identity = self.chaos, self.identity

            def with_latency(*args, **kw):
                chaos._identity_latency(identity)
                return attr(*args, **kw)
            return with_latency
        return attr
