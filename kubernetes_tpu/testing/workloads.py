"""Trace-driven gang workload generator: the LLM-traffic suite.

Real TPU traffic is gang-shaped (ROADMAP item 3): LLM training jobs that
need topology-contiguous slices, co-located inference pods sharing the
cluster, and priority preemption of gangs by gangs (Topology-aware
Preemptive Scheduling for Co-located LLM Workloads, arXiv:2411.11560).
This module stamps that traffic shape DETERMINISTICALLY (seeded RNG) so
benches (`bench.py` GangTraining / CoLocatedInference via the harness's
`gangTrace` opcode), chaos soaks and the gang parity tests all draw from
one scenario library.

Gang members share their prototype's spec OBJECT (api/types.py aliasing
contract), which is what makes the builder's identity signature cache hit
— a 512-member training gang is one signature row, one device surface.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from ..api.types import ObjectMeta, Pod, PodGroup, PodStatus, Workload, _shallow
from .wrappers import _counter, make_pod


@dataclass(frozen=True)
class GangSpec:
    """One gang's shape: `ref` is the workload ref its members carry."""

    name: str
    size: int
    min_count: int
    cpu: str
    memory: str
    priority: int

    @property
    def ref(self) -> str:
        return self.name


class GangWorkloadGenerator:
    """Seeded generator of gang-shaped traffic (see module docstring)."""

    def __init__(self, seed: int = 0):
        self.rng = np.random.RandomState(seed)
        self._pod_seq = 0

    # -- specs -----------------------------------------------------------------

    def training_gangs(self, count: int, size=(8, 512),
                       min_count_frac: float = 1.0, cpu: str = "900m",
                       memory: str = "1Gi", priority: int = 0,
                       prefix: str = "train") -> list[GangSpec]:
        """Training gangs with min-count semantics. `size` is either a
        fixed member count or a (lo, hi) range sampled log-uniformly —
        real training fleets mix 8-chip probes with 512-chip jobs, and
        log-uniform is the only draw that exercises both decades."""
        specs = []
        for i in range(count):
            if isinstance(size, tuple):
                lo, hi = size
                g = int(round(2 ** self.rng.uniform(math.log2(lo),
                                                    math.log2(hi))))
                g = max(min(g, hi), lo)
            else:
                g = int(size)
            mc = max(1, min(g, int(round(g * min_count_frac))))
            specs.append(GangSpec(name=f"{prefix}-{i}", size=g, min_count=mc,
                                  cpu=cpu, memory=memory, priority=priority))
        return specs

    # -- object stamping -------------------------------------------------------

    @staticmethod
    def workload(spec: GangSpec) -> Workload:
        return Workload(metadata=ObjectMeta(name=spec.name),
                        pod_groups=[PodGroup(name="workers",
                                             min_count=spec.min_count)])

    def _stamp(self, proto: Pod, name: str) -> Pod:
        """Shallow-clone `proto` with fresh metadata/status — the spec
        object (and with it the signature) is SHARED across the gang."""
        p = _shallow(proto)
        m = _shallow(proto.metadata)
        m.name = name
        m.uid = f"{m.namespace}/{name}"
        m.creation_index = next(_counter)
        p.metadata = m
        p.status = PodStatus()
        return p

    def gang_pods(self, spec: GangSpec) -> list[Pod]:
        proto = (make_pod(f"{spec.name}-proto")
                 .req({"cpu": spec.cpu, "memory": spec.memory})
                 .workload(spec.ref)
                 .priority(spec.priority)
                 .obj())
        out = []
        for _ in range(spec.size):
            self._pod_seq += 1
            out.append(self._stamp(proto, f"{spec.name}-m{self._pod_seq}"))
        return out

    def inference_pods(self, count: int, cpu: str = "250m",
                       memory: str = "256Mi", priority: int = 100,
                       prefix: str = "inf") -> list[Pod]:
        """Co-located inference traffic: small, latency-class pods that
        outrank training gangs (the co-location contract of
        arXiv:2411.11560 — inference preempts training, not vice versa)."""
        proto = (make_pod(f"{prefix}-proto")
                 .req({"cpu": cpu, "memory": memory})
                 .priority(priority)
                 .obj())
        out = []
        for _ in range(count):
            self._pod_seq += 1
            out.append(self._stamp(proto, f"{prefix}-{self._pod_seq}"))
        return out

    # -- traces ----------------------------------------------------------------

    def trace(self, gangs: list[GangSpec],
              inference_count: int = 0,
              inference_cpu: str = "250m",
              inference_priority: int = 100,
              preemptor_gangs: Optional[list[GangSpec]] = None,
              chunk: int = 512) -> Iterator[tuple[str, object]]:
        """Deterministic arrival trace: ("workload", Workload) events for
        every gang up front (the Workload object must exist before its
        members can pass PreEnqueue), then ("pods", [Pod...]) chunks —
        gang arrivals shuffled with inference arrivals interleaved
        between them, preemptor gangs (gangs preempting gangs) last."""
        preemptor_gangs = preemptor_gangs or []
        for spec in (*gangs, *preemptor_gangs):
            yield ("workload", self.workload(spec))
        segments: list[list[Pod]] = [self.gang_pods(s) for s in gangs]
        if inference_count:
            inf = self.inference_pods(inference_count, cpu=inference_cpu,
                                      priority=inference_priority)
            # split the inference stream into as many slices as there are
            # gangs so it arrives co-located, not as one lump
            n_slices = max(len(segments), 1)
            per = max(len(inf) // n_slices, 1)
            slices = [inf[i:i + per] for i in range(0, len(inf), per)]
            merged: list[list[Pod]] = []
            for i, seg in enumerate(segments):
                merged.append(seg)
                if i < len(slices):
                    merged.append(slices[i])
            merged.extend(slices[len(segments):])
            segments = merged
        order = self.rng.permutation(len(segments))
        flat: list[Pod] = []
        for idx in order:
            flat.extend(segments[int(idx)])
        for spec in preemptor_gangs:
            flat.extend(self.gang_pods(spec))
        for i in range(0, len(flat), chunk):
            yield ("pods", flat[i:i + chunk])


# -- open-loop arrival processes (ISSUE 18) ------------------------------------
#
# The streaming pipeline (kubernetes_tpu/pipeline.py) is exercised as a
# production scheduler sees load: pods ARRIVE on a clock, they are not
# pre-staged in batches with quiet boundaries. The processes below stamp
# deterministic (seeded) arrival schedules as (due_s, payload) events —
# due_s is the offset from stream start at which the payload is fully
# arrived. Pacing to the wall clock is the DRIVER's job (perf/harness.py
# streamPods/streamTrace; open-loop: a late driver never thins the load,
# the backlog just builds).


def poisson_arrivals(chunks: Iterator[list] | list[list], qps: float,
                     seed: int = 0) -> Iterator[tuple[float, list]]:
    """Poisson arrival process at target rate `qps` (pods/s) over
    pre-chunked payloads: per-POD inter-arrival gaps are exponential with
    mean 1/qps, so a chunk of k pods is due after a Gamma(k, 1/qps) draw —
    the exact distribution of the sum of k exponential gaps, without
    stamping k events. Deterministic for a given (seed, chunk shape)."""
    if qps <= 0:
        raise ValueError(f"qps must be positive, got {qps}")
    rng = np.random.RandomState(seed)
    due = 0.0
    for chunk in chunks:
        if not chunk:
            continue
        due += float(rng.gamma(len(chunk), 1.0 / qps))
        yield (due, chunk)


def replay_arrivals(events: list[tuple[float, list]],
                    speed: float = 1.0) -> Iterator[tuple[float, list]]:
    """Trace replay: re-emit recorded (due_s, payload) events with their
    original spacing, optionally time-scaled (`speed=2.0` replays a
    recorded trace at twice its recorded rate)."""
    if speed <= 0:
        raise ValueError(f"speed must be positive, got {speed}")
    for due, payload in events:
        yield (due / speed, payload)


def chunked(items: list, chunk: int) -> list[list]:
    """Split a flat pod list into arrival chunks (the unit one feed()
    admits)."""
    step = max(1, int(chunk))
    return [items[i:i + step] for i in range(0, len(items), step)]
