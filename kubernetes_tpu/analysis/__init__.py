"""jaxsan: device-path static analysis + runtime sanitizer rails.

The whole architecture bets that the Filter→Score→bind cycle compiles to
a STATIC device program (SURVEY §7): retraces, hidden host↔device
transfers, donated-buffer reuse and cross-thread races are therefore
correctness-and-throughput bugs, not style issues. This package is the
lint-time half of that contract (the compile ledger in perf/ledger.py is
the runtime half):

- `jaxsan` — an AST linter that walks every function reachable from the
  JIT entry points and flags device-path hazards (traced-branch,
  np-in-jit, dynamic-shape, tracer-leak, donation-after-use,
  nondeterministic-iteration);
- `locks` — a lock-discipline checker for the threaded subsystems
  (`# guarded_by:` annotations → unguarded-shared-state findings, plus
  lock-acquisition-order cycle detection);
- `rails` — runtime sanitizer rails behind the `SanitizerRails` feature
  gate (transfer guard on the drain path, per-kernel retrace budgets,
  donation-after-use poisoning, NaN/inf guards).

`tools/check.py` drives the static half over the repo; the pytest
wrapper in tests/test_jaxsan.py makes it a tier-1 gate.
"""

from .findings import Finding, RULES, parse_waivers
from .jaxsan import JaxsanAnalyzer, analyze_tree
from .locks import LockChecker
from .rails import (SanitizerRails, SanitizerError, RetraceBudgetExceeded,
                    GLOBAL as RAILS)

__all__ = [
    "Finding", "RULES", "parse_waivers",
    "JaxsanAnalyzer", "analyze_tree",
    "LockChecker",
    "SanitizerRails", "SanitizerError", "RetraceBudgetExceeded", "RAILS",
]
