"""Finding model, rule registry and inline-waiver parsing for jaxsan.

A finding is one (rule, file, line) hazard with a fix-it hint. Rules are
a closed registry — the fixture self-test in tests/test_jaxsan.py seeds
one violation per rule class and asserts each is detected, so adding a
rule here without a fixture is itself a test failure.

Waiver syntax (the inline baseline mechanism `tools/check.py` honors):

    x = int(score_floor)  # jaxsan: waive[traced-branch] host replay path

A waiver comment on the flagged line (or the line directly above, for
findings on long expressions) suppresses the named rule(s) there;
`waive[*]` suppresses every rule on that line. Waivers are deliberately
per-line and per-rule — a file-wide opt-out would rot the moment new
code lands next to old baselines.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# rule id → (summary, fix-it hint). The first six are the device-path
# (traced-region) rules; the last two come from the lock checker.
RULES: dict[str, tuple[str, str]] = {
    "traced-branch": (
        "Python control flow or host cast on a traced value",
        "use jnp.where/lax.cond/lax.select instead of if/while, and keep "
        "int()/float()/bool() casts on the host side of the dispatch"),
    "np-in-jit": (
        "numpy call inside traced code",
        "np.* executes at trace time on the host and bakes a constant "
        "into (or breaks) the compiled program; use jnp.* so the op "
        "stays on device"),
    "dynamic-shape": (
        "array shape derived from a non-static value",
        "shapes must come from constants, .shape, or static argnums — a "
        "data-dependent shape re-traces per value (retrace bomb) or "
        "fails to trace"),
    "tracer-leak": (
        "traced value escapes the traced function",
        "writing a tracer to a global/closure/attribute leaks it past "
        "the trace; return the value through the function result pytree "
        "instead"),
    "donation-after-use": (
        "donated buffer read after dispatch",
        "the callee donates this argument's buffers to XLA; reads after "
        "the call see deleted (or silently reused) memory on accelerator "
        "backends — rebind the variable to the returned carry"),
    "nondeterministic-iteration": (
        "unordered set iteration feeds tensor construction",
        "set iteration order varies per process and changes trace "
        "constants / tensor layouts between runs; iterate sorted(...) "
        "or a list"),
    "unguarded-shared-state": (
        "shared attribute accessed outside its declared lock",
        "this attribute is annotated `# guarded_by: <lock>`; take the "
        "lock (`with self.<lock>:`) around the access, or mark the "
        "helper `# jaxsan: holds <lock>` if every caller already "
        "holds it"),
    "lock-order-cycle": (
        "locks acquired in inconsistent order",
        "two code paths nest these locks in opposite orders — a classic "
        "deadlock; pick one global order and acquire in it everywhere"),
}

_WAIVE_RE = re.compile(r"#\s*jaxsan:\s*waive\[([^\]]*)\]")
_HOLDS_RE = re.compile(r"#\s*jaxsan:\s*holds\s+(\w+)")
_GUARDED_RE = re.compile(r"#\s*guarded_by:\s*(\w+)")


@dataclass
class Finding:
    """One hazard at file:line. `waived` findings are kept (so
    `tools/check.py --list-waivers` can audit the baseline) but do not
    fail the check."""

    rule: str
    path: str
    line: int
    message: str
    func: str = ""          # enclosing function/class qualname
    hint: str = ""
    waived: bool = False

    def __post_init__(self) -> None:
        if not self.hint:
            self.hint = RULES.get(self.rule, ("", ""))[1]

    def format(self, fix_hints: bool = False) -> str:
        loc = f"{self.path}:{self.line}"
        where = f" (in {self.func})" if self.func else ""
        out = f"{loc}: [{self.rule}] {self.message}{where}"
        if self.waived:
            out += "  [waived]"
        if fix_hints and self.hint:
            out += f"\n    fix: {self.hint}"
        return out

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "func": self.func,
                "hint": self.hint, "waived": self.waived}


def parse_waivers(source: str) -> dict[int, set[str]]:
    """line number (1-based) → waived rule ids (`{"*"}` = all). A waiver
    comment covers its own line and the line below it, so wrapped
    expressions can carry the waiver on their first line."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _WAIVE_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        for line in (i, i + 1):
            out.setdefault(line, set()).update(rules)
    return out


def is_waived(waivers: dict[int, set[str]], line: int, rule: str) -> bool:
    rules = waivers.get(line)
    return bool(rules) and ("*" in rules or rule in rules)


def parse_holds(source_line: str) -> str | None:
    """`# jaxsan: holds <lock>` on a def line: the method's contract is
    that every caller already holds <lock> (the lock checker treats the
    whole body as guarded)."""
    m = _HOLDS_RE.search(source_line)
    return m.group(1) if m else None


def parse_guarded_by(source_line: str) -> str | None:
    """`# guarded_by: <lock>` on an attribute assignment."""
    m = _GUARDED_RE.search(source_line)
    return m.group(1) if m else None


def apply_waivers(findings: list[Finding],
                  waivers_by_path: dict[str, dict[int, set[str]]]
                  ) -> list[Finding]:
    for f in findings:
        w = waivers_by_path.get(f.path)
        if w and is_waived(w, f.line, f.rule):
            f.waived = True
    return findings
