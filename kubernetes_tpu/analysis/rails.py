"""Runtime sanitizer rails (`SanitizerRails` feature gate).

The static linter (jaxsan.py) rejects device-path hazards it can see;
these rails catch the ones only runtime can: an implicit host↔device
transfer on the steady-state drain path, a shape-churn retrace slipping
past the ledger, a donated carry silently resurrected by the CPU
backend's donation no-op, a NaN crawling into the score surface. All
rails are OFF by default (`SanitizerRails` is an Alpha gate): they exist
for tests, soaks and staging environments, not the hot path.

The four rails:

- **transfer guard** — the scheduler's `_phase` sub-phase contexts
  declare the phases where transfers are LEGAL (host_snapshot /
  host_tensorize / host_group_seed / host_cache / device_readback);
  `stage()` explicitly `jax.device_put`s the per-dispatch pod rows
  (device_put is the blessed escape under `jax.transfer_guard`). With
  rails on, a whole drain runs correctly under an ambient
  `jax.transfer_guard("disallow")` — the transfer-guard test in
  tests/test_sanitizer_rails.py holds exactly that.
- **retrace budget** — `retrace_budget(n)` snapshots the compile
  ledger's per-kernel compile counts and raises RetraceBudgetExceeded
  if the block mints more than `n` fresh executables (warm soak ⇒ 0).
- **donation poisoning** — CPU compiles without donation (ops/program.py
  run_batch), so a use-after-donate bug is invisible until it corrupts
  state on a real accelerator. `poison_donated(donated, out)` deletes
  the donated input's buffers (skipping any buffer aliased by the
  output) so a later read raises immediately — the runtime twin of the
  linter's donation-after-use rule.
- **NaN/inf guard** — `check_scores(...)` runs the score-probe kernel
  over a drain's first signature row and `assert_finite` raises
  SanitizerError on any non-finite score; `nan_guard()` additionally
  scopes `jax.debug_nans` for ad-hoc hunts.

Like the compile ledger, the rails instance is process-global (`GLOBAL`)
because the jit caches and the transfer-guard config it drives are
process-global; the scheduler enables it from its feature gate.
"""

from __future__ import annotations

import contextlib
from typing import Optional


class SanitizerError(RuntimeError):
    """A sanitizer rail tripped (NaN score, poisoned-buffer read, ...)."""


class RetraceBudgetExceeded(SanitizerError):
    """More fresh XLA executables minted than the declared budget."""


# drain phases where host↔device transfers are declared/legal — aligned
# with perf/ledger.py H2D_PHASES plus the pod-row tensorize phase
DECLARED_PHASES = ("host_snapshot", "host_tensorize", "host_group_seed",
                   "host_cache", "device_readback")


class SanitizerRails:
    """Feature-gated runtime rails (see module docstring)."""

    def __init__(self, enabled: bool = False):
        self._enabled = bool(enabled)
        self.poisoned = 0          # buffers deleted by donation poisoning
        self.staged_bytes = 0      # bytes explicitly staged by stage()

    # -- gating ---------------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True) -> None:
        self._enabled = bool(on)

    @contextlib.contextmanager
    def enabled(self, on: bool = True):
        """Scoped toggle (test helper)."""
        prev = self._enabled
        self._enabled = bool(on)
        try:
            yield self
        finally:
            self._enabled = prev

    # -- transfer guard -------------------------------------------------------

    def declared(self, phase: str):
        """Context for a phase where transfers are part of the contract:
        opens a transfer-guard allow window iff the phase is declared.
        The scheduler's `_phase` helper calls this with every host
        sub-phase name; undeclared phases keep the ambient guard."""
        if not self._enabled or phase not in DECLARED_PHASES:
            return contextlib.nullcontext()
        import jax
        return jax.transfer_guard("allow")

    def guard_dispatch(self):
        """Disallow implicit transfers for the scope (the device-dispatch
        region must consume only device-resident inputs)."""
        if not self._enabled:
            return contextlib.nullcontext()
        import jax
        return jax.transfer_guard("disallow")

    def stage(self, tree):
        """Explicitly move host-side (numpy) array leaves of a pytree to
        device. device_put is exempt from the transfer guard by design —
        staging is the DECLARED way per-dispatch host values reach the
        device. Device-resident leaves and non-array leaves pass through
        untouched (static NamedTuple config fields must stay hashable);
        bytes are attributed to the ledger's host_cache phase like the
        table upload."""
        if not self._enabled:
            return tree
        import jax

        def put(leaf):
            if isinstance(leaf, jax.Array) or not hasattr(leaf, "nbytes"):
                return leaf
            self.staged_bytes += int(leaf.nbytes)
            return jax.device_put(leaf)

        before = self.staged_bytes
        staged = jax.tree_util.tree_map(put, tree)
        delta = self.staged_bytes - before
        if delta:
            from ..perf.ledger import GLOBAL as _ledger
            _ledger.note_h2d("host_cache", delta)
        return staged

    # -- retrace budget -------------------------------------------------------

    @contextlib.contextmanager
    def retrace_budget(self, budget: int = 0,
                       kernels: Optional[tuple] = None):
        """Assert at most `budget` fresh compiles happen inside the
        block (across `kernels`, default all ledger kernels). A warm
        steady-state drain must fit budget 0 — the no-hidden-retraces
        invariant the compile ledger documents."""
        from ..perf.ledger import GLOBAL as ledger

        def counts():
            return {k: r.compiles for k, r in ledger.kernels.items()
                    if kernels is None or k in kernels}

        before = counts()
        yield
        after = counts()
        deltas = {k: after[k] - before.get(k, 0)
                  for k in after if after[k] - before.get(k, 0) > 0}
        total = sum(deltas.values())
        if total > budget:
            raise RetraceBudgetExceeded(
                f"{total} fresh XLA compiles (budget {budget}): "
                + ", ".join(f"{k}+{v}" for k, v in sorted(deltas.items())))

    # -- donation poisoning ---------------------------------------------------

    def poison_donated(self, donated, out=None) -> int:
        """Delete the donated pytree's buffers, simulating donation on
        backends that compiled without it (CPU). Buffers the output
        aliases (pass-through leaves) are kept — deleting them would
        poison live results. Returns buffers deleted."""
        if not self._enabled or donated is None:
            return 0
        import jax

        def pointer(leaf):
            probe = getattr(leaf, "unsafe_buffer_pointer", None)
            if probe is None:
                return None
            try:
                return probe()
            except Exception:   # committed elsewhere / multi-shard
                return None

        keep = set()
        if out is not None:
            for leaf in jax.tree_util.tree_leaves(out):
                p = pointer(leaf)
                if p is not None:
                    keep.add(p)
        deleted = 0
        for leaf in jax.tree_util.tree_leaves(donated):
            delete = getattr(leaf, "delete", None)
            is_deleted = getattr(leaf, "is_deleted", None)
            if delete is None or is_deleted is None or is_deleted():
                continue
            p = pointer(leaf)
            if p is not None and p in keep:
                continue
            try:
                delete()
                deleted += 1
            except Exception:   # pragma: no cover - backend specific
                continue
        self.poisoned += deleted
        return deleted

    # -- NaN / inf guard ------------------------------------------------------

    def assert_finite(self, name: str, tree) -> None:
        """Raise SanitizerError if any float leaf holds NaN/inf."""
        if not self._enabled:
            return
        import jax
        import jax.numpy as jnp

        for leaf in jax.tree_util.tree_leaves(tree):
            dtype = getattr(leaf, "dtype", None)
            if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
                continue
            if not bool(jnp.isfinite(leaf).all()):
                raise SanitizerError(
                    f"non-finite value in {name} "
                    f"(dtype {dtype}, shape {getattr(leaf, 'shape', ())})")

    def check_scores(self, cfg, na, carry, table, tidx) -> None:
        """Probe the score surface of signature row `tidx` against the
        current carry and raise on NaN/inf. One tiny shape-stable kernel
        per drain — cheap, and exactly the check no int-typed assignment
        output can perform for us."""
        if not self._enabled:
            return
        import numpy as np
        from ..ops.program import score_probe

        score = score_probe(cfg, na, carry, table,
                            self.stage(np.int32(tidx)))
        self.assert_finite("score surface", score)

    @contextlib.contextmanager
    def nan_guard(self):
        """Scope `jax.debug_nans` (op-level NaN hunt; slow, debug only)."""
        if not self._enabled:
            yield
            return
        import jax
        try:
            ctx = jax.debug_nans(True)
        except TypeError:   # pragma: no cover - much older jax
            ctx = contextlib.nullcontext()
        with ctx:
            yield


GLOBAL = SanitizerRails()
