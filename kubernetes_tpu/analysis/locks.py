"""Lock-discipline checker for the threaded subsystems.

The scheduler's host loop is single-threaded by design, but four
subsystems run (or are read from) other threads: the async API
dispatcher's depth gauge, the HostProfiler's sampler thread, the
EventRecorder/FlightRecorder rings served by the debug HTTP thread, and
the SchedulerServer itself. The reference leans on Go's race detector
for the analogous code (internal/queue, the informer cache); Python has
no -race, so the discipline is declared and lint-checked instead:

- every shared mutable attribute is annotated at its `__init__`
  assignment (or dataclass field) with the lock that guards it:

      self._ring = deque()   # guarded_by: _lock

- the checker verifies every OTHER method touches `self._ring` only
  inside `with self._lock:` (unguarded-shared-state findings otherwise);
- helper methods whose contract is "caller holds the lock" declare it on
  their `def` line — `# jaxsan: holds _lock` — and the checker treats
  the whole body as guarded (and can later check call sites);
- every nesting `with self.A: ... with self.B:` contributes an edge
  A→B to a global acquisition-order graph; a cycle in that graph is a
  latent deadlock (lock-order-cycle finding), reported once per cycle.

`__init__`/`__post_init__`/`__del__` are exempt (construction and
teardown happen-before/after publication).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .findings import Finding, parse_guarded_by, parse_holds

_EXEMPT_METHODS = {"__init__", "__post_init__", "__del__", "__new__"}

# constructors that mark an attribute as a lock (threading module)
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


@dataclass
class ClassLockInfo:
    name: str
    module_path: str
    guarded: dict = field(default_factory=dict)   # attr → lock attr
    locks: set = field(default_factory=set)       # attrs that ARE locks


class LockChecker:
    """Runs both lock rules over every class of the loaded modules.

    `modules` is the JaxsanAnalyzer's module map (name → ModuleInfo with
    .tree/.source/.path); the checker is standalone enough that the
    fixture tests can also hand it a synthetic map.
    """

    def __init__(self, modules: dict):
        self.modules = modules
        self.findings: list[Finding] = []
        # acquisition-order edges: (lock_id, lock_id) → first With node
        self.edges: dict[tuple[str, str], tuple[str, int]] = {}

    def run(self) -> list[Finding]:
        for mi in self.modules.values():
            lines = mi.source.splitlines()
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.ClassDef):
                    info = self._collect(node, lines, mi.path)
                    self._check_class(node, info, lines)
        self._check_cycles()
        return self.findings

    # -- annotation collection ------------------------------------------------

    def _collect(self, cls: ast.ClassDef, lines: list[str],
                 path: str) -> ClassLockInfo:
        info = ClassLockInfo(name=cls.name, module_path=path)
        for node in ast.walk(cls):
            targets: list[tuple[str, int]] = []
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = self._self_attr(t)
                    if attr:
                        targets.append((attr, node.lineno))
                value = node.value
            elif isinstance(node, ast.AnnAssign):
                attr = self._self_attr(node.target)
                if attr is None and isinstance(node.target, ast.Name):
                    # dataclass field declaration
                    attr = node.target.id
                if attr:
                    targets.append((attr, node.lineno))
                value = node.value
            else:
                continue
            # the annotation comment may sit on any line of a wrapped
            # assignment statement — scan the whole span
            end = getattr(node, "end_lineno", node.lineno)
            for attr, lineno in targets:
                lock = None
                for ln in range(lineno, end + 1):
                    src = lines[ln - 1] if ln - 1 < len(lines) else ""
                    lock = parse_guarded_by(src)
                    if lock:
                        break
                if lock:
                    info.guarded[attr] = lock
                    info.locks.add(lock)
                if self._is_lock_ctor(value):
                    info.locks.add(attr)
        return info

    @staticmethod
    def _self_attr(node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return node.attr
        return None

    @staticmethod
    def _is_lock_ctor(value: ast.AST | None) -> bool:
        for node in ast.walk(value) if value is not None else []:
            if isinstance(node, ast.Call):
                name = ""
                f = node.func
                while isinstance(f, ast.Attribute):
                    name = f.attr
                    f = f.value
                if isinstance(f, ast.Name) and not name:
                    name = f.id
                if name in _LOCK_CTORS:
                    return True
        return False

    # -- per-method guarded-access check --------------------------------------

    def _check_class(self, cls: ast.ClassDef, info: ClassLockInfo,
                     lines: list[str]) -> None:
        if not info.guarded and not info.locks:
            return
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            held: set[str] = set()
            src = lines[node.lineno - 1] if node.lineno - 1 < len(lines) \
                else ""
            holds = parse_holds(src)
            if holds:
                held.add(holds)
            if node.name not in _EXEMPT_METHODS:
                self._walk_method(node, info, held, node.name)
            self._collect_order(node, info, [])

    def _walk_method(self, node: ast.AST, info: ClassLockInfo,
                     held: set, method: str,
                     in_nested: bool = False) -> None:
        if isinstance(node, ast.With):
            new = set(held)
            for item in node.items:
                lock = self._lock_of(item.context_expr, info)
                if lock:
                    new.add(lock)
            for child in node.body:
                self._walk_method(child, info, new, method, in_nested)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                not isinstance(node, ast.Module) and in_nested is False \
                and getattr(node, "_visited_root", False) is False:
            # nested def: does not execute under the enclosing with
            node._visited_root = True
            for child in ast.iter_child_nodes(node):
                self._walk_method(child, info, held if node.name == method
                                  else set(), method, True)
            return
        attr = self._self_attr(node)
        if attr and attr in info.guarded:
            lock = info.guarded[attr]
            if lock not in held:
                self.findings.append(Finding(
                    rule="unguarded-shared-state",
                    path=info.module_path, line=node.lineno,
                    message=f"{info.name}.{attr} (guarded_by {lock}) "
                            f"accessed without holding self.{lock}",
                    func=f"{info.name}.{method}"))
            # do not descend: the attribute access itself is the leaf
        for child in ast.iter_child_nodes(node):
            self._walk_method(child, info, held, method, in_nested)

    def _lock_of(self, expr: ast.AST, info: ClassLockInfo) -> str | None:
        """`with self.<lock>:` (or `self.<lock>.acquire()`-style context
        helpers) → the lock attr name, if it is a known lock."""
        attr = self._self_attr(expr)
        if attr and (attr in info.locks or attr in info.guarded.values()):
            return attr
        if isinstance(expr, ast.Call):
            return self._lock_of(expr.func, info) or (
                self._lock_of(expr.func.value, info)
                if isinstance(expr.func, ast.Attribute) else None)
        return None

    # -- acquisition-order graph ----------------------------------------------

    def _collect_order(self, node: ast.AST, info: ClassLockInfo,
                       stack: list) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lock = self._lock_of(item.context_expr, info)
                if lock:
                    lock_id = f"{info.name}.{lock}"
                    for outer in stack:
                        if outer != lock_id:
                            self.edges.setdefault(
                                (outer, lock_id),
                                (info.module_path, node.lineno))
                    acquired.append(lock_id)
            for child in node.body:
                self._collect_order(child, info, stack + acquired)
            return
        for child in ast.iter_child_nodes(node):
            self._collect_order(child, info, stack)

    def _check_cycles(self) -> None:
        graph: dict[str, set] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        seen: set = set()
        reported: set = set()

        def dfs(n: str, path: list, on_path: set) -> None:
            seen.add(n)
            on_path.add(n)
            path.append(n)
            for m in sorted(graph.get(n, ())):
                if m in on_path:
                    cycle = tuple(path[path.index(m):] + [m])
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        mod_path, line = self.edges.get(
                            (n, m), ("", 1))
                        self.findings.append(Finding(
                            rule="lock-order-cycle", path=mod_path,
                            line=line,
                            message="lock acquisition order cycle: "
                                    + " -> ".join(cycle)))
                elif m not in seen:
                    dfs(m, path, on_path)
            path.pop()
            on_path.discard(n)

        for n in sorted(graph):
            if n not in seen:
                dfs(n, [], set())
