"""jaxsan device-path linter: AST walk of everything reachable from the
JIT entry points, flagging hazards that break the static-program contract.

Why a bespoke linter instead of flake8 plugins: the hazards here are not
syntactic — `if x:` is fine on the host and a trace-time crash (or a
silently baked-in constant) on a traced value; `np.zeros(n)` is fine in
`build_dev` and a retrace bomb inside `_run_batch_impl`. Telling the two
apart requires (a) knowing WHICH functions execute under `jax.jit` — the
call-graph closure of the jitted impls behind the nine public entries
(run_batch, run_uniform, run_wave, run_wave_scan, wave_statics,
diagnose_row, dry_run_select_victims, run_batch_sharded; the same set the
compile ledger wraps) — and (b) knowing WHICH values are traced inside
them — `fam` is a static argname and `if fam.spr_f:` is the intended
kernel-trimming idiom, while the same branch on `mask` would be a bug.

The analyzer therefore does a light interprocedural dataflow:

1. load every module of the target package, index functions, imports and
   NamedTuple definitions;
2. discover jit ROOTS — functions wrapped by `jax.jit(...)` (direct call,
   `functools.partial(jax.jit, ...)` decorator, or factory pattern) —
   with their `static_argnames`/`static_argnums`/`donate_argnums`;
3. propagate static-vs-traced levels through the call graph to a
   fixpoint: a root's static argnames seed STATIC params, everything
   else traced; each resolved call site pushes its argument levels onto
   the callee's params (traced wins);
4. run the traced-region rules (traced-branch, np-in-jit, dynamic-shape,
   tracer-leak, nondeterministic-iteration) over every reachable
   function with its inferred param levels, and the host-side rules
   (donation-after-use, plus set-iteration feeding tensor construction)
   over every function in the package.

Values are classified on a two-axis level: `traced` (device value) and
`structural` (a NamedTuple/tuple OF traced arrays — iterating or
checking `is None` on the container is trace-safe even though its leaves
are not). Annotations drive structure: any parameter or return annotated
with a NamedTuple class defined in the package is structural.

The output is a list of findings.Finding; inline `# jaxsan: waive[rule]`
comments baseline intentional exceptions (see findings.py).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .findings import Finding, RULES

# the ten public JIT entries (perf/ledger.py KERNELS wraps the same
# set); tools/check.py asserts each one resolves to at least one
# discovered jit root, so the lint cannot silently lose coverage.
# run_plan is the drain compiler's program (kubernetes_tpu/compiler/
# emits DrainPlans whose wavescan spans dispatch it).
ENTRY_POINTS = {
    "kubernetes_tpu.ops.program": (
        "run_batch", "run_uniform", "run_wave", "run_wave_scan",
        "run_plan", "wave_statics", "diagnose_row",
        "dry_run_select_victims", "scatter_rows", "explain_row",
        "cluster_probe"),
    "kubernetes_tpu.ops.gang": ("run_gang",),
    "kubernetes_tpu.parallel.sharding": (
        "run_batch_sharded", "run_uniform_sharded", "run_plan_sharded",
        "run_gang_sharded", "scatter_rows_sharded",
        "cluster_probe_sharded"),
}

# public entries that DONATE an argument's buffers to the compiled
# program (ops/program.py donate_argnums factories): callers must never
# read the donated variable after the call. Param index is the position
# of the donated argument in the PUBLIC entry's signature.
DONATING_ENTRIES = {
    "run_batch": (2, "carry"),
    "run_wave": (2, "carry"),
    "run_wave_scan": (2, "carry"),
    "run_plan": (2, "carry"),
    "run_gang": (2, "carry"),
}

# attribute reads that always yield host-static values, even on tracers
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "_fields"}

# jnp/np constructors whose SHAPE argument(s) must be static
# (name → indices of positional shape args; () = every positional arg)
_SHAPE_FUNCS = {
    "zeros": (0,), "ones": (0,), "full": (0,), "empty": (0,),
    "arange": (), "linspace": (0, 1, 2), "eye": (0, 1),
    "reshape": (1,), "broadcast_to": (1,), "tile": (1,),
    "iota": (1,),
}

# python builtins that coerce a tracer to bool internally
_BOOL_BUILTINS = {"min", "max", "any", "all", "sorted"}

_MUTATORS = {"append", "extend", "add", "insert", "update", "setdefault",
             "appendleft"}


@dataclass(frozen=True)
class Level:
    """Two-axis value classification (see module docstring)."""

    traced: bool = False
    structural: bool = False

    def merge(self, other: "Level") -> "Level":
        return Level(self.traced or other.traced,
                     self.structural or other.structural)


STATIC = Level(False, False)
TRACED = Level(True, False)
STRUCT = Level(True, True)


@dataclass
class ModuleInfo:
    name: str                     # dotted module name
    path: str                     # path relative to the analysis root
    tree: ast.Module
    source: str
    funcs: dict = field(default_factory=dict)        # name → FunctionDef
    imports: dict = field(default_factory=dict)      # alias → dotted target
    import_objects: dict = field(default_factory=dict)  # alias → (mod, obj)
    namedtuples: dict = field(default_factory=dict)  # class → {field: ann}
    constants: set = field(default_factory=set)      # module-level names


@dataclass
class FnInfo:
    module: ModuleInfo
    name: str
    node: ast.FunctionDef
    is_root: bool = False
    static_params: set = field(default_factory=set)
    donated_params: set = field(default_factory=set)
    traced: bool = False          # reachable from a jit root
    param_levels: dict = field(default_factory=dict)

    @property
    def qualname(self) -> str:
        return f"{self.module.name}.{self.name}"

    def params(self) -> list[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
                + [p.arg for p in a.kwonlyargs])


def _dotted(node: ast.AST) -> str | None:
    """a.b.c attribute/name chain as a dotted string (None if dynamic)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class JaxsanAnalyzer:
    """Package-wide device-path linter (see module docstring)."""

    def __init__(self, root: str, package: str = "kubernetes_tpu",
                 entry_points: dict | None = None,
                 donating: dict | None = None):
        self.root = root
        self.package = package
        self.entry_points = (ENTRY_POINTS if entry_points is None
                             else entry_points)
        self.donating = (DONATING_ENTRIES if donating is None
                         else donating)
        self.modules: dict[str, ModuleInfo] = {}
        self.fns: dict[str, FnInfo] = {}          # qualname → FnInfo
        self.findings: list[Finding] = []
        self.missing_entries: list[str] = []

    # -- loading --------------------------------------------------------------

    def load(self) -> "JaxsanAnalyzer":
        pkg_dir = os.path.join(self.root, *self.package.split("."))
        for dirpath, _dirs, files in os.walk(pkg_dir):
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root)
                mod = rel[:-3].replace(os.sep, ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                with open(path) as f:
                    source = f.read()
                try:
                    tree = ast.parse(source, filename=rel)
                except SyntaxError as e:  # pragma: no cover - broken file
                    self.findings.append(Finding(
                        rule="traced-branch", path=rel,
                        line=e.lineno or 1,
                        message=f"unparseable module: {e.msg}"))
                    continue
                self.modules[mod] = ModuleInfo(name=mod, path=rel,
                                               tree=tree, source=source)
        for mi in self.modules.values():
            self._index_module(mi)
        self._discover_roots()
        self._propagate()
        return self

    def _index_module(self, mi: ModuleInfo) -> None:
        pkg_parts = mi.name.split(".")
        for node in mi.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.funcs[node.name] = node
                self.fns[f"{mi.name}.{node.name}"] = FnInfo(
                    module=mi, name=node.name, node=node)
            elif isinstance(node, ast.ClassDef):
                bases = {(_dotted(b) or "").split(".")[-1]
                         for b in node.bases}
                if "NamedTuple" in bases:
                    fields = {}
                    for item in node.body:
                        if isinstance(item, ast.AnnAssign) and isinstance(
                                item.target, ast.Name):
                            fields[item.target.id] = _dotted(
                                item.annotation) or ""
                    mi.namedtuples[node.name] = fields
                mi.constants.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        mi.constants.add(t.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                mi.constants.add(node.target.id)
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mi.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - node.level]
                    target = ".".join(base + ([node.module]
                                              if node.module else []))
                else:
                    target = node.module or ""
                for alias in node.names:
                    name = alias.asname or alias.name
                    if f"{target}.{alias.name}" in self.modules:
                        mi.imports[name] = f"{target}.{alias.name}"
                    else:
                        mi.import_objects[name] = (target, alias.name)

    # -- namedtuple / annotation helpers --------------------------------------

    def _is_namedtuple(self, name: str | None) -> bool:
        if not name:
            return False
        tail = name.split(".")[-1].split("|")[0].strip()
        return any(tail in mi.namedtuples for mi in self.modules.values())

    def _annotation_level(self, ann: ast.AST | None) -> Level | None:
        if ann is None:
            return None
        text = None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            text = ann.value
        else:
            text = _dotted(ann)
            if text is None and isinstance(ann, ast.BinOp):
                # X | None
                text = _dotted(ann.left)
            if text is None and isinstance(ann, ast.Subscript):
                text = _dotted(ann.value)
        if text is None:
            return None
        tail = text.split("[")[0].split("|")[0].strip().split(".")[-1]
        if tail in ("int", "float", "bool", "str", "tuple", "list", "dict"):
            return STATIC
        if self._is_namedtuple(tail):
            return STRUCT
        return None

    # -- jit root discovery ---------------------------------------------------

    def _resolve_fn(self, mi: ModuleInfo, node: ast.AST) -> FnInfo | None:
        """Resolve a callee expression to an indexed function."""
        if isinstance(node, ast.Name):
            if node.id in mi.funcs:
                return self.fns.get(f"{mi.name}.{node.id}")
            obj = mi.import_objects.get(node.id)
            if obj and obj[0] in self.modules:
                return self.fns.get(f"{obj[0]}.{obj[1]}")
        elif isinstance(node, ast.Attribute):
            base = _dotted(node.value)
            if base and base in mi.imports:
                target = mi.imports[base]
                if target in self.modules:
                    return self.fns.get(f"{target}.{node.attr}")
        return None

    @staticmethod
    def _const_names(node: ast.AST | None) -> set:
        out = set()
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
        return out

    @staticmethod
    def _const_ints(node: ast.AST | None) -> set:
        out = set()
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            out.add(node.value)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.add(e.value)
        elif isinstance(node, ast.IfExp):
            for side in (node.body, node.orelse):
                out |= JaxsanAnalyzer._const_ints(side)
        return out

    def _mark_root(self, fi: FnInfo, static_names: set, static_nums: set,
                   donate_nums: set) -> None:
        fi.is_root = True
        fi.traced = True
        params = fi.params()
        fi.static_params |= static_names
        for i in static_nums:
            if 0 <= i < len(params):
                fi.static_params.add(params[i])
        for i in donate_nums:
            if 0 <= i < len(params):
                fi.donated_params.add(params[i])
        for p in params:
            lvl = STATIC if p in fi.static_params else TRACED
            if lvl.traced:
                ann = self._param_annotation(fi, p)
                alvl = self._annotation_level(ann)
                if alvl is not None and alvl.structural:
                    lvl = STRUCT
            fi.param_levels[p] = fi.param_levels.get(p, STATIC).merge(lvl) \
                if p not in fi.static_params else STATIC

    @staticmethod
    def _param_annotation(fi: FnInfo, name: str) -> ast.AST | None:
        a = fi.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg == name:
                return p.annotation
        return None

    def _discover_roots(self) -> None:
        for mi in self.modules.values():
            for node in ast.walk(mi.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._root_from_decorators(mi, node)
                elif isinstance(node, ast.Call):
                    self._root_from_call(mi, node)

    def _jit_call_opts(self, call: ast.Call):
        names, nums, dons = set(), set(), set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                names |= self._const_names(kw.value)
            elif kw.arg == "static_argnums":
                nums |= self._const_ints(kw.value)
            elif kw.arg == "donate_argnums":
                dons |= self._const_ints(kw.value)
        return names, nums, dons

    def _root_from_call(self, mi: ModuleInfo, call: ast.Call) -> None:
        name = _dotted(call.func) or ""
        tail = name.split(".")[-1]
        if tail != "jit" or not call.args:
            return
        fi = self._resolve_fn(mi, call.args[0])
        if fi is None:
            return
        names, nums, dons = self._jit_call_opts(call)
        self._mark_root(fi, names, nums, dons)

    def _root_from_decorators(self, mi: ModuleInfo,
                              node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            fi = self.fns.get(f"{mi.name}.{node.name}")
            if fi is None:
                continue
            if isinstance(dec, (ast.Name, ast.Attribute)):
                if (_dotted(dec) or "").split(".")[-1] == "jit":
                    self._mark_root(fi, set(), set(), set())
            elif isinstance(dec, ast.Call):
                dn = _dotted(dec.func) or ""
                if dn.split(".")[-1] == "jit":
                    names, nums, dons = self._jit_call_opts(dec)
                    self._mark_root(fi, names, nums, dons)
                elif dn.split(".")[-1] == "partial" and dec.args:
                    inner = _dotted(dec.args[0]) or ""
                    if inner.split(".")[-1] == "jit":
                        names, nums, dons = self._jit_call_opts(dec)
                        self._mark_root(fi, names, nums, dons)

    # -- interprocedural propagation ------------------------------------------

    def _propagate(self) -> None:
        work = [fi for fi in self.fns.values() if fi.is_root]
        seen_edges = set()
        while work:
            fi = work.pop()
            checker = _FnChecker(self, fi, collect=False)
            checker.run()
            for callee, arg_levels in checker.calls:
                key = (fi.qualname, callee.qualname,
                       tuple(sorted((k, v.traced, v.structural)
                                    for k, v in arg_levels.items())))
                if key in seen_edges:
                    continue
                seen_edges.add(key)
                changed = not callee.traced
                callee.traced = True
                for pname, lvl in arg_levels.items():
                    ann = self._annotation_level(
                        self._param_annotation(callee, pname))
                    if ann is not None:
                        if ann is STATIC and not lvl.traced:
                            lvl = STATIC
                        elif ann.structural and lvl.traced:
                            lvl = lvl.merge(Level(True, True))
                    old = callee.param_levels.get(pname, STATIC)
                    new = old.merge(lvl)
                    if new != old:
                        callee.param_levels[pname] = new
                        changed = True
                if changed and not callee.is_root:
                    work.append(callee)

    # -- entry coverage -------------------------------------------------------

    def check_entry_coverage(self) -> list[str]:
        """Each declared JIT entry must exist and transitively reach at
        least one discovered jit root — otherwise the lint has silently
        lost device-path coverage."""
        missing = []
        for mod, names in self.entry_points.items():
            mi = self.modules.get(mod)
            for name in names:
                fi = self.fns.get(f"{mod}.{name}") if mi else None
                if fi is None or not self._reaches_root(fi, set()):
                    missing.append(f"{mod}.{name}")
        self.missing_entries = missing
        return missing

    def _reaches_root(self, fi: FnInfo, seen: set) -> bool:
        if fi.qualname in seen:
            return False
        seen.add(fi.qualname)
        if fi.is_root:
            return True
        for node in ast.walk(fi.node):
            target = None
            if isinstance(node, ast.Call):
                target = self._resolve_fn(fi.module, node.func)
                if target is None and node.args:
                    # factory pattern: jax.jit(impl) referenced as arg
                    target = self._resolve_fn(fi.module, node.args[0])
            elif isinstance(node, ast.Name):
                target = self._resolve_fn(fi.module, node)
            if target is not None and self._reaches_root(target, seen):
                return True
        return False

    # -- rule passes ----------------------------------------------------------

    def run(self) -> list[Finding]:
        self.check_entry_coverage()
        for fi in self.fns.values():
            if fi.traced:
                _FnChecker(self, fi, collect=True).run()
            else:
                _HostChecker(self, fi).run()
        return self.findings

    def emit(self, rule: str, fi: FnInfo, node: ast.AST,
             message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=fi.module.path,
            line=getattr(node, "lineno", 1), message=message,
            func=fi.qualname.split(".")[-1]))


# ---------------------------------------------------------------------------
# per-function abstract interpretation


class _FnChecker:
    """Sequentially interprets one traced function's body, tracking
    name → Level, emitting findings (when `collect`) and recording
    resolved call edges with argument levels (for propagation)."""

    def __init__(self, an: JaxsanAnalyzer, fi: FnInfo, collect: bool,
                 parent_env: dict | None = None,
                 parent_locals: set | None = None):
        self.an = an
        self.fi = fi
        self.collect = collect
        self.env: dict[str, Level] = dict(parent_env or {})
        self.outer_names = set(self.env) | (parent_locals or set())
        self.local_names: set[str] = set()
        self.nonlocal_names: set[str] = set()
        self.set_names: set[str] = set()
        self.calls: list[tuple[FnInfo, dict]] = []
        self.nested: dict[str, ast.FunctionDef] = {}
        self._nested_done: set[str] = set()

    # -- env helpers ----------------------------------------------------------

    def run(self) -> None:
        for p in self.fi.params():
            # missing level = the fixpoint never saw this param at a call
            # site (a default-only argument): its default expression is a
            # host constant, so STATIC. Roots and nested callbacks are
            # explicitly seeded (TRACED) before reaching here — an
            # optimistic default keeps one early conservative guess from
            # monotonically poisoning the whole call graph.
            self.env[p] = self.fi.param_levels.get(p, STATIC)
            self.local_names.add(p)
        self.block(self.fi.node.body)
        # nested defs never directly called (callbacks handed to lax /
        # shard_map / unknown callees) get a conservative all-traced pass
        for name, node in self.nested.items():
            if name not in self._nested_done:
                self._analyze_nested(node, {})

    def bind(self, target: ast.AST, lvl: Level,
             iter_src: ast.AST | None = None) -> None:
        if isinstance(target, ast.Name):
            if target.id in self.nonlocal_names:
                if lvl.traced and self.collect:
                    self.an.emit("tracer-leak", self.fi, target,
                                 f"traced value assigned to nonlocal/global "
                                 f"'{target.id}'")
            self.env[target.id] = self.env.get(
                target.id, STATIC).merge(lvl) if lvl.traced else lvl
            self.local_names.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # positional zip() match lets `for name, arr in zip(fields, t)`
            # keep the static element static
            zip_args = None
            if (iter_src is not None and isinstance(iter_src, ast.Call)
                    and (_dotted(iter_src.func) or "") == "zip"
                    and len(iter_src.args) == len(target.elts)):
                zip_args = [self.level(a) for a in iter_src.args]
            for i, e in enumerate(target.elts):
                elvl = zip_args[i] if zip_args is not None else (
                    Level(lvl.traced, False))
                self.bind(e, elvl)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, lvl)
        elif isinstance(target, ast.Attribute):
            if lvl.traced and self.collect:
                self.an.emit("tracer-leak", self.fi, target,
                             f"traced value stored on attribute "
                             f"'{_dotted(target) or target.attr}'")
        elif isinstance(target, ast.Subscript):
            base = target.value
            if (lvl.traced and self.collect and isinstance(base, ast.Name)
                    and base.id not in self.local_names
                    and not self.env.get(base.id, STATIC).traced):
                self.an.emit("tracer-leak", self.fi, target,
                             f"traced value stored into outer container "
                             f"'{base.id}'")

    def name_level(self, name: str) -> Level:
        if name in self.env:
            return self.env[name]
        return STATIC   # module constants / builtins / unknown → static

    # -- statements -----------------------------------------------------------

    def block(self, body: list) -> None:
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested[node.name] = node
            self.local_names.add(node.name)
            self.env[node.name] = STATIC
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.level(node.value)
        elif isinstance(node, ast.Expr):
            self.level(node.value)
        elif isinstance(node, ast.Assign):
            lvl = self.level(node.value)
            for t in node.targets:
                self.bind(t, lvl, iter_src=node.value)
            self._note_set_assign(node.targets, node.value)
        elif isinstance(node, ast.AugAssign):
            lvl = self.level(node.value)
            if isinstance(node.target, ast.Name):
                cur = self.env.get(node.target.id, STATIC)
                self.bind(node.target, cur.merge(lvl))
            else:
                self.bind(node.target, lvl)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.bind(node.target, self.level(node.value))
        elif isinstance(node, ast.If):
            self._bool_context(node.test, "if")
            self.block(node.body)
            self.block(node.orelse)
        elif isinstance(node, ast.While):
            self._bool_context(node.test, "while")
            self.block(node.body)
            self.block(node.body)     # second pass: stabilize loop levels
            self.block(node.orelse)
        elif isinstance(node, ast.For):
            self._check_iteration(node.iter)
            it = self.level(node.iter)
            self.bind(node.target, Level(it.traced, False),
                      iter_src=node.iter)
            self.block(node.body)
            self.block(node.body)
            self.block(node.orelse)
        elif isinstance(node, ast.With):
            for item in node.items:
                self.level(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, STATIC)
            self.block(node.body)
        elif isinstance(node, ast.Try):
            self.block(node.body)
            for h in node.handlers:
                if h.name:
                    self.local_names.add(h.name)
                    self.env[h.name] = STATIC
                self.block(h.body)
            self.block(node.orelse)
            self.block(node.finalbody)
        elif isinstance(node, ast.Assert):
            self._bool_context(node.test, "assert")
            if node.msg is not None:
                self.level(node.msg)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            self.nonlocal_names.update(node.names)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self.level(node.exc)
        elif isinstance(node, ast.Delete):
            pass
        elif isinstance(node, ast.ClassDef):
            self.local_names.add(node.name)

    def _note_set_assign(self, targets, value) -> None:
        is_set = isinstance(value, ast.Set) or (
            isinstance(value, ast.Call)
            and (_dotted(value.func) or "") in ("set", "frozenset")) or \
            isinstance(value, ast.SetComp)
        for t in targets:
            if isinstance(t, ast.Name):
                if is_set:
                    self.set_names.add(t.id)
                else:
                    self.set_names.discard(t.id)

    # -- bool / iteration contexts --------------------------------------------

    def _bool_context(self, test: ast.AST, kind: str) -> None:
        lvl = self.level(test)
        if lvl.traced and not lvl.structural and self.collect:
            self.an.emit("traced-branch", self.fi, test,
                         f"Python `{kind}` on a traced value "
                         f"(`{ast.unparse(test)[:60]}`)")

    def _check_iteration(self, it: ast.AST) -> None:
        lvl = self.level(it)
        if not self.collect:
            return
        if self._is_set_expr(it):
            self.an.emit("nondeterministic-iteration", self.fi, it,
                         "iteration over an unordered set inside traced "
                         "code (trace order bakes into the program)")
        elif lvl.traced and not lvl.structural:
            self.an.emit("traced-branch", self.fi, it,
                         f"Python loop over a traced value "
                         f"(`{ast.unparse(it)[:60]}`)")

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and \
                (_dotted(node.func) or "") in ("set", "frozenset"):
            return True
        return isinstance(node, ast.Name) and node.id in self.set_names

    # -- expressions ----------------------------------------------------------

    def level(self, node: ast.AST) -> Level:   # noqa: C901 - dispatch table
        if node is None or isinstance(node, ast.Constant):
            return STATIC
        if isinstance(node, ast.Name):
            return self.name_level(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                self.level(node.value)
                return STATIC
            base = self.level(node.value)
            if base.structural:
                # NamedTuple field: another NamedTuple → structural leaf
                return STRUCT if self._field_is_struct(node) else TRACED
            return Level(base.traced, False)
        if isinstance(node, ast.Subscript):
            v = self.level(node.value)
            s = self.level(node.slice)
            if not v.traced:
                return Level(s.traced, False)
            return Level(True, False)
        if isinstance(node, (ast.Tuple, ast.List)):
            lv = STATIC
            for e in node.elts:
                lv = lv.merge(self.level(e))
            return Level(lv.traced, lv.traced)   # containers are structural
        if isinstance(node, ast.Dict):
            lv = STATIC
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    lv = lv.merge(self.level(k))
                lv = lv.merge(self.level(v))
            return Level(lv.traced, lv.traced)
        if isinstance(node, ast.Set):
            for e in node.elts:
                self.level(e)
            return STATIC
        if isinstance(node, ast.BoolOp):
            lv = STATIC
            for v in node.values:
                vl = self.level(v)
                if vl.traced and not vl.structural and self.collect:
                    self.an.emit("traced-branch", self.fi, v,
                                 "`and`/`or` coerces a traced value to "
                                 "bool (use & / | / jnp.logical_*)")
                lv = lv.merge(vl)
            return Level(lv.traced, False)
        if isinstance(node, ast.UnaryOp):
            lv = self.level(node.operand)
            if isinstance(node.op, ast.Not) and lv.traced \
                    and not lv.structural and self.collect:
                self.an.emit("traced-branch", self.fi, node,
                             "`not` coerces a traced value to bool "
                             "(use ~ / jnp.logical_not)")
            return Level(lv.traced, False)
        if isinstance(node, ast.BinOp):
            return Level(self.level(node.left).traced
                         | self.level(node.right).traced, False)
        if isinstance(node, ast.Compare):
            if self._is_none_check(node):
                self.level(node.left)
                return STATIC
            lv = self.level(node.left)
            for c in node.comparators:
                lv = lv.merge(self.level(c))
            return Level(lv.traced, False)
        if isinstance(node, ast.IfExp):
            self._bool_context(node.test, "conditional expression")
            return self.level(node.body).merge(self.level(node.orelse))
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._comp(node, node.elt)
        if isinstance(node, ast.DictComp):
            return self._comp(node, node.value, key=node.key)
        if isinstance(node, ast.Lambda):
            return STATIC
        if isinstance(node, ast.Starred):
            return self.level(node.value)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.level(v.value)
            return STATIC
        if isinstance(node, ast.Slice):
            lv = STATIC
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    lv = lv.merge(self.level(part))
            return lv
        if isinstance(node, ast.NamedExpr):
            lv = self.level(node.value)
            self.bind(node.target, lv)
            return lv
        return STATIC

    @staticmethod
    def _is_none_check(node: ast.Compare) -> bool:
        return (len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Is, ast.IsNot))
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None)

    def _field_is_struct(self, node: ast.Attribute) -> bool:
        # best effort: field annotation of any known NamedTuple with this
        # field name resolving to another NamedTuple
        for mi in self.an.modules.values():
            for fields in mi.namedtuples.values():
                ann = fields.get(node.attr)
                if ann and self.an._is_namedtuple(ann):
                    return True
        return False

    def _comp(self, node, elt, key=None) -> Level:
        if self.collect:
            for gen in node.generators:
                self._check_iteration(gen.iter)
        lv = STATIC
        for gen in node.generators:
            it = self.level(gen.iter)
            self.bind(gen.target, Level(it.traced, False),
                      iter_src=gen.iter)
            for cond in gen.ifs:
                self._bool_context(cond, "comprehension filter")
        lv = lv.merge(self.level(elt))
        if key is not None:
            lv = lv.merge(self.level(key))
        return Level(lv.traced, lv.traced)

    # -- calls ----------------------------------------------------------------

    def _call(self, node: ast.Call) -> Level:   # noqa: C901
        fname = _dotted(node.func) or ""
        tail = fname.split(".")[-1]
        root = fname.split(".")[0] if fname else ""
        arg_levels = [self.level(a) for a in node.args]
        kw_levels = {kw.arg: self.level(kw.value) for kw in node.keywords
                     if kw.arg is not None}
        for kw in node.keywords:
            if kw.arg is None:
                self.level(kw.value)
        any_traced = any(l.traced for l in arg_levels) or \
            any(l.traced for l in kw_levels.values())

        # numpy inside traced code
        if self.collect and root and self._is_numpy_root(root) \
                and isinstance(node.func, ast.Attribute):
            self.an.emit("np-in-jit", self.fi, node,
                         f"`{fname}` call inside traced code")
            return TRACED

        device_lib = self._is_device_root(root)

        # casts / bool-coercing builtins
        if fname in ("int", "float", "bool") and arg_levels and \
                arg_levels[0].traced and not arg_levels[0].structural:
            if self.collect:
                self.an.emit("traced-branch", self.fi, node,
                             f"host `{fname}()` cast forces a traced value "
                             "to a Python scalar")
            return TRACED
        if fname in _BOOL_BUILTINS and any(
                l.traced and not l.structural for l in arg_levels):
            if self.collect:
                self.an.emit("traced-branch", self.fi, node,
                             f"builtin `{fname}()` on a traced value "
                             "coerces to bool internally")
            return TRACED

        # dynamic shapes
        if self.collect and tail in _SHAPE_FUNCS and (
                device_lib or isinstance(node.func, ast.Attribute)):
            self._check_shapes(node, tail, arg_levels, kw_levels)

        # leaks into outer containers
        if self.collect and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS and any_traced:
            base = node.func.value
            if isinstance(base, ast.Name) \
                    and base.id not in self.local_names \
                    and not self.env.get(base.id, STATIC).traced:
                self.an.emit("tracer-leak", self.fi, node,
                             f"traced value accumulated into outer "
                             f"container '{base.id}.{node.func.attr}'")

        # interprocedural edges
        callee = self.an._resolve_fn(self.fi.module, node.func)
        if callee is not None:
            self._record_edge(callee, node, arg_levels, kw_levels)
            ann = self.an._annotation_level(callee.node.returns)
            if ann is not None:
                return ann if not any_traced or ann is STATIC else ann
            return Level(True, False) if (any_traced or callee.traced) \
                else STATIC

        # functools.partial(F, ...): propagate bound args, rest traced
        if tail == "partial" and node.args:
            pf = self.an._resolve_fn(self.fi.module, node.args[0])
            if pf is not None:
                self._record_partial(pf, node, arg_levels[1:], kw_levels)
                return STATIC
        # nested function usage
        if isinstance(node.func, ast.Name) and node.func.id in self.nested:
            self._analyze_nested(
                self.nested[node.func.id],
                self._map_args(self.nested[node.func.id], node,
                               arg_levels, kw_levels))
            return TRACED if any_traced else STATIC
        # callbacks handed to lax.scan / while_loop / cond / shard_map /
        # vmap / indexed callees: their params are traced
        for a in node.args:
            if isinstance(a, ast.Name) and a.id in self.nested:
                self._analyze_nested(self.nested[a.id], {})
            elif isinstance(a, ast.Lambda):
                self._analyze_lambda(a)
        for kw in node.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id in self.nested:
                self._analyze_nested(self.nested[kw.value.id], {})
            elif isinstance(kw.value, ast.Lambda):
                self._analyze_lambda(kw.value)

        if device_lib:
            if tail in ("iinfo", "finfo"):
                return STATIC
            return TRACED
        # method call on a traced object (x.astype, x.at[...].set, ...)
        if isinstance(node.func, ast.Attribute):
            base = self.level(node.func.value)
            if base.traced:
                if node.func.attr == "_replace":
                    merged = base
                    for lv in kw_levels.values():
                        merged = merged.merge(Level(lv.traced, False))
                    return Level(True, base.structural)
                return Level(True, False)
        if fname == "getattr":
            return Level(bool(arg_levels) and arg_levels[0].traced, False)
        if fname in ("len", "isinstance", "hasattr", "type",
                     "range", "enumerate", "repr", "str", "id", "format"):
            return STATIC
        if fname == "zip":
            return Level(any_traced, True)
        return Level(any_traced, any_traced)

    def _is_numpy_root(self, root: str) -> bool:
        target = self.fi.module.imports.get(root, "")
        return target == "numpy" or target.startswith("numpy.")

    def _is_device_root(self, root: str) -> bool:
        if not root:
            return False
        target = self.fi.module.imports.get(root, "")
        if target == "jax" or target.startswith("jax."):
            return True
        if root in ("jnp", "lax", "jax"):
            return True
        obj = self.fi.module.import_objects.get(root)
        return bool(obj and obj[0].startswith("jax"))

    def _check_shapes(self, node: ast.Call, tail: str, arg_levels,
                      kw_levels) -> None:
        idxs = _SHAPE_FUNCS[tail]
        shape_args = (list(range(len(arg_levels))) if idxs == ()
                      else [i for i in idxs if i < len(arg_levels)])
        # method form a.reshape(...): every positional arg is shape
        if isinstance(node.func, ast.Attribute) and \
                self.level(node.func.value).traced and \
                tail in ("reshape", "broadcast_to", "tile"):
            shape_args = list(range(len(arg_levels)))
        for i in shape_args:
            if arg_levels[i].traced:
                self.an.emit("dynamic-shape", self.fi, node,
                             f"`{tail}` shape argument derives from a "
                             "traced value")
                return
        lv = kw_levels.get("shape")
        if lv is not None and lv.traced:
            self.an.emit("dynamic-shape", self.fi, node,
                         f"`{tail}` shape= derives from a traced value")

    # -- interprocedural plumbing --------------------------------------------

    def _map_args(self, target: ast.FunctionDef, node: ast.Call,
                  arg_levels, kw_levels) -> dict:
        a = target.args
        params = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
        mapping: dict[str, Level] = {}
        for i, lvl in enumerate(arg_levels):
            if i < len(params):
                mapping[params[i]] = lvl
        for name, lvl in kw_levels.items():
            mapping[name] = lvl
        return mapping

    def _record_edge(self, callee: FnInfo, node: ast.Call, arg_levels,
                     kw_levels) -> None:
        mapping = self._map_args(callee.node, node, arg_levels, kw_levels)
        # a callable handed to an indexed callee will be invoked on
        # traced values — give nested callbacks the conservative pass
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Name) and a.id in self.nested:
                self._analyze_nested(self.nested[a.id], {})
            elif isinstance(a, ast.Lambda):
                self._analyze_lambda(a)
        self.calls.append((callee, mapping))

    def _record_partial(self, callee: FnInfo, node: ast.Call,
                        bound_levels, kw_levels) -> None:
        params = callee.params()
        mapping: dict[str, Level] = {}
        for i, lvl in enumerate(bound_levels):
            if i < len(params):
                mapping[params[i]] = lvl
        for name, lvl in kw_levels.items():
            if name in params:
                mapping[name] = lvl
        for p in params:
            mapping.setdefault(p, TRACED)
        self.calls.append((callee, mapping))

    def _analyze_nested(self, node: ast.FunctionDef,
                        param_levels: dict) -> None:
        key = f"{node.name}:{node.lineno}"
        if key in self._nested_done:
            return
        self._nested_done.add(key)
        if node.name in self.nested:
            self._nested_done.add(node.name)
        sub_fi = FnInfo(module=self.fi.module, name=node.name, node=node,
                        traced=True)
        for p in sub_fi.params():
            lvl = param_levels.get(p)
            if lvl is None:
                lvl = TRACED
                ann = self.an._annotation_level(
                    JaxsanAnalyzer._param_annotation(sub_fi, p))
                if ann is not None:
                    lvl = STRUCT if ann.structural else \
                        (STATIC if ann is STATIC else TRACED)
            sub_fi.param_levels[p] = lvl
        sub = _FnChecker(self.an, sub_fi, self.collect,
                         parent_env=self.env,
                         parent_locals=self.local_names)
        sub.run()
        self.calls.extend(sub.calls)

    def _analyze_lambda(self, node: ast.Lambda) -> None:
        fn = ast.FunctionDef(
            name="<lambda>", args=node.args,
            body=[ast.Return(value=node.body, lineno=node.lineno,
                             col_offset=node.col_offset)],
            decorator_list=[], lineno=node.lineno,
            col_offset=node.col_offset)
        ast.fix_missing_locations(fn)
        self._analyze_nested(fn, {})


# ---------------------------------------------------------------------------
# host-side pass: donation-after-use + set-iteration feeding tensors


class _HostChecker:
    """Rules that apply to HOST functions: reading a donated carry after
    the donating dispatch, and unordered-set iteration that feeds tensor
    construction (parity-sensitive constants)."""

    ARRAY_CTORS = {"array", "asarray", "stack", "concatenate", "zeros",
                   "ones", "full", "fromiter"}

    def __init__(self, an: JaxsanAnalyzer, fi: FnInfo):
        self.an = an
        self.fi = fi

    def run(self) -> None:
        self._donation_pass(self.fi.node)
        self._set_iteration_pass()

    # -- donation-after-use ---------------------------------------------------

    def _donation_pass(self, fn: ast.FunctionDef) -> None:
        statements = list(ast.walk(fn))
        for body in self._bodies(fn):
            for i, stmt in enumerate(body):
                for call in ast.walk(stmt):
                    if not isinstance(call, ast.Call):
                        continue
                    entry = self._donating_entry(call)
                    if entry is None:
                        continue
                    donated = self._donated_arg(call, entry)
                    if not isinstance(donated, ast.Name):
                        continue
                    if self._rebinds(stmt, donated.id):
                        # `carry = run_batch(..., carry, ...)` — the
                        # donating statement rebinds the name to the
                        # returned carry, the blessed idiom
                        continue
                    self._check_after(body, i, donated.id, entry)
        del statements

    def _bodies(self, fn: ast.FunctionDef):
        for node in ast.walk(fn):
            for attr in ("body", "orelse", "finalbody"):
                body = getattr(node, attr, None)
                if isinstance(body, list) and body \
                        and isinstance(body[0], ast.stmt):
                    yield body

    def _donating_entry(self, call: ast.Call):
        name = (_dotted(call.func) or "").split(".")[-1]
        if name in self.an.donating:
            return name
        return None

    def _donated_arg(self, call: ast.Call, entry: str):
        idx, kwname = self.an.donating[entry]
        for kw in call.keywords:
            if kw.arg == kwname:
                return kw.value
        if idx < len(call.args):
            return call.args[idx]
        return None

    def _check_after(self, body: list, i: int, name: str,
                     entry: str) -> None:
        for stmt in body[i + 1:]:
            if self._rebinds(stmt, name):
                return
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and node.id == name \
                        and isinstance(node.ctx, ast.Load):
                    self.an.emit(
                        "donation-after-use", self.fi, node,
                        f"'{name}' was donated to {entry}() and read "
                        "afterwards")
                    return

    @staticmethod
    def _rebinds(stmt: ast.stmt, name: str) -> bool:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id == name \
                            and isinstance(n.ctx, ast.Store):
                        return True
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            t = stmt.target
            if isinstance(t, ast.Name) and t.id == name:
                return True
        return False

    # -- set iteration feeding tensor construction ----------------------------

    def _set_iteration_pass(self) -> None:
        for node in ast.walk(self.fi.node):
            it = None
            scope = None
            if isinstance(node, ast.For):
                it, scope = node.iter, node.body
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.SetComp)):
                it = node.generators[0].iter
                scope = [node]
            if it is None or not self._is_set_expr(it):
                continue
            if self._feeds_tensor(scope):
                self.an.emit(
                    "nondeterministic-iteration", self.fi, it,
                    "unordered set iteration feeds tensor construction "
                    "(parity-sensitive order)")

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return isinstance(node, ast.Call) and \
            (_dotted(node.func) or "") in ("set", "frozenset")

    def _feeds_tensor(self, scope) -> bool:
        for stmt in scope or []:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = _dotted(node.func) or ""
                    parts = name.split(".")
                    if len(parts) >= 2 and parts[-1] in self.ARRAY_CTORS \
                            and parts[0] in ("np", "numpy", "jnp"):
                        return True
        return False


# ---------------------------------------------------------------------------
# convenience driver


def analyze_tree(root: str, package: str = "kubernetes_tpu",
                 entry_points: dict | None = None,
                 donating: dict | None = None,
                 with_locks: bool = True,
                 apply_waiver_comments: bool = True) -> list[Finding]:
    """Run the full static suite (device-path rules + lock discipline)
    over `root/package`, apply inline waivers, return all findings
    (waived ones included, flagged)."""
    from .findings import apply_waivers, parse_waivers
    from .locks import LockChecker

    an = JaxsanAnalyzer(root, package=package, entry_points=entry_points,
                        donating=donating).load()
    findings = an.run()
    for entry in an.missing_entries:
        findings.append(Finding(
            rule="traced-branch", path=package.replace(".", os.sep),
            line=1,
            message=f"JIT entry point {entry} not found or does not reach "
                    "a jitted function (lint coverage lost)"))
    if with_locks:
        findings.extend(LockChecker(an.modules).run())
    if apply_waiver_comments:
        waivers = {mi.path: parse_waivers(mi.source)
                   for mi in an.modules.values()}
        apply_waivers(findings, waivers)
    return findings
