"""Device cost model: flops/bytes per compiled kernel variant (ISSUE 20).

The kernel observatory records how long every JIT entry RUNS per
plan/shape variant, but not what the variant COSTS — so a kernel row
could not say whether it is compute-bound or memory-bound, and ROADMAP
item 5's autotuner has no roofline to search against. This module is
that cost table:

- when `CompileLedger.measured_call` detects a fresh compile it reports
  the (kernel, jitted fn, args) here ONCE per plan/shape key. The model
  asks XLA for the variant's cost via `Lowered.cost_analysis()` —
  tracing + lowering only, never a second XLA compile (measured ~4ms
  for a small program on this container's jax 0.4.37, paid only on
  compile events) — and falls back to a per-kernel HOST ESTIMATOR
  (`KERNEL_COSTS` coefficients over the args' array cells/bytes) where
  XLA reports nothing. Every jaxsan ENTRY_POINT's kernel MUST have a
  `KERNEL_COSTS` entry: tools/check.py `cost_model_gaps` (exit 2)
  mirrors `observatory_gaps`, so a new JIT entry cannot land uncosted.
- per (flops, bytes) row the model derives arithmetic intensity and —
  against the backend's roofline anchors (`PEAKS`) — a modeled runtime
  `max(flops/peak_flops, bytes/peak_bw)`, the achieved-vs-modeled
  fraction once the observatory has a measured warm p50 for the same
  plan key, and a boundness verdict: compute-bound vs memory-bound by
  intensity against the ridge point, comms-bound when the sharded-lane
  profile attributes the majority of the kernel's window to
  collectives.

Rows are bounded by the observatory's own MAX_PLAN_KEYS discipline and
surface at /debug/kernels, in tools/kernel_sweep.py sweep points, and as
the per-backend cost table the critical-path verdicts read.
"""

from __future__ import annotations

import threading

# Roofline anchors per JAX backend: (peak flops/s, peak bytes/s). These
# are deliberately coarse single-socket/single-device numbers — the
# achieved fraction is a RATIO used to rank variants and spot order-of-
# magnitude gaps, not a vendor benchmark. Overridable per-process via
# `set_peaks` (the accelerator tier of ROADMAP item 5 calibrates them).
PEAKS = {
    "cpu": (1.0e11, 2.0e10),     # ~100 GFLOP/s, ~20 GB/s per socket
    "gpu": (1.0e13, 1.0e12),     # ~10 TFLOP/s, ~1 TB/s HBM
    "tpu": (1.0e14, 1.2e12),     # ~100 TFLOP/s bf16, ~1.2 TB/s HBM
}
_DEFAULT_PEAKS = (1.0e11, 2.0e10)

# Host-estimator coefficients per ledger kernel: (flops per array cell,
# bytes-accessed multiplier over the args' raw bytes). The flops
# coefficients encode each kernel's work shape — the scoring/filter
# kernels do a few tens of ops per node-pod cell, the scan/wave kernels
# revisit the carry per segment, the probe/diagnose reductions are
# single-pass. Used ONLY where XLA's cost_analysis reports nothing;
# rows carry source="host" so readers know the provenance.
# tools/check.py cost_model_gaps asserts every ENTRY_KERNELS target has
# an entry here.
KERNEL_COSTS = {
    "run_batch": (48.0, 3.0),
    "run_uniform": (32.0, 3.0),
    "run_wave": (64.0, 4.0),
    "run_wave_scan": (96.0, 5.0),
    "run_plan": (48.0, 3.0),
    "wave_statics": (8.0, 2.0),
    "diagnose": (16.0, 2.0),
    "dry_run": (40.0, 3.0),
    "run_gang": (64.0, 4.0),
    "scatter_rows": (2.0, 2.0),
    "explain_row": (16.0, 2.0),
    "cluster_probe": (24.0, 2.0),
    "run_batch_sharded": (48.0, 4.0),
    "run_uniform_sharded": (32.0, 4.0),
    "run_plan_sharded": (48.0, 4.0),
    "run_gang_sharded": (64.0, 5.0),
    "scatter_rows_sharded": (2.0, 3.0),
    "cluster_probe_sharded": (24.0, 3.0),
}

BOUND_COMPUTE = "compute_bound"
BOUND_MEMORY = "memory_bound"
BOUND_COMMS = "comms_bound"

# a sharded kernel whose lane profile attributes more than this share of
# the device window to collectives is comms-bound regardless of its
# arithmetic intensity — the roofline it sits under is the interconnect
COMMS_BOUND_SHARE = 0.35


def set_peaks(backend: str, peak_flops: float, peak_bw: float) -> None:
    """Calibration hook (ROADMAP item 5 accelerator tier)."""
    PEAKS[backend] = (float(peak_flops), float(peak_bw))


def peaks(backend: str):
    return PEAKS.get(backend, _DEFAULT_PEAKS)


def host_estimate(kernel: str, args: tuple) -> tuple:
    """(flops, bytes) from the dispatch args alone — the fallback when
    XLA reports nothing. Cells = total array elements across args;
    bytes = the args' raw bytes times the kernel's revisit multiplier."""
    coeff = KERNEL_COSTS.get(kernel)
    if coeff is None:
        return (0.0, 0.0)
    flops_per_cell, byte_mult = coeff
    cells = 0
    nbytes = 0
    for a in args:
        sh = getattr(a, "shape", None)
        if sh is not None:
            n = 1
            for d in sh:
                n *= int(d)
            cells += n
            nbytes += int(getattr(a, "nbytes", 0) or 0)
            continue
        if hasattr(a, "_fields"):
            for f in a:
                fsh = getattr(f, "shape", None)
                if fsh is None:
                    continue
                n = 1
                for d in fsh:
                    n *= int(d)
                cells += n
                nbytes += int(getattr(f, "nbytes", 0) or 0)
    return (float(cells) * flops_per_cell, float(nbytes) * byte_mult)


def xla_cost(fn, args: tuple, kw: dict) -> tuple:
    """(flops, bytes) from XLA's HLO cost analysis of the jitted fn's
    LOWERING (no second compile), or (0, 0) when the backend/API
    reports nothing — the caller falls back to the host estimator."""
    try:
        ca = fn.lower(*args, **kw).cost_analysis()
    except Exception:
        return (0.0, 0.0)
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return (0.0, 0.0)
    flops = float(ca.get("flops", 0.0) or 0.0)
    nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops < 0.0:
        flops = 0.0
    if nbytes < 0.0:
        nbytes = 0.0
    return (flops, nbytes)


def modeled_seconds(flops: float, nbytes: float, backend: str) -> float:
    """Roofline runtime: whichever of the compute and memory walls is
    binding for the variant on this backend."""
    pf, pb = peaks(backend)
    return max(flops / pf if pf > 0 else 0.0,
               nbytes / pb if pb > 0 else 0.0)


def classify(flops: float, nbytes: float, backend: str,
             comms_share: float = 0.0) -> str:
    """compute/memory/comms-bound for one (flops, bytes) row: comms wins
    when the lane profile says collectives own the window; otherwise
    arithmetic intensity against the backend's ridge point."""
    if comms_share > COMMS_BOUND_SHARE:
        return BOUND_COMMS
    pf, pb = peaks(backend)
    ridge = pf / pb if pb > 0 else 0.0
    ai = flops / nbytes if nbytes > 0 else float("inf")
    return BOUND_COMPUTE if ai >= ridge else BOUND_MEMORY


class CostModel:
    """Per-(kernel, plan-key) cost rows, filled once per fresh compile.

    Owned by the KernelObservatory (one instance behind its GLOBAL);
    thread-safe the same way — compiles land from the host loop, the
    standby scheduler and the audit worker."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (kernel, plan_key) -> {"flops","bytes","source"}
        self.rows: dict = {}

    def record_compile(self, kernel: str, fn, args: tuple,
                       kw: dict) -> None:
        """One fresh compile: cost the new variant unless its plan key
        is already costed (re-compiles of a known shape are donation/
        cache churn, not new variants)."""
        from .observatory import MAX_PLAN_KEYS, _shape_key
        key = (kernel, _shape_key(args))
        with self._lock:
            if key in self.rows:
                return
            # bound memory like the observatory's plan histograms: past
            # the cap new variants fold into the overflow row
            if sum(1 for k, _p in self.rows if k == kernel) \
                    >= MAX_PLAN_KEYS:
                key = (kernel, "~other")
                if key in self.rows:
                    return
            self.rows[key] = None          # claim under the lock
        flops, nbytes = xla_cost(fn, args, kw)
        source = "xla"
        if flops <= 0.0 and nbytes <= 0.0:
            flops, nbytes = host_estimate(kernel, args)
            source = "host"
        with self._lock:
            self.rows[key] = {"flops": flops, "bytes": nbytes,
                              "source": source}

    def kernel_rows(self, kernel: str) -> dict:
        """{plan_key: row} for one kernel (completed rows only)."""
        with self._lock:
            return {plan: dict(row) for (k, plan), row in self.rows.items()
                    if k == kernel and row is not None}

    def covered(self) -> set:
        """Kernels with at least one completed cost row."""
        with self._lock:
            return {k for (k, _p), row in self.rows.items()
                    if row is not None}

    def reset(self) -> None:
        with self._lock:
            self.rows.clear()
