"""Continuous sampling host profiler with drain-phase attribution.

The ROADMAP's remaining throughput gap is host-side Python
(pod ingest/commit ≈60% of a SchedulingBasic cycle), and the
`drain_phase` series only says WHICH coarse phase burns the time — not
which functions inside it. This module closes that gap the way
production continuous profilers (pprof, py-spy, Parca) do, without a
native agent:

- a background daemon thread samples the host-loop thread's Python stack
  via `sys._current_frames()` at `hz` (config knob `hostProfilerHz`,
  default ~200Hz; feature gate `ContinuousHostProfiling`);
- every sample is tagged with the currently-open drain phase — the
  innermost `utils/tracing.py` span name, read from the scheduler's
  `PhaseTrack` (host_snapshot / host_tensorize / host_group_seed /
  host_cache / device / commit, "other" outside a drain) — and with the
  dispatching drain's pod-signature cardinality bucket, so host cost is
  attributable per phase AND per signature-cardinality regime;
- samples aggregate into per-second buckets (a bounded ring), so
  `/debug/hostprofile?seconds=N` can render any trailing window without
  keeping raw samples;
- exports: collapsed-stack text (flamegraph.pl / speedscope both ingest
  it), speedscope JSON, a self/cumulative frame table, per-phase sample
  shares (cross-checkable against the `drain_phase` wall-clock shares),
  and top-N hottest frames (attached to slow FlightRecorder drains).

Overhead: one `sys._current_frames()` walk per tick (~10-30µs for a
50-frame stack) — ≈0.5% of one core at 200Hz, which is what keeps the
profiler ALWAYS-ON rather than a debugging session. The thread holds
only a weakref to its owner: when the Scheduler is collected, the
sampler exits on its next tick.
"""

from __future__ import annotations

import os
import sys
import threading
import time as _time
import weakref
from collections import deque
from typing import Callable, Optional


def _pow2_bucket(n: int) -> int:
    """Signature-cardinality bucket: next power of two ≥ n (0 stays 0)."""
    return 1 << (int(n) - 1).bit_length() if n > 0 else 0


class ProfileAggregate:
    """One window's aggregated samples: (phase, sig_bucket, stack) → count.

    Stacks are tuples of frame strings, root-first (the collapsed-stack
    orientation). Merging two aggregates is a dict add — that is what
    makes the per-second ring cheap to query for any trailing window."""

    __slots__ = ("counts", "total")

    def __init__(self) -> None:
        self.counts: dict[tuple, int] = {}
        self.total = 0

    def add(self, key: tuple, n: int = 1) -> None:
        self.counts[key] = self.counts.get(key, 0) + n
        self.total += n

    def merge(self, other: "ProfileAggregate") -> None:
        for key, n in other.counts.items():
            self.add(key, n)


class HostProfiler:
    """Sampling profiler bound to one host-loop thread (see module doc)."""

    def __init__(self, hz: float = 200.0,
                 phase_fn: Optional[Callable[[], str]] = None,
                 bucket_fn: Optional[Callable[[], int]] = None,
                 owner: Optional[object] = None,
                 max_depth: int = 128,
                 window_s: int = 900):
        self.hz = float(hz)
        self.phase_fn = phase_fn
        self.bucket_fn = bucket_fn
        self._owner_ref = weakref.ref(owner) if owner is not None else None
        self.max_depth = max_depth
        self._lock = threading.Lock()
        # per-second aggregation ring: (epoch_second, ProfileAggregate) —
        # written by the sampler thread, read by the debug HTTP thread
        self._ring: deque[tuple[int, ProfileAggregate]] = deque(
            maxlen=max(int(window_s), 1))               # guarded_by: _lock
        # code object → label memo: sampler-thread-private (built during
        # the stack walk, before the lock is taken)
        self._frame_names: dict[object, str] = {}
        self.target_tid: Optional[int] = None
        self.sample_count = 0      # guarded_by: _lock
        self.dropped = 0           # sampler-thread-private miss counter
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # drains slower than this get their top frames pinned onto the
        # flight-recorder entry (Scheduler reads the attribute)
        self.slow_drain_s = 0.25

    # -- lifecycle ------------------------------------------------------------

    def ensure_running(self) -> None:
        """Start (or retarget) the sampler from the host-loop thread; the
        Scheduler calls this at the top of every schedule entry point, so
        the profiler always follows whichever thread drives the loop."""
        tid = threading.get_ident()
        if self.target_tid != tid:
            self.target_tid = tid
        if self._thread is None and not self._stop.is_set():
            self._thread = threading.Thread(
                target=self._run, name="ktpu-host-profiler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        interval = 1.0 / max(self.hz, 1e-3)
        while not self._stop.wait(interval):
            if self._owner_ref is not None and self._owner_ref() is None:
                break   # owner collected: nothing left to profile
            try:
                self.sample_once()
            except Exception:   # pragma: no cover - sampling must not die
                self.dropped += 1

    # -- sampling -------------------------------------------------------------

    def _frame_label(self, code) -> str:
        label = self._frame_names.get(code)
        if label is None:
            name = getattr(code, "co_qualname", code.co_name)
            label = f"{os.path.basename(code.co_filename)}:{name}"
            self._frame_names[code] = label
        return label

    def sample_once(self, frame=None) -> bool:
        """Take one sample of the target thread (or of an explicitly
        injected `frame`, the deterministic test hook). Returns True when
        a sample was recorded."""
        if frame is None:
            if self.target_tid is None:
                return False
            frame = sys._current_frames().get(self.target_tid)
            if frame is None:
                self.dropped += 1
                return False
        stack = []
        depth = 0
        while frame is not None and depth < self.max_depth:
            stack.append(self._frame_label(frame.f_code))
            frame = frame.f_back
            depth += 1
        stack.reverse()   # root-first
        phase = (self.phase_fn() if self.phase_fn is not None else "") \
            or "other"
        bucket = (_pow2_bucket(self.bucket_fn())
                  if self.bucket_fn is not None else 0)
        key = (phase, bucket, tuple(stack))
        sec = int(_time.time())
        with self._lock:
            if self._ring and self._ring[-1][0] == sec:
                agg = self._ring[-1][1]
            else:
                agg = ProfileAggregate()
                self._ring.append((sec, agg))
            agg.add(key)
            self.sample_count += 1
        return True

    # -- querying -------------------------------------------------------------

    def aggregate(self, seconds: Optional[float] = None) -> ProfileAggregate:
        """Merged aggregate of the trailing `seconds` window (None = the
        whole retained ring)."""
        cutoff = None if seconds is None else int(_time.time() - seconds)
        out = ProfileAggregate()
        with self._lock:
            for sec, agg in self._ring:
                if cutoff is None or sec >= cutoff:
                    out.merge(agg)
        return out

    def phase_shares(self, seconds: Optional[float] = None) -> dict:
        """phase → fraction of samples; the profiler-side number the
        `drain_phase` wall-clock shares must agree with."""
        agg = self.aggregate(seconds)
        if not agg.total:
            return {}
        by_phase: dict[str, int] = {}
        for (phase, _bucket, _stack), n in agg.counts.items():
            by_phase[phase] = by_phase.get(phase, 0) + n
        return {p: n / agg.total for p, n in sorted(by_phase.items())}

    def frame_table(self, seconds: Optional[float] = None,
                    phase: Optional[str] = None) -> list[dict]:
        """Self/cumulative sample counts per frame, hottest-self first."""
        agg = self.aggregate(seconds)
        self_c: dict[str, int] = {}
        cum_c: dict[str, int] = {}
        for (p, _bucket, stack), n in agg.counts.items():
            if phase is not None and p != phase:
                continue
            if not stack:
                continue
            self_c[stack[-1]] = self_c.get(stack[-1], 0) + n
            for f in set(stack):    # cumulative counts each frame once
                cum_c[f] = cum_c.get(f, 0) + n
        return [{"frame": f, "self": s, "cum": cum_c[f]}
                for f, s in sorted(self_c.items(),
                                   key=lambda kv: (-kv[1], kv[0]))]

    def top_frames(self, n: int = 5, seconds: Optional[float] = None,
                   phase: Optional[str] = None) -> list[str]:
        """["frame self_count/total" ...] — the FlightRecorder / bench
        `host_top_frames` form."""
        table = self.frame_table(seconds, phase=phase)
        total = sum(row["self"] for row in table) or 1
        return [f"{row['frame']} {row['self']}/{total}"
                for row in table[:n]]

    # -- export ---------------------------------------------------------------

    def collapsed(self, seconds: Optional[float] = None,
                  tag_phase: bool = True) -> str:
        """flamegraph.pl collapsed-stack format, one line per distinct
        stack: `phase;frame;frame count`. The phase is the ROOT frame so
        the flamegraph's first tier is the drain-phase split."""
        agg = self.aggregate(seconds)
        lines = []
        for (phase, bucket, stack), n in sorted(agg.counts.items()):
            frames = list(stack)
            if tag_phase:
                tag = f"{phase}" + (f"[sigs≤{bucket}]" if bucket else "")
                frames = [tag] + frames
            lines.append(";".join(frames) + f" {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def speedscope(self, seconds: Optional[float] = None,
                   name: str = "ktpu-host-profile") -> dict:
        """speedscope JSON (sampled evented profile) — load the payload at
        https://www.speedscope.app. Sample weights are whole ticks."""
        agg = self.aggregate(seconds)
        frames: list[dict] = []
        index: dict[str, int] = {}
        samples: list[list[int]] = []
        weights: list[float] = []
        for (phase, bucket, stack), n in sorted(agg.counts.items()):
            tag = f"{phase}" + (f"[sigs≤{bucket}]" if bucket else "")
            ids = []
            for f in (tag, *stack):
                i = index.get(f)
                if i is None:
                    i = index[f] = len(frames)
                    frames.append({"name": f})
                ids.append(i)
            samples.append(ids)
            weights.append(float(n))
        total = sum(weights)
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": [{
                "type": "sampled", "name": name, "unit": "none",
                "startValue": 0, "endValue": total,
                "samples": samples, "weights": weights,
            }],
            "exporter": "kubernetes_tpu.perf.profiler",
            "name": name,
        }

    def write_collapsed(self, path: str,
                        seconds: Optional[float] = None) -> int:
        """Write the collapsed profile; returns distinct-stack count."""
        text = self.collapsed(seconds)
        with open(path, "w") as f:
            f.write(text)
        return len(text.splitlines())
