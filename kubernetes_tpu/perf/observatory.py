"""Kernel observatory: per-dispatch device-time attribution.

`perf/ledger.py` counts compiles and transfer bytes, but until ISSUE 14
nobody recorded how long each of the thirteen JIT kernels actually RUNS
per plan/shape variant — so the `device` phase span was a black box,
ROADMAP item 1's sharded-mesh gap could not be decomposed into
compute-vs-comms-vs-dispatch, and item 6's autotuner had no measurement
substrate to read. This module is that substrate:

- `CompileLedger.measured_call` (which already intercepts every JIT
  entry) reports each dispatch here via `on_call`: kernel name, wall
  seconds, whether the call compiled, and the call's args — from which a
  cheap shape signature is derived (array shapes, NamedTuple field
  shapes, static ints like the uniform L/K/J). Warm dispatch walls feed
  bounded streaming histograms keyed `(kernel, shape-sig)`; compiling
  calls stay out of the run histograms (their wall is trace+compile —
  the split the ledger's `runSeconds` bugfix records).
- a per-drain device-lane capture (`begin_drain`/`end_drain`, thread
  local so the standby scheduler and audit worker don't interleave):
  the scheduler brackets its `device_dispatch` span with it, stamps the
  per-kernel seconds into the FlightRecorder, and attaches the events
  as `lane="device"` child spans so the Chrome-trace export shows one
  host+device timeline (utils/tracing.py gives them their own track).
- the sharded-lane profile (parallel/sharding.py `profile_shard_lanes`)
  parks its latest result here; /debug/kernels and the
  `scheduler_shard_*` metric families read it back.

The observatory is PROCESS-GLOBAL (`GLOBAL`) for the same reason the
ledger is: the jit caches it observes are process-global. The
`KernelObservatory` feature gate (Beta/on) of the most recently
constructed Scheduler wins, mirroring the SanitizerRails pattern.
Memory is bounded: fixed log-spaced histogram buckets, at most
`MAX_PLAN_KEYS` per-plan histograms per kernel (overflow folds into
`~other`).
"""

from __future__ import annotations

import threading

from .costmodel import CostModel, classify, modeled_seconds
from .ledger import KERNELS

# log2-spaced bucket edges, 1µs .. ~67s: edge[i] = 1e-6 * 2**i. A
# dispatch landing beyond the last edge folds into the final bucket —
# bounded memory, and nothing a scheduler drain does should take longer.
_EDGES = tuple(1e-6 * (2.0 ** i) for i in range(27))

# distinct per-plan/shape histograms kept per kernel; the tail folds
# into "~other" so shape churn can't grow the observatory unboundedly
MAX_PLAN_KEYS = 32
_OVERFLOW_KEY = "~other"

# jaxsan ENTRY_POINT function name → ledger/observatory kernel name.
# tools/check.py walks this: a JIT entry missing here (or mapping to an
# unknown kernel) fails the config check — a new kernel cannot land
# unmeasured. The names differ where the public wrapper is not the
# kernel ("diagnose_row" dispatches the "diagnose" reductions).
ENTRY_KERNELS = {
    "run_batch": "run_batch",
    "run_uniform": "run_uniform",
    "run_wave": "run_wave",
    "run_wave_scan": "run_wave_scan",
    "run_plan": "run_plan",
    "wave_statics": "wave_statics",
    "diagnose_row": "diagnose",
    "dry_run_select_victims": "dry_run",
    "scatter_rows": "scatter_rows",
    "explain_row": "explain_row",
    "cluster_probe": "cluster_probe",
    "run_gang": "run_gang",
    "run_batch_sharded": "run_batch_sharded",
    "run_uniform_sharded": "run_uniform_sharded",
    "run_plan_sharded": "run_plan_sharded",
    "run_gang_sharded": "run_gang_sharded",
    "scatter_rows_sharded": "scatter_rows_sharded",
    "cluster_probe_sharded": "cluster_probe_sharded",
}


def _quantile(counts, total: int, q: float) -> float:
    """q-quantile in seconds from bucket counts (geometric bucket
    midpoint — the log2 lattice makes that exact to within ~√2)."""
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank and c:
            lo = _EDGES[i]
            hi = _EDGES[i + 1] if i + 1 < len(_EDGES) else _EDGES[-1] * 2.0
            return (lo * hi) ** 0.5
    return _EDGES[-1]


class StreamingHist:
    """Bounded streaming histogram over the fixed log2 second lattice."""

    __slots__ = ("counts", "count", "sum", "max")

    def __init__(self) -> None:
        self.counts = [0] * len(_EDGES)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        i = 0
        # linear scan beats bisect here: dispatches cluster in the
        # 0.1-10ms decades, ~12 comparisons
        while i + 1 < len(_EDGES) and seconds >= _EDGES[i + 1]:
            i += 1
        self.counts[i] += 1
        self.count += 1
        self.sum += seconds
        if seconds > self.max:
            self.max = seconds

    def quantile(self, q: float) -> float:
        return _quantile(self.counts, self.count, q)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "seconds": round(self.sum, 6),
            "p50_ms": round(self.quantile(0.50) * 1e3, 4),
            "p90_ms": round(self.quantile(0.90) * 1e3, 4),
            "p99_ms": round(self.quantile(0.99) * 1e3, 4),
            "max_ms": round(self.max * 1e3, 4),
        }


class _KernelStats:
    """One kernel's run-time profile: the merged histogram plus the
    bounded per-plan/shape breakdown."""

    __slots__ = ("hist", "plans", "dispatches", "compile_calls")

    def __init__(self) -> None:
        self.hist = StreamingHist()
        self.plans: dict = {}
        self.dispatches = 0      # every call, compiling or warm
        self.compile_calls = 0   # calls excluded from the run histogram

    def plan_hist(self, key) -> StreamingHist:
        h = self.plans.get(key)
        if h is None:
            if len(self.plans) >= MAX_PLAN_KEYS:
                key = _OVERFLOW_KEY
                h = self.plans.get(key)
                if h is None:
                    h = self.plans[key] = StreamingHist()
                return h
            h = self.plans[key] = StreamingHist()
        return h


def _shape_key(args) -> tuple:
    """Cheap, hashable shape signature of a dispatch's positional args:
    array shapes, one level of NamedTuple field shapes, and static ints
    (the uniform L/K/J, the gang need). Static config NamedTuples
    contribute an empty tuple; meshes and floats are ignored — they
    never change a kernel's executable without a shape changing too."""
    parts = []
    for a in args:
        sh = getattr(a, "shape", None)
        if sh is not None:
            parts.append(tuple(sh))
            continue
        if hasattr(a, "_fields"):
            parts.append(tuple(
                tuple(s) for s in (getattr(f, "shape", None) for f in a)
                if s is not None))
            continue
        if isinstance(a, (bool, int)):
            parts.append(int(a))
    return tuple(parts)


class KernelObservatory:
    """Process-wide per-dispatch run-time attribution (module docstring)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._enabled = True
        # pre-seed every instrumented kernel so /debug/kernels (and the
        # metric mirror) list all thirteen before the first dispatch
        self.kernels: dict[str, _KernelStats] = {
            k: _KernelStats() for k in KERNELS}
        self._backend = ""
        self._shard_profile: dict = {}
        self._tl = threading.local()
        # device cost model (perf/costmodel.py, ISSUE 20): per-variant
        # flops/bytes rows, filled on compile events. Gated separately
        # (`CriticalPathObservatory`) so the run-time histograms keep
        # working with the cost model off.
        self.costs = CostModel()
        self._cost_enabled = True

    # -- gate -----------------------------------------------------------------

    def enable(self, on: bool = True) -> None:
        self._enabled = bool(on)

    def enable_cost_model(self, on: bool = True) -> None:
        """`CriticalPathObservatory` gate hook (scheduler ctor): the
        most recently constructed Scheduler wins, like `enable`."""
        self._cost_enabled = bool(on)

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def cost_model_enabled(self) -> bool:
        return self._enabled and self._cost_enabled

    # -- capture --------------------------------------------------------------

    def backend(self) -> str:
        if not self._backend:
            try:
                import jax
                self._backend = jax.default_backend()
            except Exception:  # pragma: no cover - jax always importable
                self._backend = "unknown"
        return self._backend

    def on_call(self, kernel: str, start: float, seconds: float,
                compiled: bool, args: tuple) -> None:
        """One dispatch, reported by `CompileLedger.measured_call`.
        `start` is the perf_counter at call entry (the tracer's clock, so
        lane events nest inside the drain's device span)."""
        if not self._enabled:
            return
        key = _shape_key(args)
        with self._lock:
            stats = self.kernels.get(kernel)
            if stats is None:
                stats = self.kernels[kernel] = _KernelStats()
            stats.dispatches += 1
            if compiled:
                # trace+compile wall stays out of the run histograms —
                # the ledger's compile split records it
                stats.compile_calls += 1
            else:
                stats.hist.observe(seconds)
                stats.plan_hist(key).observe(seconds)
        events = getattr(self._tl, "events", None)
        if events is not None:
            events.append((kernel, start, seconds, compiled))

    def on_compile(self, kernel: str, fn, args: tuple, kw: dict) -> None:
        """A fresh compile, reported by `CompileLedger.measured_call`
        (cache-size delta > 0): cost the new variant. Once per plan key;
        tracing+lowering only — never a second XLA compile."""
        if not (self._enabled and self._cost_enabled):
            return
        self.costs.record_compile(kernel, fn, args, kw)

    # -- per-drain device lane ------------------------------------------------

    def begin_drain(self) -> None:
        """Open the calling thread's dispatch capture window (the
        scheduler brackets its device_dispatch span with this)."""
        if self._enabled:
            self._tl.events = []

    def end_drain(self) -> list:
        """Close the capture window; returns [(kernel, start, seconds,
        compiled)] in dispatch order (empty when disabled)."""
        events = getattr(self._tl, "events", None)
        self._tl.events = None
        return events or []

    @staticmethod
    def lane_seconds(events: list) -> dict:
        """Per-kernel seconds of one drain's capture — the FlightRecord
        `kernels` stamp."""
        out: dict[str, float] = {}
        for kernel, _start, seconds, _compiled in events:
            out[kernel] = out.get(kernel, 0.0) + seconds
        return {k: round(v, 6) for k, v in out.items()}

    @staticmethod
    def lane_spans(events: list, drain_id: int = 0) -> list:
        """Capture events → `lane="device"` child Spans for the tracer's
        device_dispatch span (utils/tracing.py routes the lane onto its
        own Chrome-trace track)."""
        from ..utils.tracing import Span
        spans = []
        for kernel, start, seconds, compiled in events:
            attrs = {"lane": "device", "drain": drain_id}
            if compiled:
                attrs["compiled"] = True
            spans.append(Span(name=f"kernel:{kernel}", start=start,
                              duration_s=seconds, attributes=attrs))
        return spans

    # -- shard lanes ----------------------------------------------------------

    def set_shard_profile(self, profile: dict) -> None:
        with self._lock:
            self._shard_profile = dict(profile or {})

    def shard_profile(self) -> dict:
        with self._lock:
            return dict(self._shard_profile)

    # -- reporting ------------------------------------------------------------

    def _cost_table(self, name: str, st: _KernelStats, backend: str,
                    comms_share: float) -> list:
        """One kernel's cost-model rows (perf/costmodel.py), joined with
        the plan histograms' measured warm p50 for the achieved-vs-
        modeled fraction. Caller holds self._lock (the cost model's own
        lock nests inside — no reverse path exists)."""
        rows = []
        for plan, row in sorted(self.costs.kernel_rows(name).items(),
                                key=repr):
            flops = float(row["flops"])
            nbytes = float(row["bytes"])
            h = st.plans.get(plan)
            measured = (h.quantile(0.50)
                        if h is not None and h.count else 0.0)
            model_s = modeled_seconds(flops, nbytes, backend)
            rows.append({
                "plan": str(plan),
                "flops": flops,
                "bytes": nbytes,
                # arithmetic intensity (flops/byte) — the roofline x-axis
                "ai": round(flops / nbytes, 4) if nbytes > 0 else 0.0,
                "modeledMs": round(model_s * 1e3, 4),
                "measuredP50Ms": round(measured * 1e3, 4),
                # modeled/measured: the fraction of the backend roofline
                # this variant achieves (0.0 until a warm call lands)
                "achievedFraction": (round(model_s / measured, 4)
                                     if measured > 0 and model_s > 0
                                     else 0.0),
                "bound": classify(flops, nbytes, backend,
                                  comms_share=comms_share),
                "source": row["source"],
            })
        return rows

    def cost_view(self) -> dict:
        """{kernel: [cost rows]} for every kernel with at least one
        costed variant — tools/kernel_sweep.py's roofline annotation and
        the /debug/kernels costModel field share this."""
        backend = self.backend()
        out = {}
        with self._lock:
            shard_comms = float(self._shard_profile.get("commsShare",
                                                        0.0) or 0.0)
            for name, st in self.kernels.items():
                comms = shard_comms if name.endswith("_sharded") else 0.0
                rows = self._cost_table(name, st, backend, comms)
                if rows:
                    out[name] = rows
        return out

    def snapshot(self, top_plans: int = 5) -> dict:
        """/debug/kernels payload: per-kernel run-time table (all
        thirteen pre-seeded entries, zeros before the first dispatch),
        the top-N per-plan variants by cumulative seconds, each
        variant's cost-model rows, and the latest sharded-lane
        profile."""
        backend = self.backend()
        with self._lock:
            shard = dict(self._shard_profile)
            shard_comms = float(shard.get("commsShare", 0.0) or 0.0)
            kernels = {}
            for name in sorted(self.kernels):
                st = self.kernels[name]
                top = sorted(st.plans.items(),
                             key=lambda kv: kv[1].sum, reverse=True)
                comms = shard_comms if name.endswith("_sharded") else 0.0
                kernels[name] = st.hist.to_dict() | {
                    "dispatches": st.dispatches,
                    "compileCalls": st.compile_calls,
                    "plans": {str(k): h.to_dict()
                              for k, h in top[:top_plans]},
                    "costModel": self._cost_table(name, st, backend,
                                                  comms),
                }
        return {"enabled": self._enabled, "backend": backend,
                "costModelEnabled": self._cost_enabled,
                "kernels": kernels, "shardLanes": shard}

    def metrics_view(self) -> tuple:
        """({kernel: (dispatches, warm seconds)}, shard profile) — the
        scheduler_kernel_*/scheduler_shard_* mirror read at exposition
        time (metrics/__init__.py sync_observatory)."""
        with self._lock:
            return ({k: (st.dispatches, st.hist.sum)
                     for k, st in self.kernels.items()},
                    dict(self._shard_profile))

    def checkpoint(self) -> dict:
        """Opaque marker for `delta_since` (the bench harness brackets a
        run with it — the observatory is process-global, so absolute
        numbers mix warm-up and earlier workloads)."""
        with self._lock:
            return {k: (st.hist.count, st.hist.sum, tuple(st.hist.counts),
                        st.dispatches)
                    for k, st in self.kernels.items()}

    def delta_since(self, chk: dict) -> dict:
        """Per-kernel run-time stats accumulated since `chk`: counts and
        quantiles computed from the bucket-count difference."""
        out = {}
        with self._lock:
            for name, st in self.kernels.items():
                c0, s0, buckets0, d0 = chk.get(
                    name, (0, 0.0, (0,) * len(_EDGES), 0))
                count = st.hist.count - c0
                if count <= 0 and st.dispatches - d0 <= 0:
                    continue
                counts = [a - b for a, b in zip(st.hist.counts, buckets0)]
                out[name] = {
                    "calls": count,
                    "dispatches": st.dispatches - d0,
                    "seconds": round(st.hist.sum - s0, 6),
                    "p50_ms": round(
                        _quantile(counts, count, 0.50) * 1e3, 4),
                    "p99_ms": round(
                        _quantile(counts, count, 0.99) * 1e3, 4),
                }
        return out

    def reset(self) -> None:
        """Test hook, mirroring `CompileLedger.reset`."""
        with self._lock:
            self.kernels = {k: _KernelStats() for k in KERNELS}
            self._shard_profile = {}
        self.costs.reset()


GLOBAL = KernelObservatory()
