"""Compile ledger: device/compile cost capture for the JIT entry points.

The scheduler's cold-start and tail latency are dominated by a handful of
XLA executables (scan buckets, uniform L/K/J variants, wave kernels) and
by host↔device transfers. The reference has nothing comparable to
instrument — its hot path is host Go — but every measurement-driven
placement system (Gavel, arXiv:2008.09213; topology-aware co-located LLM
scheduling, arXiv:2411.11560) keeps an attributable cost profile of its
own scheduler loop. This module is that profile's device half:

- every public JIT entry (`ops/program.py` run_batch / run_uniform /
  run_wave / run_wave_scan / wave_statics / diagnose_row /
  dry_run_select_victims, `parallel/sharding.py` run_batch_sharded) calls
  through `measured_call`, which detects fresh compiles via the jitted
  function's `_cache_size()` delta and records per-kernel compile wall
  seconds, call counts, retraces (compiles beyond the first) and
  donated-buffer misses (a donated carry whose buffer survived the call —
  the donation was ignored, so the dispatch paid a full carry copy);
- host↔device transfer sites (`state/tensorize.py` node-array uploads,
  `ops/groups.py` group-tensor uploads, the signature-table upload and
  the drain readbacks) report byte counts via `note_h2d`, keyed by the
  drain phase that paid them;
- warm (non-compiling) call walls are recorded separately
  (`runCalls`/`runSeconds`), splitting the trace/compile cost out of
  `compileSeconds` (`compileOverheadSeconds`), and every dispatch is
  forwarded to the kernel observatory (perf/observatory.py) for
  per-plan run-time histograms and the per-drain device lane.

The ledger is PROCESS-GLOBAL (`GLOBAL`) because the jit caches it
observes are process-global; `SchedulerMetrics` mirrors it into
`scheduler_xla_compiles_total{kernel}`,
`scheduler_xla_compile_seconds{kernel}` and
`scheduler_h2d_bytes_total{phase}` at exposition time, and
`/debug/compileledger` serves the full snapshot (retraces and donation
misses included). A warm process re-running identical shapes must show a
ZERO compile delta — that invariant is the "no hidden retraces" test.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field


@dataclass
class KernelRecord:
    """Per-kernel compile/call accounting."""

    calls: int = 0
    compiles: int = 0            # fresh executables minted (cache-size delta)
    compile_seconds: float = 0.0  # wall time of calls that compiled
    donation_misses: int = 0     # donated carry not consumed by the call
    # warm-call accounting (ISSUE 14 bugfix): compile_seconds conflates
    # tracing+compile with the first execution; recording the run wall of
    # NON-compiling calls separately both fixes the split (the derived
    # compileOverheadSeconds below) and feeds the kernel observatory's
    # run-time histograms (perf/observatory.py)
    run_calls: int = 0
    run_seconds: float = 0.0     # wall time of calls that did NOT compile

    @property
    def retraces(self) -> int:
        """Compiles beyond the first: shape/static-arg churn minting extra
        executables for the same kernel (each one is 20-40s on a tunneled
        TPU — the thing shape-stable dispatch exists to avoid)."""
        return max(self.compiles - 1, 0)

    @property
    def compile_overhead_seconds(self) -> float:
        """compile_seconds minus the estimated execution share of the
        compiling calls (mean warm run wall × compiles) — the pure
        trace/compile cost, clamped at zero while no warm call has
        calibrated the estimate yet."""
        if not self.run_calls:
            return self.compile_seconds
        warm = self.run_seconds / self.run_calls
        return max(self.compile_seconds - warm * self.compiles, 0.0)

    def to_dict(self) -> dict:
        return {"calls": self.calls, "compiles": self.compiles,
                "retraces": self.retraces,
                "compileSeconds": round(self.compile_seconds, 3),
                "compileOverheadSeconds": round(
                    self.compile_overhead_seconds, 3),
                "runCalls": self.run_calls,
                "runSeconds": round(self.run_seconds, 6),
                "donationMisses": self.donation_misses}


# every instrumented kernel, pre-seeded into the metric families so
# dashboards see the series before the first dispatch
KERNELS = ("run_batch", "run_uniform", "run_wave", "run_wave_scan",
           "run_plan", "wave_statics", "diagnose", "dry_run",
           "run_batch_sharded", "run_uniform_sharded", "run_plan_sharded",
           "run_gang_sharded", "scatter_rows_sharded",
           "cluster_probe_sharded", "run_gang",
           "scatter_rows", "explain_row", "cluster_probe")

# h2d phase labels, aligned with scheduler_drain_phase_seconds{phase}
# where the transfer is paid (device_readback is the d2h direction of the
# same tunnel — kept in one family so transfer dashboards need one query)
H2D_PHASES = ("host_snapshot", "host_group_seed", "host_cache",
              "device_readback")


class CompileLedger:
    """Process-wide compile + transfer accounting (see module docstring).

    Record and snapshot are thread-safe (ISSUE 14): the standby
    scheduler's warm-up drains and the shadow-audit worker's replays
    dispatch kernels concurrently with the host loop, and all of them
    land here. The lock brackets only the counter updates — never the
    jitted call itself."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.kernels: dict[str, KernelRecord] = {}  # guarded_by: _lock
        self.h2d: dict[str, int] = {}               # guarded_by: _lock

    # -- compile capture ------------------------------------------------------

    def _rec(self, kernel: str) -> KernelRecord:  # jaxsan: holds _lock
        rec = self.kernels.get(kernel)
        if rec is None:
            rec = self.kernels[kernel] = KernelRecord()
        return rec

    @staticmethod
    def _cache_size(fn) -> int:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return -1
        try:
            return int(probe())
        except Exception:  # pragma: no cover - backend specific
            return -1

    def measured_call(self, kernel: str, fn, *args, donated=None, **kw):
        """Call jitted `fn`, attributing any fresh compile (cache-size
        delta) to `kernel`. `donated` is the carry the caller donated (or
        None when the backend compiles without donation): if its buffer
        survives the call, the donation was ignored and the dispatch paid
        a copy of the resident node state — counted as a miss."""
        before = self._cache_size(fn)
        t0 = _time.perf_counter()
        out = fn(*args, **kw)
        dt = _time.perf_counter() - t0
        delta = 0
        if before >= 0:
            delta = self._cache_size(fn) - before
        miss = False
        if donated is not None:
            # probe one leaf of the donated pytree; is_deleted() is the
            # jax.Array donation witness (True = buffer consumed)
            leaf = getattr(donated, "used", donated)
            deleted = getattr(leaf, "is_deleted", None)
            miss = deleted is not None and not deleted()
        with self._lock:
            rec = self._rec(kernel)
            rec.calls += 1
            if delta > 0:
                rec.compiles += delta
                rec.compile_seconds += dt
            else:
                rec.run_calls += 1
                rec.run_seconds += dt
            if miss:
                rec.donation_misses += 1
        # per-dispatch run-time attribution (perf/observatory.py): the
        # observatory decides itself whether its gate is on
        obs = _observatory()
        obs.on_call(kernel, t0, dt, delta > 0, args)
        if delta > 0:
            # a fresh executable was minted: cost the new variant
            # (perf/costmodel.py — trace+lower only, once per plan key)
            obs.on_compile(kernel, fn, args, kw)
        return out

    def wrap(self, kernel: str, fn):
        """Instrument a module-level jitted callable in place (the
        non-factory entry points); keeps the wrapped signature."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kw):
            return self.measured_call(kernel, fn, *args, **kw)

        wrapped.__wrapped__ = fn
        return wrapped

    # -- transfer capture -----------------------------------------------------

    def note_h2d(self, phase: str, nbytes: int) -> None:
        with self._lock:
            self.h2d[phase] = self.h2d.get(phase, 0) + int(nbytes)

    def note_h2d_tree(self, phase: str, tree) -> None:
        """Account every array leaf of a NamedTuple/iterable (the upload
        helpers all move whole structs)."""
        total = 0
        for leaf in tree:
            nb = getattr(leaf, "nbytes", None)
            if nb is not None:
                total += int(nb)
        if total:
            self.note_h2d(phase, total)

    # -- reporting ------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            recs = {k: r.to_dict() for k, r in sorted(self.kernels.items())}
            h2d = dict(sorted(self.h2d.items()))
            compiles = sum(r.compiles for r in self.kernels.values())
            compile_s = sum(r.compile_seconds
                            for r in self.kernels.values())
            run_s = sum(r.run_seconds for r in self.kernels.values())
            retraces = sum(r.retraces for r in self.kernels.values())
        return {
            "kernels": recs,
            "h2dBytes": h2d,
            "totalCompiles": compiles,
            "totalCompileSeconds": round(compile_s, 3),
            "totalRunSeconds": round(run_s, 6),
            "totalRetraces": retraces,
        }

    def reset(self) -> None:
        """Test hook: forget everything (the jit caches themselves are
        untouched, so a reset ledger on a warm process records zero
        compiles — exactly the warm-run invariant)."""
        with self._lock:
            self.kernels.clear()
            self.h2d.clear()


GLOBAL = CompileLedger()

# resolved lazily (observatory imports KERNELS from this module, so a
# top-level import back would be circular); cached after the first call
_OBS = None


def _observatory():
    global _OBS
    if _OBS is None:
        from .observatory import GLOBAL as _g
        _OBS = _g
    return _OBS
