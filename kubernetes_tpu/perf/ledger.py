"""Compile ledger: device/compile cost capture for the JIT entry points.

The scheduler's cold-start and tail latency are dominated by a handful of
XLA executables (scan buckets, uniform L/K/J variants, wave kernels) and
by host↔device transfers. The reference has nothing comparable to
instrument — its hot path is host Go — but every measurement-driven
placement system (Gavel, arXiv:2008.09213; topology-aware co-located LLM
scheduling, arXiv:2411.11560) keeps an attributable cost profile of its
own scheduler loop. This module is that profile's device half:

- every public JIT entry (`ops/program.py` run_batch / run_uniform /
  run_wave / run_wave_scan / wave_statics / diagnose_row /
  dry_run_select_victims, `parallel/sharding.py` run_batch_sharded) calls
  through `measured_call`, which detects fresh compiles via the jitted
  function's `_cache_size()` delta and records per-kernel compile wall
  seconds, call counts, retraces (compiles beyond the first) and
  donated-buffer misses (a donated carry whose buffer survived the call —
  the donation was ignored, so the dispatch paid a full carry copy);
- host↔device transfer sites (`state/tensorize.py` node-array uploads,
  `ops/groups.py` group-tensor uploads, the signature-table upload and
  the drain readbacks) report byte counts via `note_h2d`, keyed by the
  drain phase that paid them.

The ledger is PROCESS-GLOBAL (`GLOBAL`) because the jit caches it
observes are process-global; `SchedulerMetrics` mirrors it into
`scheduler_xla_compiles_total{kernel}`,
`scheduler_xla_compile_seconds{kernel}` and
`scheduler_h2d_bytes_total{phase}` at exposition time, and
`/debug/compileledger` serves the full snapshot (retraces and donation
misses included). A warm process re-running identical shapes must show a
ZERO compile delta — that invariant is the "no hidden retraces" test.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field


@dataclass
class KernelRecord:
    """Per-kernel compile/call accounting."""

    calls: int = 0
    compiles: int = 0            # fresh executables minted (cache-size delta)
    compile_seconds: float = 0.0  # wall time of calls that compiled
    donation_misses: int = 0     # donated carry not consumed by the call

    @property
    def retraces(self) -> int:
        """Compiles beyond the first: shape/static-arg churn minting extra
        executables for the same kernel (each one is 20-40s on a tunneled
        TPU — the thing shape-stable dispatch exists to avoid)."""
        return max(self.compiles - 1, 0)

    def to_dict(self) -> dict:
        return {"calls": self.calls, "compiles": self.compiles,
                "retraces": self.retraces,
                "compileSeconds": round(self.compile_seconds, 3),
                "donationMisses": self.donation_misses}


# every instrumented kernel, pre-seeded into the metric families so
# dashboards see the series before the first dispatch
KERNELS = ("run_batch", "run_uniform", "run_wave", "run_wave_scan",
           "run_plan", "wave_statics", "diagnose", "dry_run",
           "run_batch_sharded", "run_gang", "scatter_rows", "explain_row",
           "cluster_probe")

# h2d phase labels, aligned with scheduler_drain_phase_seconds{phase}
# where the transfer is paid (device_readback is the d2h direction of the
# same tunnel — kept in one family so transfer dashboards need one query)
H2D_PHASES = ("host_snapshot", "host_group_seed", "host_cache",
              "device_readback")


class CompileLedger:
    """Process-wide compile + transfer accounting (see module docstring)."""

    def __init__(self) -> None:
        self.kernels: dict[str, KernelRecord] = {}
        self.h2d: dict[str, int] = {}

    # -- compile capture ------------------------------------------------------

    def _rec(self, kernel: str) -> KernelRecord:
        rec = self.kernels.get(kernel)
        if rec is None:
            rec = self.kernels[kernel] = KernelRecord()
        return rec

    @staticmethod
    def _cache_size(fn) -> int:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return -1
        try:
            return int(probe())
        except Exception:  # pragma: no cover - backend specific
            return -1

    def measured_call(self, kernel: str, fn, *args, donated=None, **kw):
        """Call jitted `fn`, attributing any fresh compile (cache-size
        delta) to `kernel`. `donated` is the carry the caller donated (or
        None when the backend compiles without donation): if its buffer
        survives the call, the donation was ignored and the dispatch paid
        a copy of the resident node state — counted as a miss."""
        rec = self._rec(kernel)
        before = self._cache_size(fn)
        t0 = _time.perf_counter()
        out = fn(*args, **kw)
        rec.calls += 1
        if before >= 0:
            delta = self._cache_size(fn) - before
            if delta > 0:
                rec.compiles += delta
                rec.compile_seconds += _time.perf_counter() - t0
        if donated is not None:
            # probe one leaf of the donated pytree; is_deleted() is the
            # jax.Array donation witness (True = buffer consumed)
            leaf = getattr(donated, "used", donated)
            deleted = getattr(leaf, "is_deleted", None)
            if deleted is not None and not deleted():
                rec.donation_misses += 1
        return out

    def wrap(self, kernel: str, fn):
        """Instrument a module-level jitted callable in place (the
        non-factory entry points); keeps the wrapped signature."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kw):
            return self.measured_call(kernel, fn, *args, **kw)

        wrapped.__wrapped__ = fn
        return wrapped

    # -- transfer capture -----------------------------------------------------

    def note_h2d(self, phase: str, nbytes: int) -> None:
        self.h2d[phase] = self.h2d.get(phase, 0) + int(nbytes)

    def note_h2d_tree(self, phase: str, tree) -> None:
        """Account every array leaf of a NamedTuple/iterable (the upload
        helpers all move whole structs)."""
        total = 0
        for leaf in tree:
            nb = getattr(leaf, "nbytes", None)
            if nb is not None:
                total += int(nb)
        if total:
            self.note_h2d(phase, total)

    # -- reporting ------------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "kernels": {k: r.to_dict()
                        for k, r in sorted(self.kernels.items())},
            "h2dBytes": dict(sorted(self.h2d.items())),
            "totalCompiles": sum(r.compiles for r in self.kernels.values()),
            "totalCompileSeconds": round(
                sum(r.compile_seconds for r in self.kernels.values()), 3),
            "totalRetraces": sum(r.retraces for r in self.kernels.values()),
        }

    def reset(self) -> None:
        """Test hook: forget everything (the jit caches themselves are
        untouched, so a reset ledger on a warm process records zero
        compiles — exactly the warm-run invariant)."""
        self.kernels.clear()
        self.h2d.clear()


GLOBAL = CompileLedger()
