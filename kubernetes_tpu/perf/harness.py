"""scheduler_perf harness: YAML-driven workloads + throughput collection.

Ports the reference benchmark contract
(test/integration/scheduler_perf/scheduler_perf.go):
- testCases loaded from YAML (`:1217` RunBenchmarkPerfScheduling): each has a
  `workloadTemplate` of ops and parameterized `workloads` with an optional
  `threshold` (minimum average pods/s, the failure gate, `:375-430`).
- op registry (`:518-552`): createNodes, createPods (collectMetrics),
  churn, barrier, sleep.
- throughputCollector (util.go:457-660): average scheduled-pods/s over the
  measured phase, plus percentile summaries of per-batch scheduling rates.

The measured window covers creation + ingestion + scheduling + binds, like
the reference's wall-clock sampler: pods stream in `createBatch`-sized
chunks (default 512), each chunk is dispatched without waiting
(`schedule_pending(wait=False)` — the async commit pipeline), and the
collector samples cumulative scheduled counts per chunk, giving
count/createBatch rate windows for real percentiles.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import yaml

from ..api.types import PodStatus
from ..api.types import _shallow as _SHALLOW
from ..backend.apiserver import APIServer
from ..scheduler import Scheduler
from ..testing.wrappers import _counter
from ..testing.wrappers import make_node, make_pod

LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_HOSTNAME = "kubernetes.io/hostname"


@dataclass
class Workload:
    name: str
    params: dict
    labels: list[str] = field(default_factory=list)
    threshold: float = 0.0


@dataclass
class TestCase:
    name: str
    workload_template: list[dict]
    workloads: list[Workload]
    default_pod_template: Optional[dict] = None


def load_test_cases(path: str) -> list[TestCase]:
    with open(path) as f:
        raw = yaml.safe_load(f)
    cases = []
    for tc in raw:
        workloads = [Workload(name=w["name"], params=w.get("params", {}),
                              labels=w.get("labels", []),
                              threshold=w.get("threshold", 0.0))
                     for w in tc.get("workloads", [])]
        cases.append(TestCase(name=tc["name"],
                              workload_template=tc["workloadTemplate"],
                              workloads=workloads,
                              default_pod_template=tc.get("defaultPodTemplate")))
    return cases


def _resolve(op: dict, key: str, params: dict, default=None):
    """countParam: $foo indirection (scheduler_perf.go op params)."""
    pkey = op.get(key + "Param")
    if pkey is not None:
        return params[pkey.lstrip("$")]
    return op.get(key, default)


@dataclass
class DataItem:
    """One measured phase (util.go DataItem)."""

    name: str
    average: float          # pods/s over the measured phase
    perc50: float = 0.0     # per-window rate percentiles
    perc95: float = 0.0
    perc99: float = 0.0
    pods: int = 0
    duration_s: float = 0.0
    samples: int = 0        # rate windows behind the percentiles
    # per-op wall times for the WHOLE workload run, as ("opcode[i]", s)
    # pairs — lets bench.py report phases OUTSIDE the measured window
    # (e.g. PreemptionChurn's preemptor wave) without widening it
    op_seconds: list = field(default_factory=list)
    # scheduler-side breakdown (drain phases, wave placement stats) pulled
    # from the metrics registry after the run — bench.py merges these into
    # each case's extras
    extras: dict = field(default_factory=dict)


class ThroughputCollector:
    """Samples cumulative scheduled-pod counts over the measured phase
    (reference throughputCollector, scheduler_perf/util.go:457-660: a
    free-running sampler of scheduled pods/interval). The op loop calls
    `sample()` after every ingest+dispatch step — the measured window
    INCLUDES pod creation and event-handler ingestion, exactly like the
    reference's wall-clock sampling — and percentiles come from the
    per-window rates (one window ≈ one create batch)."""

    def __init__(self) -> None:
        self.samples_: list[tuple[float, int]] = []
        self.start = 0.0
        self.elapsed = 0.0
        self.base = 0
        self.pods = 0

    def begin(self, scheduled_total: int = 0) -> None:
        self.base = scheduled_total
        self.start = time.perf_counter()
        self.samples_ = [(self.start, scheduled_total)]

    def sample(self, scheduled_total: int) -> None:
        self.samples_.append((time.perf_counter(), scheduled_total))

    def end(self, scheduled_total: int) -> None:
        self.sample(scheduled_total)
        self.elapsed = time.perf_counter() - self.start
        self.pods = scheduled_total - self.base

    def item(self, name: str) -> DataItem:
        # rate per commit span: zero-progress windows (the async pipeline
        # holds results in flight for several chunks) are MERGED into the
        # span that finally commits, so a lumpy commit cadence cannot
        # inflate the percentiles — each rate is Δpods/Δt between
        # consecutive points where the scheduled count actually advanced
        rates = []
        t0, c0 = self.samples_[0] if self.samples_ else (0.0, 0)
        for t1, c1 in self.samples_[1:]:
            if c1 > c0 and t1 > t0:
                rates.append((c1 - c0) / (t1 - t0))
                t0, c0 = t1, c1
        rates.sort()

        def perc(p: float) -> float:
            if not rates:
                return 0.0
            return rates[min(len(rates) - 1, int(p * len(rates)))]

        avg = self.pods / self.elapsed if self.elapsed > 0 else 0.0
        return DataItem(name=name, average=avg, perc50=perc(0.50),
                        perc95=perc(0.95), perc99=perc(0.99),
                        pods=self.pods, duration_s=self.elapsed,
                        samples=len(rates))


def _make_nodes(api: APIServer, count: int, start: int, params: dict) -> None:
    cpu = params.get("nodeCpu", 32)
    mem = params.get("nodeMemoryGi", 64)
    zones = params.get("zones", 16)
    for i in range(start, start + count):
        api.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": cpu, "memory": f"{mem}Gi", "pods": 110})
            .zone(f"zone-{i % zones}")
            .label(LABEL_HOSTNAME, f"node-{i}")
            .obj())


def _pod_from_template(name: str, template: Optional[dict], seq: int = 0,
                       zones: int = 16, gang_size: int = 1):
    w = make_pod(name)
    t = template or {}
    cpu = t.get("cpu", "900m")
    cyc = int(t.get("signatureCycle", 0))
    if cyc:
        # rotate the cpu request over `cyc` distinct values: consecutive
        # pods then interleave `cyc` distinct signatures (every other
        # template field — labels, spread, affinity — identical), the
        # high-signature mixed-drain shape the drain compiler maps to one
        # plan program (MixedHighSignature workload)
        cpu = f"{250 + 50 * (seq % cyc)}m"
    w = w.req({"cpu": cpu, "memory": t.get("memory", "1Gi")})
    if t.get("priority"):
        w = w.priority(int(t["priority"]))
    for k, v in t.get("labels", {}).items():
        w = w.label(k, v)
    if t.get("nodeSelectorZone"):
        w = w.node_selector({LABEL_ZONE: f"zone-{seq % zones}"})
    if "spreadZone" in t:
        w = w.spread_constraint(t.get("maxSkew", 1), LABEL_ZONE,
                                t.get("whenUnsatisfiable", "DoNotSchedule"),
                                t["spreadZone"])
    if "podAntiAffinity" in t:
        w = w.pod_affinity(t.get("topologyKey", LABEL_ZONE),
                           t["podAntiAffinity"], anti=True)
    if "podAffinity" in t:
        w = w.pod_affinity(t.get("topologyKey", LABEL_ZONE), t["podAffinity"])
    if "workloadRef" in t:
        ref = t["workloadRef"].replace("$gang", str(seq // max(gang_size, 1)))
        w = w.workload(ref.replace("$seq", str(seq)))
    return w.obj()


class PodFactory:
    """Stamps pods from shared prototypes: metadata (and status) are fresh
    per pod; the spec and label-dict OBJECTS are shared, per the object
    model's aliasing contract (api/types.py) — which is also what makes
    the builder's identity signature cache hit (state/batch.py). Template
    fields that genuinely vary per pod fall back to full construction."""

    def __init__(self, template: Optional[dict], zones: int = 16,
                 gang_size: int = 1):
        self.template = template or {}
        self.zones = zones
        self.gang_size = max(gang_size, 1)
        t = self.template
        self.per_seq = "workloadRef" in t
        self.zone_protos = None
        if t.get("nodeSelectorZone") and not self.per_seq:
            self.zone_protos = [
                _pod_from_template(f"proto-z{z}", t, seq=z, zones=zones)
                for z in range(zones)]
        self.cycle_protos = None
        cyc = int(t.get("signatureCycle", 0))
        if cyc and not self.per_seq and self.zone_protos is None:
            # one shared prototype per signature in the cycle: pods
            # sharing a prototype share spec identity, so the builder's
            # signature cache hits while the drain still interleaves
            # `cyc` distinct signatures
            self.cycle_protos = [
                _pod_from_template(f"proto-c{c}", t, seq=c, zones=zones)
                for c in range(cyc)]
        self.proto = _pod_from_template("proto", t, seq=0, zones=zones,
                                        gang_size=self.gang_size)

    # every pod stamped from a proto shares this empty status shape; the
    # copies below are safe because status mutations in the object model
    # REPLACE fields (apiserver patch semantics), never mutate the
    # shared conditions list in place
    _STATUS_PROTO = PodStatus()

    def make(self, name: str, seq: int):
        # inlined shallow copies + hoisted imports: this runs once per
        # created pod inside the measured window — the client-side cost
        # the reference benchmark's QPS-bound createPods pays too
        if self.per_seq:
            return _pod_from_template(name, self.template, seq=seq,
                                      zones=self.zones,
                                      gang_size=self.gang_size)
        if self.cycle_protos is not None:
            proto = self.cycle_protos[seq % len(self.cycle_protos)]
        elif self.zone_protos is not None:
            proto = self.zone_protos[seq % self.zones]
        else:
            proto = self.proto
        new = object.__new__
        p = new(type(proto))
        p.__dict__.update(proto.__dict__)
        meta = proto.metadata
        m = new(type(meta))
        m.__dict__.update(meta.__dict__)
        m.name = name
        m.uid = f"{m.namespace}/{name}"
        m.creation_index = next(_counter)
        p.metadata = m
        st = new(PodStatus)
        st.__dict__.update(self._STATUS_PROTO.__dict__)
        p.status = st
        return p


class WorkloadRunner:
    """Executes one workload's op list against a fresh Scheduler."""

    def __init__(self, scheduler_factory: Optional[Callable[[APIServer], Scheduler]] = None,
                 batch_size: int = 8192, create_batch: int = 512,
                 trace: bool = False):
        # `create_batch` streams pods in realistic chunks (the reference
        # benchmark's createPods ingestion rate is bounded by client
        # QPS/Burst 5000, util.go:123-124); the async commit pipeline
        # overlaps each chunk's device readback with the next chunk's
        # ingestion, so small chunks no longer serialize on the tunnel
        # round trip. `batch_size` only caps a single drain.
        self.batch_size = batch_size
        self.create_batch = create_batch
        self.trace = trace
        self.last_tracer = None
        self.last_pipeline_stats: Optional[dict] = None
        self.factory = scheduler_factory or self._default_factory

    def _default_factory(self, api: APIServer) -> Scheduler:
        sched = Scheduler(api, batch_size=self.batch_size)
        # KTPU_AUDIT_SAMPLE=1.0 forces the shadow audit onto every drain
        # (the acceptance sweep: a full bench at 100% sampling must
        # record zero divergences); unset = the config default rate
        rate = os.environ.get("KTPU_AUDIT_SAMPLE")
        if rate and sched.audit is not None:
            sched.audit.sample_rate = float(rate)
        return sched

    def run(self, tc: TestCase, wl: Workload, verbose: bool = False) -> list[DataItem]:
        # serve the measured window with the cyclic collector paused
        # (utils/runtime.py): drain-chunk allocation churn otherwise
        # triggers gen-2 collections inside the commit tail — measured
        # as the dominant commit_s cost. Restored (with a full collect)
        # on exit, so the surrounding process sees normal GC.
        from ..utils.runtime import scheduling_gc_pause
        with scheduling_gc_pause():
            return self._run_ops(tc, wl, verbose)

    def _run_ops(self, tc: TestCase, wl: Workload,
                 verbose: bool = False) -> list[DataItem]:
        api = APIServer()
        sched = self.last_scheduler = self.factory(api)
        self.last_pipeline_stats = None
        if self.trace:
            # capture EVERY cycle's span tree for Chrome-trace export
            # (bench --trace-dir): slow-threshold inf keeps the slow ring
            # quiet, keep_recent retains the full drain history
            from ..utils.tracing import Tracer
            self.last_tracer = sched.tracer = Tracer(
                slow_threshold_s=float("inf"), keep_recent=65536)
        params = wl.params
        items: list[DataItem] = []
        node_seq = 0
        pod_seq = 0
        op_times: list[tuple[str, float]] = []
        # kernel-observatory bracket: the observatory is process-global,
        # so per-run numbers must be deltas, not absolutes
        obs_chk = (sched.observatory.checkpoint()
                   if sched.observatory.enabled else None)
        for op_i, op in enumerate(tc.workload_template):
            code = op["opcode"]
            t_op = time.perf_counter()
            if code == "createNodes":
                count = int(_resolve(op, "count", params))
                _make_nodes(api, count, node_seq, params)
                node_seq += count
                # informer-sync analog (reference WaitForCacheSync runs
                # before the measured phase): build snapshot + device
                # staging now, not inside the first scheduling cycle
                sched.prime()
            elif code == "createPods":
                count = int(_resolve(op, "count", params))
                template = op.get("podTemplate", tc.default_pod_template)
                collect = op.get("collectMetrics", False)
                factory = PodFactory(template,
                                     zones=params.get("zones", 16),
                                     gang_size=int(params.get("gangSize", 1)))
                col = ThroughputCollector() if collect else None
                if col:
                    col.begin(sched.scheduled_count)
                created = 0
                create_batch = int(op.get("createBatch", self.create_batch))
                make = factory.make
                while created < count:
                    n = min(create_batch, count - created)
                    base = pod_seq + created
                    api.create_pods([make(f"pod-{base + i}", base + i)
                                     for i in range(n)])
                    created += n
                    # dispatch without waiting: the device results of this
                    # chunk commit while the next chunk is being created
                    sched.schedule_pending(wait=False)
                    if col:
                        col.sample(sched.scheduled_count)
                    if verbose:
                        print(f"  createPods: {created}/{count} "
                              f"scheduled={sched.scheduled_count}")
                # final full drain: dispatch whatever accumulated under the
                # adaptive batcher, then barrier the commit pipeline
                sched.schedule_pending()
                pod_seq += count
                if col:
                    col.end(sched.scheduled_count)
                    items.append(col.item(f"{tc.name}/{wl.name}"))
            elif code in ("streamPods", "streamTrace"):
                # open-loop streaming load (ISSUE 18): pods ARRIVE on a
                # Poisson clock at a target QPS (or a replayed gang
                # trace) and the streaming pipeline — or the lock-step
                # A/B — absorbs them. Open-loop: a slow scheduler never
                # thins the offered load, the backlog builds.
                items.extend(self._run_stream(
                    code, op, tc, wl, params, api, sched, pod_seq,
                    verbose))
                if code == "streamPods":
                    pod_seq += int(_resolve(op, "count", params))
            elif code == "gangTrace":
                # trace-driven gang traffic (testing/workloads.py): LLM
                # training gangs + co-located inference + gangs-preempt-
                # gangs, streamed in arrival chunks like createPods
                from ..testing.workloads import GangWorkloadGenerator
                gen = GangWorkloadGenerator(
                    seed=int(op.get("seed", params.get("seed", 0))))
                gangs = int(_resolve(op, "gangs", params, 0))
                gang_size = _resolve(op, "gangSize", params, None)
                size = (int(gang_size) if gang_size is not None
                        else (int(op.get("gangSizeMin", 8)),
                              int(op.get("gangSizeMax", 512))))
                specs = gen.training_gangs(
                    gangs, size=size,
                    min_count_frac=float(op.get("minCountFrac", 1.0)),
                    cpu=op.get("gangCpu", "900m"),
                    memory=op.get("gangMemory", "1Gi"),
                    priority=int(op.get("gangPriority", 0)))
                pre_specs = gen.training_gangs(
                    int(_resolve(op, "preemptorGangs", params, 0)),
                    size=int(op.get("preemptorSize", 8)),
                    cpu=op.get("preemptorCpu", "900m"),
                    memory=op.get("gangMemory", "1Gi"),
                    priority=int(op.get("preemptorPriority", 200)),
                    prefix="preemptor")
                contig = op.get("contiguityWeight",
                                params.get("contiguityWeight"))
                if contig is not None:
                    sched.gang_contiguity_weight = int(contig)
                collect = op.get("collectMetrics", False)
                col = ThroughputCollector() if collect else None
                if col:
                    col.begin(sched.scheduled_count)
                create_batch = int(op.get("createBatch", self.create_batch))
                for kind, obj in gen.trace(
                        specs,
                        inference_count=int(
                            _resolve(op, "inferencePods", params, 0)),
                        inference_cpu=op.get("inferenceCpu", "250m"),
                        inference_priority=int(
                            op.get("inferencePriority", 100)),
                        preemptor_gangs=pre_specs,
                        chunk=create_batch):
                    if kind == "workload":
                        api.create_workload(obj)
                        continue
                    api.create_pods(obj)
                    sched.schedule_pending(wait=False)
                    if col:
                        col.sample(sched.scheduled_count)
                    if verbose:
                        print(f"  gangTrace: scheduled="
                              f"{sched.scheduled_count}")
                sched.schedule_pending()
                if col:
                    col.end(sched.scheduled_count)
                    items.append(col.item(f"{tc.name}/{wl.name}"))
            elif code == "createWorkloads":
                from ..api.types import ObjectMeta, PodGroup, Workload
                count = int(_resolve(op, "count", params, 1))
                min_count = int(_resolve(op, "minCount", params, 1))
                prefix = op.get("namePrefix", "wl")
                for i in range(count):
                    api.create_workload(Workload(
                        metadata=ObjectMeta(name=f"{prefix}-{i}"),
                        pod_groups=[PodGroup(name="workers",
                                             min_count=min_count)]))
            elif code == "barrier":
                deadline = time.time() + float(op.get("timeoutSeconds", 60))
                while len(sched.queue) and time.time() < deadline:
                    sched.flush_queues()
                    if sched.schedule_pending() == 0:
                        # nothing schedulable right now: wait for backoffs
                        # instead of spinning the drain loop
                        time.sleep(0.05)
            elif code == "churn":
                # churn mode "recreate" (scheduler_perf.go:870): create and
                # delete pods/nodes repeatedly to exercise event handling
                number = int(_resolve(op, "number", params, 100))
                for i in range(number):
                    name = f"churn-{i}"
                    api.create_pod(_pod_from_template(name, tc.default_pod_template))
                    sched.schedule_pending()
                    api.delete_pod(f"default/{name}")
            elif code == "sleep":
                time.sleep(float(op.get("duration", op.get("seconds", 0.1))))
            else:
                raise ValueError(f"unknown opcode {code}")
            op_times.append((f"{code}[{op_i}]", time.perf_counter() - t_op))
        self.last_op_seconds = op_times
        m = sched.metrics
        extras = {
            "host_build_s": round(m.drain_phase.sum("host_build"), 3),
            "device_s": round(m.drain_phase.sum("device"), 3),
            "commit_s": round(m.drain_phase.sum("commit"), 3),
            # host_build decomposition (this PR's observability layer)
            "host_snapshot_s": round(m.drain_phase.sum("host_snapshot"), 3),
            "host_tensorize_s": round(m.drain_phase.sum("host_tensorize"), 3),
            "host_group_seed_s": round(
                m.drain_phase.sum("host_group_seed"), 3),
            "host_cache_s": round(m.drain_phase.sum("host_cache"), 3),
            # per-attempt latency percentiles from the attempt-duration
            # histogram (all result/profile series merged)
            "attempt_p50_ms": round(
                m.attempt_duration.quantile(0.50) * 1e3, 3),
            "attempt_p99_ms": round(
                m.attempt_duration.quantile(0.99) * 1e3, 3),
            # queue→bind e2e percentiles from the SLI histogram (all
            # attempt-count series merged) — the bench_compare e2e gate
            "e2e_p50_ms": round(m.sli_duration.quantile(0.50) * 1e3, 3),
            "e2e_p99_ms": round(m.sli_duration.quantile(0.99) * 1e3, 3),
        }
        if self.last_pipeline_stats is not None:
            # streaming-pipeline occupancy block (ISSUE 18): stage busy
            # seconds, overlap factor, backpressure + batch-close counts
            extras["pipeline"] = self.last_pipeline_stats
        waves = m.wave_placement_waves.value()
        if waves:
            nconf = m.wave_conflict_ratio.count()
            extras["waves"] = int(waves)
            extras["wave_conflict_ratio"] = round(
                m.wave_conflict_ratio.sum() / max(nconf, 1), 4)
        if obs_chk is not None:
            kernels = sched.observatory.delta_since(obs_chk)
            if kernels:
                # per-kernel device-time breakdown of this run (warm
                # dispatch walls; compile cost lives in the ledger split)
                extras["kernels"] = kernels
            shard = sched.observatory.shard_profile()
            if shard:
                extras["shard_lanes"] = shard
        if getattr(sched, "critical_path_enabled", False):
            # critical-path headroom block (ISSUE 20): fold the run's
            # per-drain verdicts (this scheduler is fresh per run, so the
            # flight ring is exactly this run's last <=256 drains) into
            # the verdict histogram + ceiling factor bench.py projects
            # a pods/s ceiling from
            from .critical_path import aggregate
            cp = aggregate(d.get("criticalPath")
                           for d in sched.flight.dump())
            if cp.get("drains"):
                extras["critical_path"] = cp
        prof = getattr(sched, "profiler", None)
        if prof is not None and prof.sample_count:
            # hottest host frames of the run (continuous profiler): the
            # function-level answer behind the host_*_s phase sums
            extras["host_top_frames"] = prof.top_frames(5)
        # SLO verdict at bench end (obs/slo.py): burn-rate breaches +
        # shadow-audit divergence — the bench_compare --slo gate input.
        # The audit worker must land its in-flight replays first.
        audit = getattr(sched, "audit", None)
        if audit is not None:
            audit.flush(timeout=120.0)
        slo_engine = getattr(sched, "slo", None)
        if slo_engine is not None:
            slo = slo_engine.snapshot(compact=True)
            slo["audited"] = int(
                m.shadow_audit_drains.value("clean")
                + m.shadow_audit_drains.value("divergent"))
            slo["divergence_total"] = int(
                sum(m.oracle_divergence.value(kind)
                    for kind in ("assignment", "reason", "verdict")))
            extras["slo"] = slo
        for item in items:
            item.op_seconds = list(op_times)
            item.extras = dict(extras)
        return items

    def _run_stream(self, code: str, op: dict, tc: TestCase, wl: Workload,
                    params: dict, api: APIServer, sched: Scheduler,
                    pod_seq: int, verbose: bool) -> list[DataItem]:
        """streamPods / streamTrace opcodes: stamp the arrival schedule,
        pace it open-loop against the wall clock, and absorb it through
        the streaming pipeline or the lock-step A/B twin."""
        from ..testing.workloads import (GangWorkloadGenerator, chunked,
                                         poisson_arrivals)
        qps = float(_resolve(op, "qps", params, 10_000))
        mode = str(_resolve(op, "mode", params, "pipeline"))
        chunk = int(op.get("chunk", params.get("arrivalChunk", 128)))
        seed = int(op.get("seed", params.get("seed", 0)))
        budget_s = float(op.get("latencyBudgetMs",
                                params.get("latencyBudgetMs", 5.0))) / 1e3
        workload_objs: list = []
        if code == "streamPods":
            count = int(_resolve(op, "count", params))
            template = op.get("podTemplate", tc.default_pod_template)
            factory = PodFactory(template, zones=params.get("zones", 16),
                                 gang_size=int(params.get("gangSize", 1)))
            make = factory.make
            chunks = chunked([make(f"pod-{pod_seq + i}", pod_seq + i)
                              for i in range(count)], chunk)
        else:   # streamTrace: the gang/inference trace, paced
            gen = GangWorkloadGenerator(seed=seed)
            specs = gen.training_gangs(
                int(_resolve(op, "gangs", params, 0)),
                size=(int(op.get("gangSizeMin", 8)),
                      int(op.get("gangSizeMax", 512))),
                cpu=op.get("gangCpu", "900m"),
                memory=op.get("gangMemory", "1Gi"),
                priority=int(op.get("gangPriority", 0)))
            chunks = []
            for kind, obj in gen.trace(
                    specs,
                    inference_count=int(
                        _resolve(op, "inferencePods", params, 0)),
                    chunk=chunk):
                if kind == "workload":
                    workload_objs.append(obj)
                else:
                    chunks.append(obj)
        events = list(poisson_arrivals(chunks, qps=qps, seed=seed))
        collect = op.get("collectMetrics", False)
        col = ThroughputCollector() if collect else None
        use_pipeline = (mode == "pipeline" and sched.feature_gates.enabled(
            "StreamingDrainPipeline"))
        self.last_pipeline_stats = None
        # per-tier e2e quantiles as deltas from here: the warmup phase's
        # compile-wait outliers must not pollute the tier's p50/p99
        sli_chk = sched.metrics.sli_duration.merged_counts()
        if col:
            col.begin(sched.scheduled_count)
        if use_pipeline:
            from ..pipeline import StreamingPipeline
            pipe = StreamingPipeline(
                sched, latency_budget_s=budget_s,
                dispatch_depth=int(op.get("dispatchDepth", 3)))
            pipe.start()
            try:
                for w in workload_objs:
                    pipe.feed_workload(w)
                t0 = time.perf_counter()
                for due, pods in events:
                    lag = t0 + due - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    pipe.feed(pods)
                    if col:
                        col.sample(sched.scheduled_count)
                arrival_done = time.perf_counter()
                pipe.drain()
            finally:
                pipe.stop()
            self.last_pipeline_stats = pipe.stats()
        elif mode == "lockstep":
            # the lock-step phase train at the same offered load: with no
            # overlap the device is idle at every decision point, so the
            # adaptive close policy fires on each arrival chunk and runs
            # build -> device -> commit to the barrier before the next.
            # This is the A/B twin the streaming gate compares against.
            for w in workload_objs:
                api.create_workload(w)
            t0 = time.perf_counter()
            for due, pods in events:
                lag = t0 + due - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                api.create_pods(pods)
                if sched.dispatch_once():
                    sched.wait_pending()
                if col:
                    col.sample(sched.scheduled_count)
            arrival_done = time.perf_counter()
            deadline = time.time() + 120.0
            while len(sched.queue) and time.time() < deadline:
                sched.flush_queues()
                if sched.dispatch_once():
                    sched.wait_pending()
                else:
                    time.sleep(0.01)
        else:
            # "async": the pre-pipeline schedule_pending(wait=False) path
            # (commit tail detached, adaptive batcher accumulating) at
            # the same offered load
            for w in workload_objs:
                api.create_workload(w)
            t0 = time.perf_counter()
            for due, pods in events:
                lag = t0 + due - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                api.create_pods(pods)
                sched.schedule_pending(wait=False)
                if col:
                    col.sample(sched.scheduled_count)
            arrival_done = time.perf_counter()
            sched.schedule_pending()
        m = sched.metrics
        # coordinated-omission guard: how far the arrival driver finished
        # behind the ideal Poisson schedule. The SLI e2e clock starts at
        # enqueue, so a mode that stalls the (single-threaded) driver
        # delays enqueues and understates its own latency — a nonzero lag
        # flags exactly that. The pipeline's feed() returns immediately,
        # so its lag stays ~0 and its e2e is the honest open-loop number.
        lag_s = (max(0.0, arrival_done - (t0 + events[-1][0]))
                 if events else 0.0)
        stream = {
            "mode": mode,
            "offered_qps": qps,
            "arrival_lag_s": round(lag_s, 3),
            "stream_e2e_p50_ms": round(
                m.sli_duration.quantile(0.50, since=sli_chk) * 1e3, 3),
            "stream_e2e_p99_ms": round(
                m.sli_duration.quantile(0.99, since=sli_chk) * 1e3, 3),
        }
        if self.last_pipeline_stats is None:
            self.last_pipeline_stats = stream
        else:
            self.last_pipeline_stats.update(stream)
        if col:
            col.end(sched.scheduled_count)
            if verbose:
                print(f"  {code}[{mode}] qps={qps:g}: "
                      f"scheduled={sched.scheduled_count}")
            return [col.item(f"{tc.name}/{wl.name}")]
        return []



def run_config(path: str, case_filter: str = "", workload_filter: str = "",
               verbose: bool = False, scheduler_factory=None,
               metrics_path: str = "",
               trace_dir: str = "",
               profile_dir: str = "",
               timeline_dir: str = "") -> list[tuple[DataItem, float]]:
    """Run matching (case, workload) pairs; returns [(item, threshold)].
    `metrics_path` appends each run's Prometheus exposition (the reference
    benchmark collects /metrics the same way, scheduler_perf/util.go);
    `trace_dir` writes one Chrome-trace JSON of the run's span trees per
    workload (loadable at chrome://tracing / ui.perfetto.dev);
    `profile_dir` writes one collapsed-stack host profile per workload
    (flamegraph.pl / speedscope.app ingest it directly);
    `timeline_dir` writes one JSON-lines telemetry timeline per workload
    (obs/timeline.py: per-second aggregates over all SLIs + probe)."""
    out = []
    for tc in load_test_cases(path):
        if case_filter and case_filter != tc.name:
            continue
        for wl in tc.workloads:
            if workload_filter and workload_filter != wl.name:
                continue
            runner = WorkloadRunner(scheduler_factory=scheduler_factory,
                                    trace=bool(trace_dir))
            for item in runner.run(tc, wl, verbose=verbose):
                out.append((item, wl.threshold))
            if metrics_path:
                with open(metrics_path, "a") as f:
                    f.write(f"# == {tc.name}/{wl.name} ==\n")
                    f.write(runner.last_scheduler.metrics.exposition())
            if trace_dir and runner.last_tracer is not None:
                os.makedirs(trace_dir, exist_ok=True)
                dest = os.path.join(trace_dir,
                                    f"{tc.name}_{wl.name}.trace.json")
                n = runner.last_tracer.export_chrome_trace(dest)
                if verbose:
                    print(f"  trace: {dest} ({n} events)")
            prof = getattr(runner.last_scheduler, "profiler", None)
            if profile_dir and prof is not None:
                os.makedirs(profile_dir, exist_ok=True)
                dest = os.path.join(profile_dir,
                                    f"{tc.name}_{wl.name}.collapsed.txt")
                n = prof.write_collapsed(dest)
                if verbose:
                    print(f"  profile: {dest} ({n} stacks)")
            tl = getattr(runner.last_scheduler, "timeline", None)
            if timeline_dir and tl is not None:
                os.makedirs(timeline_dir, exist_ok=True)
                dest = os.path.join(timeline_dir,
                                    f"{tc.name}_{wl.name}.timeline.jsonl")
                n = tl.to_jsonl(dest)
                if verbose:
                    print(f"  timeline: {dest} ({n} buckets)")
    return out
