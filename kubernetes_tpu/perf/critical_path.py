"""Critical-path observatory: per-drain bottleneck verdicts (ISSUE 20).

The measurement rails (drain phases, kernel lanes, pipeline stage
counters, shard profile) are descriptive: they say how long each segment
took, but every "the mesh drain is host-bound" claim in ROADMAP items
1-2 was still derived by hand from three separate surfaces, and item 5's
autotuner has no cost table to search against. This module turns the
rails into VERDICTS:

- `attribute_drain` walks one drain's recorded segments — the host_build
  sub-phases stamped by `Scheduler._phase`, the per-kernel device lanes
  from the kernel observatory, the readback wait, the commit tail, and
  (under `StreamingDrainPipeline`) the stage workers' backpressure
  stalls — and emits the binding chain plus a dominant-bottleneck
  verdict over the CAUSES taxonomy, with per-cause seconds. The
  scheduler stamps the result on the drain's FlightRecord and mirrors
  it into `scheduler_critical_path_seconds{cause}` /
  `scheduler_bottleneck_drains_total{cause}`.
- `aggregate` folds many per-drain verdicts into the bench summary's
  `critical_path` block: a verdict histogram, total per-cause seconds,
  and the ceiling factor — the projected speedup if the dominant cause
  were free (`total / (total - dominant)`, the headroom formula README
  documents). bench.py multiplies it into a projected pods/s ceiling.
- `phase_shares` is the ONE implementation of the stage-share math that
  bench.py's `phase_pct`/`host_share` summary and the pipeline occupancy
  block previously computed independently (the ISSUE 20 bugfix): given
  {segment: seconds} and an optional wall denominator it returns the
  fractional shares plus the host share (host_build + commit over the
  cycle), so both surfaces agree on the same FlightRecorder window.
- `attribute_delta` explains a throughput delta between two aggregated
  blocks by the cause whose per-drain seconds moved most — the
  differential-attribution mode of tools/bench_compare.py.

Everything here is pure stdlib arithmetic over dicts the rails already
record: no jax, no locks, safe to import from metrics/ and tools/.
Gate: `CriticalPathObservatory` (Beta/on), owned by the constructing
Scheduler like the other observability gates.
"""

from __future__ import annotations

# The verdict taxonomy — the exact label set of the
# scheduler_critical_path_seconds / scheduler_bottleneck_drains_total
# families (the exposition lint asserts it). Order breaks ties: an
# earlier cause wins an exact-seconds tie, so a fully idle drain says
# "idle" only when nothing else claimed time.
#
#   host_build     columnar ingest, signature/plan compile, group seeding
#                  (the host_snapshot/tensorize/group_seed/cache children)
#   device_compute the device lanes' local compute share
#   device_comms   the collective/all-reduce share of a sharded dispatch
#                  (the lane profile's commsShare split)
#   commit         assume + bind enqueue + failure handling
#   backpressure   streaming-pipeline stall seconds (a depth cap held the
#                  drain back); structurally zero in lock-step operation
#   idle           host blocked on the device readback with no overlap
#                  (device_wait) — the seconds the pipeline exists to
#                  reclaim
CAUSES = ("host_build", "device_compute", "device_comms", "commit",
          "backpressure", "idle")

# host_build's named children (Scheduler._phase): part of the chain
# rendering, never separate causes — host_build already covers them
HOST_SUBPHASES = ("host_snapshot", "host_tensorize", "host_group_seed",
                  "host_cache")


def attribute_drain(phases: dict, kernels: dict = None,
                    comms_share: float = 0.0,
                    backpressure_s: float = 0.0) -> dict:
    """One drain's segments → {"verdict", "causes", "chain"}.

    `phases` is the FlightRecord/_PendingDrain phase dict (host_build,
    device_dispatch, device_wait, commit + the host sub-phases);
    `kernels` the per-kernel device-lane seconds; `comms_share` the
    sharded-lane profile's collective share of the device window (0.0
    unsharded); `backpressure_s` the pipeline stall seconds attributed
    to this drain (0.0 in lock-step operation — a lock-step drain can
    never carry a backpressure verdict).
    """
    phases = phases or {}
    device_s = max(float(phases.get("device_dispatch", 0.0)), 0.0)
    share = min(max(float(comms_share), 0.0), 1.0)
    causes = {
        "host_build": max(float(phases.get("host_build", 0.0)), 0.0),
        "device_compute": device_s * (1.0 - share),
        "device_comms": device_s * share,
        "commit": max(float(phases.get("commit", 0.0)), 0.0),
        "backpressure": max(float(backpressure_s), 0.0),
        "idle": max(float(phases.get("device_wait", 0.0)), 0.0),
    }
    verdict = max(CAUSES, key=lambda c: (causes[c], -CAUSES.index(c)))
    if causes[verdict] <= 0.0:
        verdict = "idle"             # an empty record binds on nothing
    return {"verdict": verdict,
            "causes": {c: round(s, 6) for c, s in causes.items()},
            "chain": _chain(phases, kernels or {}, causes)}


def _chain(phases: dict, kernels: dict, causes: dict) -> list:
    """The binding chain: the drain's segments in execution order, each
    tagged with the cause that claims it. Zero segments are dropped —
    the chain is what a human reads at /debug/criticalpath."""
    chain: list[dict] = []

    def seg(span: str, seconds: float, cause: str) -> None:
        if seconds > 0.0:
            chain.append({"span": span, "seconds": round(seconds, 6),
                          "cause": cause})

    named = 0.0
    for sub in HOST_SUBPHASES:
        s = float(phases.get(sub, 0.0))
        named += max(s, 0.0)
        seg(sub, s, "host_build")
    seg("host_other", float(phases.get("host_build", 0.0)) - named,
        "host_build")
    lane_total = 0.0
    dev_cause = ("device_comms"
                 if causes.get("device_comms", 0.0)
                 > causes.get("device_compute", 0.0) else "device_compute")
    for kernel in sorted(kernels):
        s = float(kernels[kernel])
        lane_total += max(s, 0.0)
        seg(f"kernel:{kernel}", s, dev_cause)
    seg("device_other",
        float(phases.get("device_dispatch", 0.0)) - lane_total, dev_cause)
    seg("backpressure_stall", causes.get("backpressure", 0.0),
        "backpressure")
    seg("device_wait", float(phases.get("device_wait", 0.0)), "idle")
    seg("commit", float(phases.get("commit", 0.0)), "commit")
    return chain


def aggregate(verdicts) -> dict:
    """Fold per-drain `attribute_drain` results (or their FlightRecord
    `criticalPath` dict form) into the bench/debug summary block:
    verdict histogram, per-cause seconds, the modal verdict, and the
    ceiling factor — measured_rate * ceiling_factor is the projected
    rate if the dominant cause were free."""
    hist: dict[str, int] = {}
    causes = {c: 0.0 for c in CAUSES}
    drains = 0
    for v in verdicts:
        if not isinstance(v, dict) or not v.get("verdict"):
            continue
        drains += 1
        hist[v["verdict"]] = hist.get(v["verdict"], 0) + 1
        for c, s in (v.get("causes") or {}).items():
            if c in causes:
                causes[c] += float(s)
    out = {"drains": drains,
           "verdicts": dict(sorted(hist.items())),
           "causes": {c: round(s, 6) for c, s in causes.items()}}
    if drains:
        # the dominant cause of the WINDOW is the one with the most
        # seconds, not the modal per-drain verdict — a long tail of
        # small drains must not outvote one giant commit stall
        dominant = max(CAUSES, key=lambda c: (causes[c], -CAUSES.index(c)))
        out["dominant"] = dominant
        out["ceiling_factor"] = round(
            ceiling_factor(causes, dominant), 4)
    return out


def ceiling_factor(causes: dict, dominant: str) -> float:
    """Headroom projection: with the dominant cause's seconds removed
    from the cycle, throughput scales by total / (total - dominant).
    1.0 when nothing was measured; capped at 100x — a cause that IS the
    whole cycle projects "infinite" speedup, which is noise, not
    headroom."""
    total = sum(max(float(s), 0.0) for s in causes.values())
    freed = max(float(causes.get(dominant, 0.0)), 0.0)
    rest = total - freed
    if total <= 0.0:
        return 1.0
    if rest <= total * 0.01:
        return 100.0
    return total / rest


def phase_shares(parts: dict, wall: float = None) -> dict:
    """THE stage-share math (ISSUE 20 bugfix): bench.py's summary
    `phase_pct`/`host_share` and the pipeline occupancy block previously
    computed shares independently; both now call here. `parts` maps
    segment → seconds; `wall` is the denominator (None = the segments'
    own sum — a lock-step cycle; a pipeline window passes its wall so
    overlapping stages can sum past 1.0). Returns the rounded fractional
    shares, the total, the occupancy (total/wall) and the host share
    (host_build + commit over the denominator — the Python-claims-the-
    cycle number bench_compare gates)."""
    total = sum(max(float(v), 0.0) for v in parts.values())
    base = float(wall) if wall is not None and wall > 0 else total
    shares = {k: (round(max(float(v), 0.0) / base, 4) if base > 0 else 0.0)
              for k, v in parts.items()}
    host = (max(float(parts.get("host_build", 0.0)), 0.0)
            + max(float(parts.get("commit", 0.0)), 0.0))
    return {"total": round(total, 6),
            "shares": shares,
            "occupancy": round(total / base, 4) if base > 0 else 0.0,
            "host_share": round(host / base, 4) if base > 0 else 0.0}


def attribute_delta(base: dict, new: dict) -> dict:
    """Differential attribution (tools/bench_compare.py --attribute):
    explain a throughput delta between two aggregated `critical_path`
    blocks by the cause whose PER-DRAIN seconds moved most. Normalizing
    by drain count makes unequal windows comparable — 2x the drains is
    2x every cause, not a regression. Returns {} when either side lacks
    verdicts; otherwise the moved cause, its per-drain seconds on both
    sides, the growth ratio, and the full per-cause delta table."""
    b_n = int((base or {}).get("drains") or 0)
    n_n = int((new or {}).get("drains") or 0)
    if b_n <= 0 or n_n <= 0:
        return {}
    b_c = (base or {}).get("causes") or {}
    n_c = (new or {}).get("causes") or {}
    deltas = {}
    for c in CAUSES:
        b_s = max(float(b_c.get(c, 0.0)), 0.0) / b_n
        n_s = max(float(n_c.get(c, 0.0)), 0.0) / n_n
        deltas[c] = {"base_s": round(b_s, 6), "new_s": round(n_s, 6),
                     "delta_s": round(n_s - b_s, 6),
                     "ratio": round(n_s / b_s, 4) if b_s > 0 else None}
    moved = max(CAUSES,
                key=lambda c: (abs(deltas[c]["delta_s"]),
                               -CAUSES.index(c)))
    return {"cause": moved, **deltas[moved], "deltas": deltas}
