"""Kernel observatory (ISSUE 14): per-dispatch device-time attribution.

Covers the full stack the tentpole ships:

- the streaming-histogram substrate (bounded buckets, plan-key overflow);
- the `CompileLedger.measured_call` compile/run split and its thread
  safety under concurrent dispatch;
- capture semantics (warm vs compiling routing, the per-drain device
  lane, checkpoint/delta);
- /debug/kernels over a live SchedulerServer, including the acceptance
  cross-check that a drain's per-kernel seconds decompose its
  device_dispatch phase wall;
- the Chrome-trace merge: device-lane child spans land on their own
  thread track, strictly nested inside their drain's device span;
- sharded-lane profiling on the 8-device test mesh;
- retrace_budget(0) holding over warm re-runs with the observatory ON;
- tools/kernel_sweep.py --self-test and tools/check.py observatory_gaps;
- the slow-marked throughput gate: observatory ON within 5% of OFF.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from kubernetes_tpu.analysis.rails import GLOBAL as RAILS  # noqa: E402
from kubernetes_tpu.backend.apiserver import APIServer  # noqa: E402
from kubernetes_tpu.config import KubeSchedulerConfiguration  # noqa: E402
from kubernetes_tpu.parallel.sharding import make_mesh  # noqa: E402
from kubernetes_tpu.perf.ledger import (GLOBAL as LEDGER,  # noqa: E402
                                        KERNELS, CompileLedger,
                                        KernelRecord)
from kubernetes_tpu.perf import observatory as obs_mod  # noqa: E402
from kubernetes_tpu.perf.observatory import (GLOBAL as OBS,  # noqa: E402
                                             _KernelStats, _OVERFLOW_KEY,
                                             ENTRY_KERNELS, MAX_PLAN_KEYS,
                                             StreamingHist)
from kubernetes_tpu.scheduler import Scheduler  # noqa: E402
from kubernetes_tpu.server import SchedulerServer  # noqa: E402
from kubernetes_tpu.testing.wrappers import make_node, make_pod  # noqa: E402
from kubernetes_tpu.utils.tracing import (DEVICE_LANE_TID,  # noqa: E402
                                          Tracer, to_chrome_trace)


@pytest.fixture
def fresh_obs():
    """Zeroed process-global observatory; restored (re-enabled, zeroed)
    afterwards so absolute-count assertions don't see other tests'
    dispatches and vice versa."""
    OBS.reset()
    OBS.enable(True)
    yield OBS
    OBS.reset()
    OBS.enable(True)


def _mk(nodes=24, **kw):
    """Small drainable cluster with a REAL tracer (the scheduler default
    is NOOP_TRACER, which drops the device-lane child spans)."""
    api = APIServer()
    kw.setdefault("tracer", Tracer(slow_threshold_s=999.0, keep_recent=64))
    sched = Scheduler(api, batch_size=64, **kw)
    for i in range(nodes):
        api.create_node(
            make_node(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
            .zone(f"z{i % 4}")
            .label("kubernetes.io/hostname", f"n{i}").obj())
    return api, sched


def _feed(api, n, spread=0):
    pods = []
    for i in range(n):
        w = make_pod(f"p{i}").req({"cpu": "100m", "memory": "64Mi"})
        if i < spread:
            w = w.label("app", "obs").spread_constraint(
                1, "topology.kubernetes.io/zone", "ScheduleAnyway",
                {"app": "obs"})
        pods.append(w.obj())
    api.create_pods(pods)


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# streaming histograms


class TestStreamingHist:
    def test_observe_and_quantiles(self):
        h = StreamingHist()
        for _ in range(90):
            h.observe(0.001)
        for _ in range(10):
            h.observe(0.016)
        assert h.count == 100
        assert abs(h.sum - (90 * 0.001 + 10 * 0.016)) < 1e-9
        assert h.max == 0.016
        # p50 sits in the 1ms decade, p99 in the 16ms decade, and the
        # log2 lattice keeps each within ~sqrt(2) of the true value
        assert 0.0005 < h.quantile(0.50) < 0.002
        assert 0.008 < h.quantile(0.99) < 0.032
        assert h.quantile(0.50) <= h.quantile(0.90) <= h.quantile(0.99)

    def test_to_dict_contract(self):
        h = StreamingHist()
        h.observe(0.002)
        d = h.to_dict()
        assert set(d) == {"count", "seconds", "p50_ms", "p90_ms",
                          "p99_ms", "max_ms"}
        assert d["count"] == 1 and d["max_ms"] == 2.0

    def test_overflow_folds_into_last_bucket(self):
        h = StreamingHist()
        h.observe(1e9)  # absurd wall: beyond the ~67s last edge
        assert h.counts[-1] == 1
        assert h.quantile(0.99) > 0  # finite, not an IndexError

    def test_empty_quantile_is_zero(self):
        assert StreamingHist().quantile(0.99) == 0.0

    def test_plan_key_overflow_bounded(self):
        st = _KernelStats()
        for i in range(MAX_PLAN_KEYS + 8):
            st.plan_hist((i,)).observe(0.001)
        assert len(st.plans) == MAX_PLAN_KEYS + 1
        assert st.plans[_OVERFLOW_KEY].count == 8


# ---------------------------------------------------------------------------
# ledger compile/run split + thread safety


class _CompilingFn:
    """Mimics a jitted callable whose first call mints an executable."""

    def __init__(self):
        self.cache = 0

    def _cache_size(self):
        return self.cache

    def __call__(self, *a, **kw):
        if not self.cache:
            self.cache = 1
            time.sleep(0.002)
        return 0


class _WarmFn:
    """A jitted callable with its executable already minted."""

    def _cache_size(self):
        return 1

    def __call__(self, *a, **kw):
        return 0


class TestLedgerSplit:
    def test_compile_vs_run_seconds_split(self, fresh_obs):
        led = CompileLedger()
        fn = _CompilingFn()
        led.measured_call("run_batch", fn)
        led.measured_call("run_batch", fn)
        rec = led.kernels["run_batch"]
        assert rec.calls == 2 and rec.compiles == 1
        assert rec.compile_seconds > 0
        assert rec.run_calls == 1 and rec.run_seconds >= 0
        # the observatory saw both, routed by compile flag
        st = fresh_obs.kernels["run_batch"]
        assert st.dispatches == 2
        assert st.compile_calls == 1 and st.hist.count == 1

    def test_fn_without_cache_probe_counts_warm(self, fresh_obs):
        led = CompileLedger()
        led.measured_call("run_uniform", lambda: 7)
        rec = led.kernels["run_uniform"]
        assert rec.compiles == 0 and rec.run_calls == 1

    def test_compile_overhead_property(self):
        rec = KernelRecord(calls=3, compiles=1, compile_seconds=2.0,
                           run_calls=2, run_seconds=0.2)
        assert abs(rec.compile_overhead_seconds - 1.9) < 1e-9
        # no warm sample yet: the whole compiling wall is overhead
        rec2 = KernelRecord(calls=1, compiles=1, compile_seconds=2.0)
        assert rec2.compile_overhead_seconds == 2.0

    def test_measured_call_thread_safe(self, fresh_obs):
        led = CompileLedger()
        fn = _WarmFn()
        n_threads, n_calls = 8, 200

        def hammer():
            for _ in range(n_calls):
                led.measured_call("run_batch", fn)

        threads = [threading.Thread(target=hammer)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rec = led.kernels["run_batch"]
        total = n_threads * n_calls
        assert rec.calls == total
        assert rec.run_calls == total and rec.compiles == 0
        st = fresh_obs.kernels["run_batch"]
        assert st.dispatches == total and st.hist.count == total


# ---------------------------------------------------------------------------
# observatory capture semantics


class TestObservatoryCapture:
    def test_warm_vs_compiled_routing(self, fresh_obs):
        OBS.on_call("run_wave", 0.0, 0.004, False, ())
        OBS.on_call("run_wave", 1.0, 2.500, True, ())
        st = OBS.kernels["run_wave"]
        assert st.dispatches == 2 and st.compile_calls == 1
        assert st.hist.count == 1 and abs(st.hist.sum - 0.004) < 1e-9

    def test_disabled_gate_drops_calls(self, fresh_obs):
        OBS.enable(False)
        OBS.on_call("run_wave", 0.0, 0.004, False, ())
        OBS.enable(True)
        assert OBS.kernels["run_wave"].dispatches == 0

    def test_drain_window_captures_in_order(self, fresh_obs):
        OBS.on_call("run_plan", 0.0, 0.001, False, ())   # outside: dropped
        OBS.begin_drain()
        OBS.on_call("run_uniform", 1.0, 0.010, False, ())
        OBS.on_call("run_wave", 2.0, 0.020, True, ())
        events = OBS.end_drain()
        assert [e[0] for e in events] == ["run_uniform", "run_wave"]
        assert OBS.end_drain() == []  # window closed

    def test_lane_seconds_and_spans(self, fresh_obs):
        events = [("run_uniform", 0.0, 0.5, False),
                  ("run_uniform", 1.0, 0.25, False),
                  ("run_wave", 2.0, 0.125, True)]
        assert OBS.lane_seconds(events) == {"run_uniform": 0.75,
                                            "run_wave": 0.125}
        spans = OBS.lane_spans(events, drain_id=7)
        assert [s.name for s in spans] == ["kernel:run_uniform",
                                           "kernel:run_uniform",
                                           "kernel:run_wave"]
        assert all(s.attributes["lane"] == "device" and
                   s.attributes["drain"] == 7 for s in spans)
        assert spans[2].attributes.get("compiled") is True
        assert "compiled" not in spans[0].attributes

    def test_shape_keys_split_plan_histograms(self, fresh_obs):
        OBS.on_call("run_batch", 0.0, 0.001, False, (np.zeros((4, 2)), 3))
        OBS.on_call("run_batch", 0.0, 0.001, False, (np.zeros((8, 2)), 3))
        OBS.on_call("run_batch", 0.0, 0.001, False, (np.zeros((4, 2)), 3))
        st = OBS.kernels["run_batch"]
        assert len(st.plans) == 2
        assert sorted(h.count for h in st.plans.values()) == [1, 2]

    def test_checkpoint_delta(self, fresh_obs):
        OBS.on_call("diagnose", 0.0, 0.002, False, ())
        chk = OBS.checkpoint()
        for _ in range(3):
            OBS.on_call("diagnose", 0.0, 0.004, False, ())
        delta = OBS.delta_since(chk)
        assert set(delta) == {"diagnose"}
        d = delta["diagnose"]
        assert d["calls"] == 3 and d["dispatches"] == 3
        assert abs(d["seconds"] - 0.012) < 1e-9
        assert d["p50_ms"] > 0

    def test_snapshot_preseeds_all_kernels(self, fresh_obs):
        snap = OBS.snapshot()
        assert set(snap["kernels"]) == set(KERNELS)
        assert snap["enabled"] is True and snap["backend"]
        assert snap["shardLanes"] == {}

    def test_snapshot_top_plans_limit(self, fresh_obs):
        for i in range(7):
            OBS.on_call("run_gang", 0.0, 0.001 * (i + 1), False,
                        (np.zeros((i + 1,)),))
        snap = OBS.snapshot(top_plans=3)
        plans = snap["kernels"]["run_gang"]["plans"]
        assert len(plans) == 3
        # ranked by cumulative seconds: the slowest variants survive
        assert all(p["count"] == 1 for p in plans.values())

    def test_metrics_view_covers_all_kernels(self, fresh_obs):
        kernels, shard = OBS.metrics_view()
        assert set(kernels) == set(KERNELS)
        assert shard == {}

    def test_entry_kernels_cover_ledger(self):
        # every mapped kernel is a real ledger kernel, and the map spans
        # all thirteen (the tools/check.py config gate's ground truth)
        assert set(ENTRY_KERNELS.values()) == set(KERNELS)


# ---------------------------------------------------------------------------
# /debug/kernels + the flight-record decomposition (acceptance)


class TestDebugKernels:
    def test_lists_all_thirteen_after_drain(self, fresh_obs):
        api, sched = _mk()
        _feed(api, 48, spread=12)
        sched.schedule_pending()
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/kernels")
            assert code == 200
            snap = json.loads(body)
            assert snap["enabled"] is True
            assert set(snap["kernels"]) == set(KERNELS)
            dispatched = {k: v for k, v in snap["kernels"].items()
                          if v["dispatches"]}
            assert dispatched, snap["kernels"]
            # the drain's mainline kernels ran and have run-time stats
            assert any(v["count"] > 0 or v["compileCalls"] > 0
                       for v in dispatched.values())
            code, body = _get(srv.port, "/debug/kernels?plans=1")
            assert code == 200
            snap = json.loads(body)
            assert all(len(v["plans"]) <= 1
                       for v in snap["kernels"].values())
        finally:
            srv.stop()

    def test_gate_off_404(self, fresh_obs):
        cfg = KubeSchedulerConfiguration(
            feature_gates={"KernelObservatory": False})
        api, sched = _mk(config=cfg)
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/kernels")
            assert code == 404 and "KernelObservatory" in body
        finally:
            srv.stop()

    def test_flight_kernels_decompose_device_phase(self, fresh_obs):
        """ISSUE 14 acceptance: a drain's per-kernel seconds cross-check
        against its device_dispatch phase span within 10%."""
        api, sched = _mk()
        _feed(api, 96, spread=24)
        sched.schedule_pending()
        recs = [r for r in sched.flight.dump()
                if r["kernels"] and r["phases"].get("device_dispatch")]
        assert recs, "no device drains recorded"
        rec = max(recs, key=lambda r: r["phases"]["device_dispatch"])
        ksum = sum(rec["kernels"].values())
        dev = rec["phases"]["device_dispatch"]
        assert set(rec["kernels"]) <= set(KERNELS)
        assert ksum <= dev * 1.02 + 1e-6, (ksum, dev)
        assert ksum >= 0.90 * dev, (ksum, dev)


# ---------------------------------------------------------------------------
# Chrome-trace merge


class TestChromeTraceMerge:
    def test_device_lane_spans_merge_into_trace(self, fresh_obs):
        api, sched = _mk()
        _feed(api, 48, spread=12)
        sched.schedule_pending()
        spans = list(sched.tracer.recent)
        assert spans, "tracer retained no root spans"
        trace = to_chrome_trace(spans)
        json.dumps(trace)  # valid JSON end to end

        events = trace["traceEvents"]
        names = [e for e in events
                 if e["ph"] == "M" and e["name"] == "thread_name"
                 and e["tid"] == DEVICE_LANE_TID]
        assert names and names[0]["args"]["name"] == "device-lanes"

        lanes = [e for e in events
                 if e["ph"] == "X" and e["tid"] == DEVICE_LANE_TID]
        assert lanes, "no device-lane events in the merged trace"
        assert all(e["name"].startswith("kernel:") for e in lanes)
        assert all(e["name"].split(":", 1)[1] in KERNELS for e in lanes)

        devs = {e["args"]["drain"]: e for e in events
                if e["ph"] == "X" and e["name"] == "device_dispatch"}
        assert devs
        for lane in lanes:
            dev = devs[lane["args"]["drain"]]
            # strict timewise nesting inside the owning drain's span
            assert lane["ts"] >= dev["ts"] - 0.5, (lane, dev)
            assert (lane["ts"] + lane["dur"]
                    <= dev["ts"] + dev["dur"] + 0.5), (lane, dev)
        for did, dev in devs.items():
            in_span = [e for e in lanes if e["args"]["drain"] == did]
            assert sum(e["dur"] for e in in_span) <= dev["dur"] * 1.01 + 0.5


# ---------------------------------------------------------------------------
# sharded-lane profile (8-device host mesh from conftest XLA_FLAGS)


class TestShardLanes:
    def test_profile_lands_after_sharded_drain(self, fresh_obs):
        mesh = make_mesh(4)
        api, sched = _mk(nodes=32, mesh=mesh)
        _feed(api, 48)
        sched.schedule_pending()
        prof = sched.observatory.shard_profile()
        assert prof.get("nDevices") == 4, prof
        assert len(prof["laneSeconds"]) == 4
        assert prof["totalSeconds"] > 0
        assert prof["imbalanceRatio"] >= 1.0
        assert 0.0 <= prof["commsShare"] <= 1.0
        # the metric mirror exports it at exposition time
        text = sched.metrics.exposition()
        assert 'scheduler_shard_lane_seconds{lane="0"}' in text
        assert "scheduler_shard_imbalance_ratio" in text

    def test_debug_refresh_reruns_probe(self, fresh_obs):
        mesh = make_mesh(4)
        api, sched = _mk(nodes=32, mesh=mesh)
        _feed(api, 48)
        sched.schedule_pending()
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/kernels?lanes=refresh")
            assert code == 200
            snap = json.loads(body)
            assert snap["shardLanes"].get("nDevices") == 4
        finally:
            srv.stop()

    def test_force_reprofile(self, fresh_obs):
        mesh = make_mesh(4)
        api, sched = _mk(nodes=32, mesh=mesh)
        _feed(api, 48)
        sched.schedule_pending()
        first = sched.observatory.shard_profile()
        again = sched.profile_shard_lanes(force=True)
        assert again and again.get("nDevices") == first.get("nDevices")


# ---------------------------------------------------------------------------
# no hidden retraces with the observatory ON


class TestRetraceBudgetWithObservatory:
    WARM_PASSES_MAX = 4

    def test_warm_rerun_fits_zero_budget(self, fresh_obs):
        assert OBS.enabled

        def one_pass():
            api, sched = _mk(nodes=32)
            _feed(api, 48, spread=12)
            sched.schedule_pending()

        for _ in range(self.WARM_PASSES_MAX):
            before = {k: r.compiles for k, r in LEDGER.kernels.items()}
            one_pass()
            deltas = {k: r.compiles - before.get(k, 0)
                      for k, r in LEDGER.kernels.items()
                      if k in KERNELS and r.compiles - before.get(k, 0)}
            if not deltas:
                break
        else:
            pytest.fail(f"kernels still minting after "
                        f"{self.WARM_PASSES_MAX} warm passes: {deltas}")
        # observing every dispatch must not mint a single executable
        with RAILS.retrace_budget(0, kernels=KERNELS):
            one_pass()


# ---------------------------------------------------------------------------
# tools: kernel_sweep self-test + check.py observatory gate


class TestKernelSweep:
    def test_self_test_subprocess(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "kernel_sweep.py"),
             "--self-test"],
            capture_output=True, text=True, env=env, timeout=300)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "self-test: OK" in p.stdout


def _load_check():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_tpu_tools_check", os.path.join(REPO, "tools", "check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestObservatoryGaps:
    def test_real_config_fully_covered(self):
        assert _load_check().observatory_gaps() == []

    def test_unmapped_entry_reported(self):
        gaps = _load_check().observatory_gaps({"m": ("bogus_fn",)})
        assert gaps == ["m.bogus_fn (not in ENTRY_KERNELS)"]

    def test_entry_mapped_to_unknown_kernel(self, monkeypatch):
        monkeypatch.setitem(obs_mod.ENTRY_KERNELS, "weird_fn",
                            "no_such_kernel")
        gaps = _load_check().observatory_gaps({"m": ("weird_fn",)})
        assert gaps and "no_such_kernel" in gaps[0]


# ---------------------------------------------------------------------------
# overhead gate (slow tier)


@pytest.mark.slow
class TestObservatoryOverheadGate:
    def test_overhead_within_5_percent_at_5k_nodes(self):
        """ISSUE 14 acceptance: SchedulingBasic-shaped 5k-node drains
        with KernelObservatory ON stay within 5% of gate-OFF throughput
        (median of 3 measured passes each, warm shapes — the ISSUE 13
        gate shape)."""

        def _feed_many(api, n, start=0):
            api.create_pods([make_pod(f"p{start + i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj() for i in range(n)])

        def one_pass(gate_on):
            cfg = KubeSchedulerConfiguration(feature_gates={
                "KernelObservatory": gate_on})
            api = APIServer()
            sched = Scheduler(api, batch_size=8192, config=cfg)
            for i in range(5000):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
            sched.prime()
            t0 = time.perf_counter()
            created = 0
            while created < 10000:
                _feed_many(api, 512, start=created)
                created += 512
                sched.schedule_pending(wait=False)
            sched.schedule_pending()
            dt = time.perf_counter() - t0
            assert sched.scheduled_count == created
            return created / dt

        try:
            one_pass(True)   # warm every executable outside the measurement
            off = sorted(one_pass(False) for _ in range(3))[1]
            on = sorted(one_pass(True) for _ in range(3))[1]
        finally:
            OBS.enable(True)
        assert on >= 0.95 * off, (
            f"observatory overhead gate: on={on:.0f} off={off:.0f} pods/s "
            f"({on / off - 1:+.1%})")
