"""DRA: structured-parameters device allocation
(plugins/dynamicresources.py; reference
pkg/scheduler/framework/plugins/dynamicresources/)."""

import pytest

from kubernetes_tpu.api.types import (Device, DeviceRequest, ObjectMeta,
                                      ResourceClaim, ResourceSlice)
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _gpu_slice(node, count=2, driver="gpu.example.com", mem="16Gi"):
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}-{driver}"),
        node_name=node, driver=driver,
        devices=[Device(name=f"{node}-gpu{i}",
                        attributes=(("memory", mem), ("kind", "gpu")))
                 for i in range(count)])


def _claim(name, driver="gpu.example.com", count=1, selectors=None):
    return ResourceClaim(
        metadata=ObjectMeta(name=name),
        requests=[DeviceRequest(name="req-0", driver=driver, count=count,
                                selectors=selectors or {})])


def _cluster(n_nodes=3, gpus_on=("n1",)):
    api = APIServer()
    sched = Scheduler(api, batch_size=32)
    for i in range(n_nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 110}).obj())
    for n in gpus_on:
        api.create_resource_slice(_gpu_slice(n))
    return api, sched


class TestAllocation:
    def test_claim_pod_lands_on_device_node(self):
        api, sched = _cluster(gpus_on=("n1",))
        api.create_resource_claim(_claim("c0"))
        api.create_pod(make_pod("p0").req(
            {"cpu": "1", "memory": "1Gi"}).claim("c0").obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/p0"].spec.node_name == "n1"
        claim = api.get_resource_claim("default", "c0")
        assert claim.allocation is not None
        assert claim.allocation.node_name == "n1"
        assert claim.reserved_for == ["default/p0"]

    def test_selector_filters_devices(self):
        api, sched = _cluster(gpus_on=("n1",))
        api.create_resource_slice(_gpu_slice("n2", mem="80Gi"))
        api.create_resource_claim(_claim("big", selectors={"memory": "80Gi"}))
        api.create_pod(make_pod("p0").req(
            {"cpu": "1", "memory": "1Gi"}).claim("big").obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/p0"].spec.node_name == "n2"

    def test_devices_are_exclusive_across_claims(self):
        """Two pods, two claims, one node with 2 GPUs asking 2 each: only
        one can allocate; the other is unschedulable until capacity."""
        api, sched = _cluster(gpus_on=("n1",))
        for i in range(2):
            api.create_resource_claim(_claim(f"c{i}", count=2))
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).claim(f"c{i}").obj())
        assert sched.schedule_pending() == 1
        pods = [api.pods[f"default/p{i}"] for i in range(2)]
        assert sorted(bool(p.spec.node_name) for p in pods) == [False, True]

    def test_allocated_claim_pins_node(self):
        """A pre-allocated claim restricts the pod to the allocation's
        node (PreFilter shortcut)."""
        from kubernetes_tpu.api.types import DeviceAllocation
        api, sched = _cluster(gpus_on=("n1", "n2"))
        c = _claim("pinned")
        c.allocation = DeviceAllocation(
            node_name="n2",
            results={"req-0": (("gpu.example.com", "n2-gpu0"),)})
        api.create_resource_claim(c)
        api.create_pod(make_pod("p0").req(
            {"cpu": "1", "memory": "1Gi"}).claim("pinned").obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/p0"].spec.node_name == "n2"

    def test_missing_claim_unschedulable_until_created(self):
        api, sched = _cluster(gpus_on=("n1",))
        api.create_pod(make_pod("p0").req(
            {"cpu": "1", "memory": "1Gi"}).claim("later").obj())
        assert sched.schedule_pending() == 0
        # claim arrival requeues via the ResourceClaim event
        api.create_resource_claim(_claim("later"))
        import time
        time.sleep(1.1)   # pod backoff
        sched.flush_queues()
        assert sched.schedule_pending() == 1

    def test_no_devices_no_fit(self):
        api, sched = _cluster(gpus_on=())
        api.create_resource_claim(_claim("c0"))
        api.create_pod(make_pod("p0").req(
            {"cpu": "1", "memory": "1Gi"}).claim("c0").obj())
        assert sched.schedule_pending() == 0

    def test_gate_removes_plugin(self):
        from kubernetes_tpu.config import (KubeSchedulerConfiguration,
                                           build_profiles)
        cfg = KubeSchedulerConfiguration(
            feature_gates={"DynamicResourceAllocation": False})
        profs = build_profiles(cfg, APIServer())
        names = [p.name() for p in profs[0].framework.plugins]
        assert "DynamicResources" not in names

    def test_claimless_pods_keep_fast_path(self):
        """DRA in the default plugin set must not push claim-free pods
        onto the per-pod hook chain."""
        from kubernetes_tpu.scheduler import _needs_per_pod_hooks
        api, sched = _cluster()
        prof = next(iter(sched.profiles.values()))
        assert prof.gang_only_hooks
        pod = make_pod("plain").req({"cpu": "1", "memory": "1Gi"}).obj()
        assert not _needs_per_pod_hooks(prof, pod.spec)
        claimed = make_pod("claimed").req(
            {"cpu": "1", "memory": "1Gi"}).claim("c").obj()
        assert _needs_per_pod_hooks(prof, claimed.spec)


class TestReviewRegressions:
    def test_one_pod_two_claims_cannot_double_book_a_device(self):
        """Review finding: Filter/Reserve must thread occupancy across a
        pod's OWN claims."""
        api, sched = _cluster(gpus_on=())
        api.create_resource_slice(_gpu_slice("n1", count=1))
        for i in range(2):
            api.create_resource_claim(_claim(f"c{i}", count=1))
        api.create_pod(make_pod("p0").req(
            {"cpu": "1", "memory": "1Gi"}).claim("c0", "c1").obj())
        assert sched.schedule_pending() == 0   # 1 device can't serve 2 claims
        # and with 2 devices it fits, on distinct devices
        api.create_resource_slice(_gpu_slice("n2", count=2))
        import time; time.sleep(1.1)
        sched.flush_queues()
        assert sched.schedule_pending() == 1
        c0 = api.get_resource_claim("default", "c0")
        c1 = api.get_resource_claim("default", "c1")
        assert not (c0.allocation.device_ids() & c1.allocation.device_ids())

    def test_plugin_args_without_strategy_keep_profile_strategy(self):
        """Review finding: pluginArgs lacking scoringStrategy must not
        reset the profile-level MostAllocated."""
        from kubernetes_tpu.config import (KubeSchedulerConfiguration,
                                           build_profiles)
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [{
            "scoringStrategy": "MostAllocated",
            "pluginArgs": {"NodeResourcesFit": {
                "ignoredResources": ["example.com/foo"]}}}]})
        cfg.validate()
        profs = build_profiles(cfg, APIServer())
        assert profs[0].score_config.strategy == "MostAllocated"
        fit = next(p for p in profs[0].framework.plugins
                   if p.name() == "NodeResourcesFit")
        assert fit.args.scoring_strategy == "MostAllocated"
        assert "example.com/foo" in fit.args.ignored_resources

    def test_pdb_change_requeues_unschedulable_pod(self):
        """Review finding: the PDB watch must actually move pods."""
        api, sched = _cluster(n_nodes=1, gpus_on=())
        filler = make_pod("filler").req(
            {"cpu": "8", "memory": "1Gi"}).label("app", "f").obj()
        api.create_pod(filler)
        api.bind(filler, "n0")
        from kubernetes_tpu.api.types import (LabelSelector, ObjectMeta,
                                              PodDisruptionBudget)
        api.create_pdb(PodDisruptionBudget(
            metadata=ObjectMeta(name="block"),
            selector=LabelSelector.of(match_labels={"app": "f"}),
            min_available=1))
        api.create_pod(make_pod("vip").req(
            {"cpu": "8", "memory": "1Gi"}).priority(100).obj())
        sched.schedule_pending()
        # preemption proceeds despite the PDB (best effort) OR parks the
        # pod; either way deleting the PDB must requeue, not strand
        api.delete_pdb("default/block")
        assert ("default/vip" not in sched.queue.unschedulable_pods
                or sched.queue.unschedulable_pods["default/vip"].gated
                or "default/vip" in sched.queue.backoff_q
                or "default/vip" in sched.queue.active_q)
