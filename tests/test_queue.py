"""SchedulingQueue tests (reference backend/queue/scheduling_queue_test.go
essentials)."""

from kubernetes_tpu.backend.queue import (ClusterEventWithHint, SchedulingQueue)
from kubernetes_tpu.framework.interface import Status
from kubernetes_tpu.framework.types import (ActionType, ClusterEvent,
                                            EventResource, QueueingHint)
from kubernetes_tpu.testing.wrappers import make_pod

NODE_ADD = ClusterEvent(EventResource.NODE, ActionType.ADD)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def mk_queue(**kw):
    clock = kw.pop("clock", FakeClock())
    return SchedulingQueue(clock=clock, **kw), clock


class TestPopOrder:
    def test_priority_then_fifo(self):
        q, _ = mk_queue()
        low = make_pod("low").priority(1).obj()
        high = make_pod("high").priority(10).obj()
        mid = make_pod("mid").priority(5).obj()
        for p in (low, high, mid):
            q.add(p)
        assert [q.pop().pod.name for _ in range(3)] == ["high", "mid", "low"]

    def test_drain_whole_queue(self):
        q, _ = mk_queue()
        for i in range(5):
            q.add(make_pod(f"p{i}").obj())
        batch = q.drain()
        assert len(batch) == 5
        assert q.pop() is None


class TestUnschedulableFlow:
    def test_parked_until_event(self):
        q, clock = mk_queue()
        q.add(make_pod("p").obj())
        qpi = q.pop()
        qpi.unschedulable_plugins = {"NodeResourcesFit"}
        q.add_unschedulable_if_not_present(qpi)
        assert q.pop() is None
        assert len(q.unschedulable_pods) == 1

        # no hints registered for the plugin → any matching event requeues
        q.move_all_to_active_or_backoff_queue(NODE_ADD)
        clock.t += 2.0  # past backoff (1s for first failure)
        assert q.pop().pod.name == "p"

    def test_hint_skip_keeps_parked(self):
        hints = {"NodeResourcesFit": [ClusterEventWithHint(
            NODE_ADD, hint_fn=lambda pod, old, new: QueueingHint.SKIP)]}
        q, _ = mk_queue(queueing_hints=hints)
        q.add(make_pod("p").obj())
        qpi = q.pop()
        qpi.unschedulable_plugins = {"NodeResourcesFit"}
        q.add_unschedulable_if_not_present(qpi)
        assert q.move_all_to_active_or_backoff_queue(NODE_ADD) == 0
        assert len(q.unschedulable_pods) == 1

    def test_hint_queue_moves(self):
        hints = {"NodeResourcesFit": [ClusterEventWithHint(
            NODE_ADD, hint_fn=lambda pod, old, new: QueueingHint.QUEUE)]}
        q, clock = mk_queue(queueing_hints=hints)
        q.add(make_pod("p").obj())
        qpi = q.pop()
        qpi.unschedulable_plugins = {"NodeResourcesFit"}
        q.add_unschedulable_if_not_present(qpi)
        assert q.move_all_to_active_or_backoff_queue(NODE_ADD) == 1
        clock.t += 2.0
        assert q.pop().pod.name == "p"

    def test_in_flight_event_requeues_to_backoff(self):
        # an event arriving DURING the scheduling attempt must not be lost
        # (active_queue.go:358-431)
        q, clock = mk_queue()
        q.add(make_pod("p").obj())
        qpi = q.pop()
        q.move_all_to_active_or_backoff_queue(NODE_ADD)  # while in flight
        qpi.unschedulable_plugins = {"NodeResourcesFit"}
        q.add_unschedulable_if_not_present(qpi)
        # went to backoffQ, not the unschedulable pool
        assert len(q.unschedulable_pods) == 0
        clock.t += 2.0
        assert q.pop().pod.name == "p"

    def test_backoff_grows_exponentially(self):
        q, clock = mk_queue()
        q.add(make_pod("p").obj())
        for attempt, expected_backoff in ((1, 1.0), (2, 2.0), (3, 4.0)):
            qpi = q.pop()
            assert qpi is not None, f"attempt {attempt}"
            qpi.unschedulable_plugins = {"X"}
            q.add_unschedulable_if_not_present(qpi)
            q.move_all_to_active_or_backoff_queue(NODE_ADD)
            clock.t += expected_backoff - 0.01
            assert q.pop() is None  # still backing off
            clock.t += 0.02

    def test_unschedulable_timeout_flush(self):
        q, clock = mk_queue()
        q.add(make_pod("p").obj())
        qpi = q.pop()
        qpi.unschedulable_plugins = {"X"}
        q.add_unschedulable_if_not_present(qpi)
        clock.t += 299.0
        assert q.flush_unschedulable_leftover() == 0
        clock.t += 2.0
        assert q.flush_unschedulable_leftover() == 1


class TestGating:
    def test_pre_enqueue_gate(self):
        gate_open = {"open": False}

        def pre_enqueue(pod):
            return (Status.success() if gate_open["open"]
                    else Status.unschedulable("gated", plugin="SchedulingGates"))

        q, _ = mk_queue(pre_enqueue=pre_enqueue)
        q.add(make_pod("p").obj())
        assert q.pop() is None
        assert len(q.gated_pods_could_be_ungated()) == 1
        gate_open["open"] = True
        assert q.retry_gated() == 1
        assert q.pop().pod.name == "p"

    def test_gated_pods_ignore_events(self):
        q, _ = mk_queue(pre_enqueue=lambda pod: Status.unschedulable(
            "g", plugin="SchedulingGates"))
        q.add(make_pod("p").obj())
        assert q.move_all_to_active_or_backoff_queue(NODE_ADD) == 0


class TestActivateAndNominator:
    def test_activate_skips_backoff(self):
        q, _ = mk_queue()
        q.add(make_pod("p").obj())
        qpi = q.pop()
        qpi.unschedulable_plugins = {"X"}
        q.add_unschedulable_if_not_present(qpi)
        q.activate([qpi.pod])
        assert q.pop().pod.name == "p"  # no backoff wait

    def test_nominator(self):
        q, _ = mk_queue()
        p = make_pod("p").obj()
        q.add(p)
        qpi = q.pop()
        q.nominator.add(qpi, "node-1")
        assert q.nominator.nominated_node_for(p) == "node-1"
        assert [x.pod.name for x in q.nominator.pods_for_node("node-1")] == ["p"]
        q.nominator.delete(p)
        assert q.nominator.pods_for_node("node-1") == []


def test_default_sort_key_matches_less():
    """default_queue_sort_key must induce exactly default_queue_sort_less's
    order (the bulk drain depends on it)."""
    import random
    from kubernetes_tpu.backend.queue import (default_queue_sort_key,
                                              default_queue_sort_less)
    from kubernetes_tpu.framework.types import PodInfo, QueuedPodInfo
    from kubernetes_tpu.testing.wrappers import make_pod
    rng = random.Random(5)
    qpis = [QueuedPodInfo(pod_info=PodInfo.of(
                make_pod(f"p{i}").priority(rng.randint(0, 3)).obj()),
            timestamp=float(rng.randint(0, 3))) for i in range(40)]
    by_key = sorted(qpis, key=default_queue_sort_key)
    # insertion sort by the less-fn gives the canonical order
    by_less = []
    for q in qpis:
        i = 0
        while i < len(by_less) and default_queue_sort_less(by_less[i], q):
            i += 1
        by_less.insert(i, q)
    assert [q.pod.uid for q in by_key] == [q.pod.uid for q in by_less]


def test_bulk_drain_matches_per_pop():
    import random
    from kubernetes_tpu.backend.queue import SchedulingQueue
    from kubernetes_tpu.testing.wrappers import make_pod
    rng = random.Random(7)
    pods = [make_pod(f"p{i}").priority(rng.randint(0, 4)).obj()
            for i in range(50)]
    q1 = SchedulingQueue(clock=lambda: 0.0)
    q2 = SchedulingQueue(clock=lambda: 0.0)
    for p in pods:
        q1.add(p)
        q2.add(p)
    bulk = q1.drain()                      # sort fast path
    singles = []
    while True:                            # per-pop path
        qpi = q2.pop()
        if qpi is None:
            break
        singles.append(qpi)
    assert [x.pod.uid for x in bulk] == [x.pod.uid for x in singles]
    # capped drain: remainder stays poppable in order
    q3 = SchedulingQueue(clock=lambda: 0.0)
    for p in pods:
        q3.add(p)
    first = q3.drain(20)
    rest = q3.drain()
    assert [x.pod.uid for x in first + rest] == [x.pod.uid for x in singles]
