"""ImageLocality tests (reference image_locality_test.go essentials)."""

from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.plugins.imagelocality import (MB, ImageLocality,
                                                  calculate_priority,
                                                  normalized_image_name)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def test_normalized_image_name():
    assert normalized_image_name("nginx") == "nginx:latest"
    assert normalized_image_name("nginx:1.25") == "nginx:1.25"
    assert normalized_image_name("reg:5000/nginx") == "reg:5000/nginx:latest"
    assert normalized_image_name("reg:5000/nginx:tag") == "reg:5000/nginx:tag"


def test_calculate_priority_clamps():
    assert calculate_priority(0, 1) == 0
    assert calculate_priority(23 * MB, 1) == 0
    assert calculate_priority(1000 * MB, 1) == 100
    assert calculate_priority(5000 * MB, 1) == 100
    mid = calculate_priority(500 * MB, 1)
    assert 0 < mid < 100


def test_score_prefers_node_with_image():
    ni_with = NodeInfo(node=make_node("with").obj())
    ni_with.image_sizes["nginx:latest"] = 900 * MB
    ni_without = NodeInfo(node=make_node("without").obj())

    pod = make_pod("p").obj()
    pod.spec.containers[0].image = "nginx"

    pl = ImageLocality()
    state = CycleState()
    pl.pre_score(state, pod, [ni_with, ni_without])
    s_with, _ = pl.score(state, pod, ni_with)
    s_without, _ = pl.score(state, pod, ni_without)
    assert s_with > s_without == 0


MBs = 1024 * 1024


class TestDeviceParity:
    """The tensor form (ops/program.py image_locality_score) must agree
    with the host plugin on the same cluster — image-bearing pods no
    longer fall back to the host oracle."""

    def test_device_pod_prefers_image_node(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(4):
            n = make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 110})
            if i == 2:
                n = n.image("ml-train:latest", 900 * MBs)
            api.create_node(n.obj())
        for i in range(3):
            pod = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj()
            pod.spec.containers[0].image = "ml-train"
            api.create_pod(pod)
        assert sched.schedule_pending() == 3
        # no host fallback: the batch stayed on device
        assert sched.host_scheduled == 0
        # the image node wins until resource scores outweigh it
        assert api.pods["default/p0"].spec.node_name == "n2"

    def test_device_matches_oracle_with_images(self):
        import numpy as np
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.framework.runtime import schedule_pod
        from kubernetes_tpu.scheduler import Scheduler
        # two clusters, one scheduled by device, one by the host oracle
        def build(run_min):
            api = APIServer()
            sched = Scheduler(api, batch_size=64)
            sched.UNIFORM_RUN_MIN = run_min
            for i in range(5):
                n = make_node(f"n{i}").capacity(
                    {"cpu": 16, "memory": "32Gi", "pods": 110})
                if i % 2 == 0:
                    n = n.image("app:v1", (300 + 100 * i) * MBs)
                api.create_node(n.obj())
            for i in range(12):
                pod = make_pod(f"p{i}").req(
                    {"cpu": "2", "memory": "1Gi"}).obj()
                pod.spec.containers[0].image = "app:v1"
                api.create_pod(pod)
            assert sched.schedule_pending() == 12
            return {p.name: p.spec.node_name for p in api.pods.values()}
        fast = build(16)        # closed-form path
        scan = build(10 ** 9)   # scan path
        assert fast == scan

    def test_many_images_grow_instead_of_truncate(self):
        """A node holding more images than the padded dim must grow the
        arrays — truncation would silently drop the pod's image and pick
        the wrong node (reproduced in review)."""
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(3):
            n = make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 110})
            if i == 2:
                for j in range(10):   # zz images sort past the default dim
                    n = n.image(f"aa-filler-{j:02d}:latest", 50 * MBs)
                n = n.image("zz-wanted:latest", 900 * MBs)
            api.create_node(n.obj())
        pod = make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj()
        pod.spec.containers[0].image = "zz-wanted"
        api.create_pod(pod)
        assert sched.schedule_pending() == 1
        assert sched.host_scheduled == 0
        assert api.pods["default/p"].spec.node_name == "n2"
