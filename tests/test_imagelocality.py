"""ImageLocality tests (reference image_locality_test.go essentials)."""

from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.plugins.imagelocality import (MB, ImageLocality,
                                                  calculate_priority,
                                                  normalized_image_name)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def test_normalized_image_name():
    assert normalized_image_name("nginx") == "nginx:latest"
    assert normalized_image_name("nginx:1.25") == "nginx:1.25"
    assert normalized_image_name("reg:5000/nginx") == "reg:5000/nginx:latest"
    assert normalized_image_name("reg:5000/nginx:tag") == "reg:5000/nginx:tag"


def test_calculate_priority_clamps():
    assert calculate_priority(0, 1) == 0
    assert calculate_priority(23 * MB, 1) == 0
    assert calculate_priority(1000 * MB, 1) == 100
    assert calculate_priority(5000 * MB, 1) == 100
    mid = calculate_priority(500 * MB, 1)
    assert 0 < mid < 100


def test_score_prefers_node_with_image():
    ni_with = NodeInfo(node=make_node("with").obj())
    ni_with.image_sizes["nginx:latest"] = 900 * MB
    ni_without = NodeInfo(node=make_node("without").obj())

    pod = make_pod("p").obj()
    pod.spec.containers[0].image = "nginx"

    pl = ImageLocality()
    state = CycleState()
    pl.pre_score(state, pod, [ni_with, ni_without])
    s_with, _ = pl.score(state, pod, ni_with)
    s_without, _ = pl.score(state, pod, ni_without)
    assert s_with > s_without == 0
