"""Queueing hints: per-plugin events_to_register + precise requeues.

The VERDICT criterion: a cluster event requeues ONLY the pods whose
rejection it can fix (fit.go EventsToRegister et al. +
scheduling_queue.go:456 isPodWorthRequeuing). Pods rejected by a plugin
whose hints say SKIP must stay in unschedulablePods.
"""

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _cluster(nodes=()):
    api = APIServer()
    clock = FakeClock()
    sched = Scheduler(api, batch_size=64, clock=clock)
    sched._clock_handle = clock
    for n in nodes:
        api.create_node(n)
    return api, sched


def _active_uids(sched):
    sched._clock_handle.t += 15.0
    sched.flush_queues()
    return set(sched.queue.active_q._items.keys())


class TestTaintHints:
    def test_taint_removal_requeues_only_taint_rejected(self):
        """The done-criterion test: one pod rejected by TaintToleration,
        one by NodeResourcesFit. Removing the taint must requeue only the
        taint-rejected pod."""
        api, sched = _cluster([
            make_node("n0").capacity({"cpu": 4, "memory": "8Gi", "pods": 10})
            .taint("dedicated", "db", "NoSchedule").obj()])
        api.create_pod(make_pod("tainted-out").req(
            {"cpu": "1", "memory": "1Gi"}).obj())
        # tolerates the taint so its rejection is attributed to Fit (the
        # filter chain checks taints before resources — reference order)
        api.create_pod(make_pod("too-big").req(
            {"cpu": "99", "memory": "1Gi"})
            .toleration(key="dedicated", value="db").obj())
        assert sched.schedule_pending() == 0
        assert len(sched.queue.unschedulable_pods) == 2
        # untaint the node
        api.update_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        active = _active_uids(sched)
        assert "default/tainted-out" in active
        assert "default/too-big" not in active
        assert "default/too-big" in sched.queue.unschedulable_pods

    def test_irrelevant_taint_change_requeues_nothing(self):
        api, sched = _cluster([
            make_node("n0").capacity({"cpu": 4, "memory": "8Gi", "pods": 10})
            .taint("dedicated", "db", "NoSchedule").obj()])
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        # taint changes but stays untolerated
        api.update_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10})
            .taint("dedicated", "cache", "NoSchedule").obj())
        assert _active_uids(sched) == set()


class TestFitHints:
    def test_node_growth_requeues_only_fitting_pods(self):
        api, sched = _cluster([
            make_node("n0").capacity({"cpu": 2, "memory": "8Gi", "pods": 10}).obj()])
        api.create_pod(make_pod("mid").req({"cpu": "4", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("huge").req({"cpu": "64", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        assert len(sched.queue.unschedulable_pods) == 2
        # allocatable grows to 8 cpu: enough for mid, not huge
        api.update_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "8Gi", "pods": 10}).obj())
        active = _active_uids(sched)
        assert "default/mid" in active and "default/huge" not in active

    def test_pod_delete_requeues_resource_overlappers(self):
        api, sched = _cluster([
            make_node("n0").capacity({"cpu": 4, "memory": "8Gi", "pods": 10}).obj()])
        api.create_pod(make_pod("holder").req({"cpu": "4", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1
        api.create_pod(make_pod("waiter").req({"cpu": "2", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        assert "default/waiter" in sched.queue.unschedulable_pods
        api.delete_pod("default/holder")
        active = _active_uids(sched)
        assert "default/waiter" in active


class TestNodeAffinityHints:
    def test_label_change_requeues_only_matching(self):
        api, sched = _cluster([
            make_node("n0").capacity({"cpu": 8, "memory": "8Gi", "pods": 10}).obj()])
        api.create_pod(make_pod("wants-gpu").req({"cpu": "1", "memory": "1Gi"})
                       .node_affinity_in("accel", ["gpu"]).obj())
        api.create_pod(make_pod("wants-tpu").req({"cpu": "1", "memory": "1Gi"})
                       .node_affinity_in("accel", ["tpu"]).obj())
        sched.schedule_pending()
        assert len(sched.queue.unschedulable_pods) == 2
        api.update_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "8Gi", "pods": 10})
            .label("accel", "gpu").obj())
        active = _active_uids(sched)
        assert "default/wants-gpu" in active
        assert "default/wants-tpu" not in active

    def test_node_name_hint_fn(self):
        # unit level: a pod pinned by spec.nodeName is only requeued by the
        # arrival of THAT node (pods created with nodeName pre-set bypass
        # the scheduler entirely, so this path only matters for NodeName
        # rejections during scheduling)
        from kubernetes_tpu.framework.types import QueueingHint
        from kubernetes_tpu.plugins.node_basics import NodeName
        (hint,) = NodeName().events_to_register()
        pod = make_pod("pinned").node("n9").obj()
        other = make_node("n5").obj()
        mine = make_node("n9").obj()
        assert hint.hint_fn(pod, None, other) == QueueingHint.SKIP
        assert hint.hint_fn(pod, None, mine) == QueueingHint.QUEUE


class TestSpreadHints:
    def test_matching_pod_delete_requeues(self):
        zone = "topology.kubernetes.io/zone"
        nodes = [make_node(f"n{i}").capacity(
            {"cpu": 2, "memory": "8Gi", "pods": 10})
            .zone(f"z{i}").obj() for i in range(2)]
        api, sched = _cluster(nodes)
        # saturate z0 with spread-labeled pods so skew blocks the next one
        for i in range(2):
            api.create_pod(make_pod(f"s{i}").req({"cpu": "2", "memory": "1Gi"})
                           .label("app", "x")
                           .spread_constraint(1, zone, "DoNotSchedule",
                                              {"app": "x"}).obj())
        assert sched.schedule_pending() == 2
        api.create_pod(make_pod("s2").req({"cpu": "2", "memory": "1Gi"})
                       .label("app", "x")
                       .spread_constraint(1, zone, "DoNotSchedule",
                                          {"app": "x"}).obj())
        sched.schedule_pending()
        assert "default/s2" in sched.queue.unschedulable_pods
        # delete one member: counts move → requeue
        api.delete_pod("default/s0")
        assert "default/s2" in _active_uids(sched)

    def test_spread_hint_fn_selector_precision(self):
        # unit level: the PTS pod-event hint queues only for pods matching
        # a spread selector in the same namespace
        from kubernetes_tpu.framework.types import QueueingHint
        from kubernetes_tpu.plugins.podtopologyspread import PodTopologySpread
        zone = "topology.kubernetes.io/zone"
        me = (make_pod("s").label("app", "x")
              .spread_constraint(1, zone, "DoNotSchedule", {"app": "x"}).obj())
        hints = PodTopologySpread().events_to_register()
        pod_hint = next(h for h in hints if h.hint_fn is not None)
        matching = make_pod("m").label("app", "x").obj()
        unrelated = make_pod("u").label("app", "y").obj()
        other_ns = make_pod("o", namespace="kube-system").label("app", "x").obj()
        assert pod_hint.hint_fn(me, matching, None) == QueueingHint.QUEUE
        assert pod_hint.hint_fn(me, unrelated, None) == QueueingHint.SKIP
        assert pod_hint.hint_fn(me, other_ns, None) == QueueingHint.SKIP
