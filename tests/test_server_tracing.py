"""Serving surface (healthz/readyz/metrics, leader election) + tracing."""

import json
import urllib.request

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.server import LeaderElector, SchedulerServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.utils.tracing import Tracer, to_chrome_trace


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestServer:
    def test_endpoints(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        srv = SchedulerServer(sched).start()
        try:
            assert _get(srv.port, "/healthz") == (200, "ok")
            assert _get(srv.port, "/readyz")[0] == 200
            code, body = _get(srv.port, "/metrics")
            assert code == 200
            assert "scheduler_schedule_attempts_total" in body
            code, body = _get(srv.port, "/statusz")
            assert code == 200 and '"scheduled": 1' in body
            assert _get(srv.port, "/nope")[0] == 404
        finally:
            srv.stop()

    def test_readyz_requires_leadership(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        clock = FakeClock()
        el = LeaderElector(api, "sched-a", clock=clock)
        srv = SchedulerServer(sched, elector=el).start()
        try:
            assert _get(srv.port, "/readyz")[0] == 503
            el.tick()
            assert _get(srv.port, "/readyz")[0] == 200
        finally:
            srv.stop()


class TestLeaderElection:
    def test_single_leader_and_failover(self):
        api = APIServer()
        clock = FakeClock()
        a = LeaderElector(api, "a", lease_duration_s=15, clock=clock)
        b = LeaderElector(api, "b", lease_duration_s=15, clock=clock)
        assert a.tick() is True
        assert b.tick() is False          # lease held by a
        clock.t += 10
        assert a.tick() is True           # renew
        clock.t += 10
        assert b.tick() is False          # a renewed 10s ago, not expired
        clock.t += 20                     # a stops renewing → lease expires
        assert b.tick() is True           # b takes over
        assert not a.is_leader() or a.tick() is False

    def test_release_hands_off_immediately(self):
        api = APIServer()
        clock = FakeClock()
        events = []
        a = LeaderElector(api, "a", clock=clock,
                          on_stopped_leading=lambda: events.append("a-stop"))
        b = LeaderElector(api, "b", clock=clock,
                          on_started_leading=lambda: events.append("b-start"))
        a.tick()
        a.release()
        assert events == ["a-stop"]
        assert b.tick() is True
        assert events == ["a-stop", "b-start"]


class TestTracing:
    def test_slow_cycle_capture(self):
        clock = FakeClock()
        slow = []
        tr = Tracer(slow_threshold_s=0.5, clock=clock, on_slow=slow.append)
        with tr.span("scheduling_cycle") as root:
            with tr.span("schedule_batch"):
                clock.t += 0.4
            with tr.span("dispatcher_flush"):
                clock.t += 0.3
        assert len(slow) == 1
        sp = slow[0]
        assert sp.duration_s == 0.7
        assert [c.name for c in sp.children] == ["schedule_batch",
                                                 "dispatcher_flush"]
        assert "schedule_batch: 400.0ms" in sp.breakdown()

    def test_fast_cycles_not_captured(self):
        clock = FakeClock()
        tr = Tracer(slow_threshold_s=0.5, clock=clock)
        with tr.span("scheduling_cycle"):
            clock.t += 0.1
        assert not tr.slow_cycles

    def test_scheduler_wires_spans(self):
        api = APIServer()
        tr = Tracer(slow_threshold_s=0.0)   # capture every cycle
        sched = Scheduler(api, batch_size=64, tracer=tr)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1
        assert tr.slow_cycles
        root = tr.slow_cycles[-1]
        names = [c.name for c in root.children]
        assert "schedule_batch" in names and "dispatcher_flush" in names
        # async commit pipeline: the bind may land after the cycle span
        # closes (wait_pending), so `bound` counts commits inside the cycle
        assert root.attributes.get("pods") == 1
        assert root.attributes.get("bound") in (0, 1)


class TestDebugEndpoints:
    def _scheduled_cluster(self, tracer=None):
        api = APIServer()
        sched = Scheduler(api, batch_size=64, tracer=tracer)
        api.create_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 20}).obj())
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("big").req(
            {"cpu": "100", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        return api, sched

    def test_flightrecorder_and_events_endpoints(self):
        api, sched = self._scheduled_cluster()
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/flightrecorder")
            assert code == 200
            records = json.loads(body)["records"]
            assert records and records[-1]["pods"] == 4
            assert records[-1]["bound"] == 3
            assert records[-1]["failed"] == 1
            assert "host_build" in records[-1]["phases"]

            code, body = _get(srv.port, "/debug/events")
            assert code == 200
            dump = json.loads(body)
            assert dump["counts"]["Normal/Scheduled"] == 3
            assert dump["counts"]["Warning/FailedScheduling"] == 1

            code, body = _get(srv.port,
                              "/debug/events?reason=FailedScheduling&limit=1")
            assert code == 200
            evs = json.loads(body)["events"]
            assert len(evs) == 1
            assert "Insufficient cpu" in evs[0]["message"]
        finally:
            srv.stop()

    def test_cachedump_and_slowcycles_endpoints(self):
        tracer = Tracer(slow_threshold_s=0.0)   # every cycle is "slow"
        api, sched = self._scheduled_cluster(tracer=tracer)
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/cachedump")
            assert code == 200
            dump = json.loads(body)
            assert "cache" in dump and "queue" in dump
            # bound pods show up in the cache dump, the failed one pends
            assert "default/big" in dump["queue"]["pending"]

            code, body = _get(srv.port, "/debug/slowcycles")
            assert code == 200
            payload = json.loads(body)
            assert payload["slowCycles"]
            names = [c["name"] for c in payload["slowCycles"]]
            assert "scheduling_cycle" in names
            assert payload["slowestDrains"]
        finally:
            srv.stop()

    def test_cache_debugger_dump_shape(self):
        api, sched = self._scheduled_cluster()
        dump = sched.debugger.dump()
        assert set(dump) == {"cache", "queue"}
        assert "summary" in dump["queue"]
        assert isinstance(dump["queue"]["pending"], list)

    def test_divergence_counter_on_seeded_mismatch(self):
        api, sched = self._scheduled_cluster()
        sched.wait_pending()
        before = sched.metrics.cache_divergence.value("host_vs_apiserver")
        # seed a mismatch: a node the cache never heard of
        api.nodes["ghost"] = make_node("ghost").capacity(
            {"cpu": 1, "memory": "1Gi", "pods": 5}).obj()
        out = sched.debugger.compare()
        assert any("ghost" in line for line in out)
        after = sched.metrics.cache_divergence.value("host_vs_apiserver")
        assert after >= before + 1


class TestChromeTraceExport:
    def test_host_build_decomposes_into_children(self, tmp_path):
        tracer = Tracer(slow_threshold_s=float("inf"), keep_recent=128)
        api = APIServer()
        sched = Scheduler(api, batch_size=64, tracer=tracer)
        api.create_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 20}).obj())
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 3
        assert tracer.recent
        hb = next(sp for root in tracer.recent
                  for sp in [root.find("host_build")] if sp is not None)
        child_names = {c.name for c in hb.children}
        # the acceptance gate: host_build decomposes into >= 3 phases
        assert len(child_names & {"host_snapshot", "host_tensorize",
                                  "host_group_seed", "host_cache"}) >= 3
        dd = next(sp for root in tracer.recent
                  for sp in [root.find("device_dispatch")] if sp is not None)
        assert dd.attributes["pods"] == 3
        assert "runs" in dd.attributes

        dest = tmp_path / "run.trace.json"
        n = tracer.export_chrome_trace(str(dest))
        trace = json.loads(dest.read_text())   # loadable JSON
        events = trace["traceEvents"]
        assert len(events) == n
        complete = [e for e in events if e["ph"] == "X"]
        assert {"host_build", "device_dispatch"} <= {e["name"]
                                                     for e in complete}
        for e in complete:
            assert e["dur"] >= 0 and "ts" in e

    def test_to_chrome_trace_nests_all_spans(self):
        clock = FakeClock()
        tr = Tracer(slow_threshold_s=float("inf"), clock=clock,
                    keep_recent=4)
        with tr.span("root", pods=2):
            with tr.span("child_a"):
                clock.t += 0.25
            with tr.span("child_b"):
                clock.t += 0.5
        trace = to_chrome_trace(list(tr.recent))
        byname = {e["name"]: e for e in trace["traceEvents"]
                  if e["ph"] == "X"}
        assert byname["root"]["dur"] == 750000.0
        assert byname["child_a"]["dur"] == 250000.0
        assert byname["child_b"]["ts"] == 250000.0
        assert byname["root"]["args"] == {"pods": 2}

    def test_jax_profiler_session_noop_when_unset(self):
        from kubernetes_tpu.utils.tracing import jax_profiler_session
        with jax_profiler_session(""):
            pass
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        assert sched.profiler_trace_dir == ""
        with sched.profile_session():
            pass


class TestExtenders:
    def _cluster(self, extenders):
        from kubernetes_tpu.scheduler import Profile, Scheduler, \
            default_plugins, DEFAULT_WEIGHTS
        from kubernetes_tpu.framework.runtime import Framework
        api = APIServer()
        fwk = Framework("default-scheduler", default_plugins(api),
                        weights=dict(DEFAULT_WEIGHTS))
        prof = Profile(framework=fwk, extenders=tuple(extenders))
        sched = Scheduler(api, profiles=[prof], batch_size=64)
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 50}).obj())
        return api, sched

    def test_extender_filter_vetoes_nodes(self):
        from kubernetes_tpu.framework.extender import CallableExtender

        def only_even(pod, nodes):
            keep = [ni for ni in nodes if int(ni.name[1:]) % 2 == 0]
            failed = {ni.name: "odd node" for ni in nodes
                      if ni not in keep}
            return keep, failed

        api, sched = self._cluster([CallableExtender(
            name="parity", filter_fn=only_even)])
        for i in range(4):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 4
        placed = {api.pods[f"default/p{i}"].spec.node_name
                  for i in range(4)}
        assert placed <= {"n0", "n2"}
        assert sched.host_scheduled == 4   # batching disabled

    def test_extender_prioritize_steers_placement(self):
        from kubernetes_tpu.framework.extender import CallableExtender

        def prefer_n3(pod, nodes):
            return {"n3": 10}

        api, sched = self._cluster([CallableExtender(
            name="steer", prioritize_fn=prefer_n3, weight=1000)])
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/p"].spec.node_name == "n3"

    def test_ignorable_extender_failure_is_skipped(self):
        from kubernetes_tpu.framework.extender import CallableExtender

        def boom(pod, nodes):
            raise RuntimeError("extender down")

        api, sched = self._cluster([CallableExtender(
            name="flaky", filter_fn=boom, ignorable=True)])
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1

    def test_binder_extender_takes_over_bind(self):
        from kubernetes_tpu.framework.extender import CallableExtender
        bound = []

        api_holder = {}
        def ext_bind(pod, node_name):
            bound.append((pod.name, node_name))
            api_holder["api"].bind(pod, node_name)

        api, sched = self._cluster([CallableExtender(
            name="binder", bind_fn=ext_bind)])
        api_holder["api"] = api
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1
        assert bound and bound[0][0] == "p"
        assert api.pods["default/p"].spec.node_name == bound[0][1]

    def test_total_veto_empty_list(self):
        from kubernetes_tpu.framework.extender import CallableExtender

        def veto_all(pod, nodes):
            return [], {ni.name: "vetoed" for ni in nodes}

        api, sched = self._cluster([CallableExtender(
            name="veto", filter_fn=veto_all)])
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 0
        assert api.pods["default/p"].spec.node_name == ""


class TestMainEntry:
    def test_once_demo_run(self, capsys):
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from kubernetes_tpu.__main__ import main
        rc = main(["--port", "0", "--demo", "40", "--once"])
        assert rc == 0
        err = capsys.readouterr().err
        assert "scheduled 40 pods" in err

    def test_once_with_config(self, tmp_path, capsys):
        p = tmp_path / "cfg.yaml"
        p.write_text("batchSize: 32\n")
        from kubernetes_tpu.__main__ import main
        rc = main(["--port", "0", "--config", str(p), "--demo", "10",
                   "--once", "--leader-elect"])
        assert rc == 0
        assert "scheduled 10 pods" in capsys.readouterr().err
