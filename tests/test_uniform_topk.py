"""run_uniform (closed-form top-L batch assignment) ↔ scan parity.

The uniform-run program (ops/program.py run_uniform) claims BIT-EXACT
equality with the sequential scan (run_batch) for same-signature runs
whenever its `ok` flag is true — same assignments, same carry. These tests
verify that claim across empty/preloaded/heterogeneous/saturating clusters
and fuzzed states, verify the flag goes False when an exactness precondition
fails (preferred affinity ⇒ shifting normalization), and verify the
Scheduler-level routing (fast path + fallbacks) keeps oracle parity.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.ops.program import (PodXs, ScoreConfig, initial_carry,
                                        pod_rows_from_batch, run_batch,
                                        run_uniform)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState, pow2_at_least
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _device_state(nodes, pods):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    builder = BatchBuilder(state)
    batch = builder.build(pods)
    assert not batch.host_fallback.any()
    na = state.device_arrays()
    xs, table = pod_rows_from_batch(batch)
    return state, batch, na, xs, table


def _run_both(nodes, pods, cfg=ScoreConfig(), expect_ok=True):
    """Run the scan and the closed form on identical state; compare."""
    state, batch, na, xs, table = _device_state(nodes, pods)
    carry0 = initial_carry(na)
    scan_carry, scan_assign = run_batch(cfg, na, carry0, xs, table)
    scan_assign = np.asarray(scan_assign)[:len(pods)]

    L = pow2_at_least(len(pods))
    K = min(L, na.cap.shape[0])
    xone = PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[0]),
                 tidx=np.int32(batch.tidx[0]))
    uni_carry, packed = run_uniform(
        cfg, na, carry0, xone, table, np.int32(len(pods)), L, K, L + 1)
    packed = np.asarray(packed)
    uni_assign, ok = packed[:L], bool(packed[L] & packed[L + 1])
    assert ok == expect_ok
    if not expect_ok:
        return None
    np.testing.assert_array_equal(np.asarray(uni_assign)[:len(pods)],
                                  scan_assign)
    np.testing.assert_array_equal(np.asarray(uni_carry.used),
                                  np.asarray(scan_carry.used))
    np.testing.assert_array_equal(np.asarray(uni_carry.npods),
                                  np.asarray(scan_carry.npods))
    np.testing.assert_array_equal(np.asarray(uni_carry.nonzero_used),
                                  np.asarray(scan_carry.nonzero_used))
    # cache refresh parity: next-pod evaluation rows must agree so a
    # subsequent batch starting from either carry behaves identically
    np.testing.assert_array_equal(np.asarray(uni_carry.cache.fit_ok),
                                  np.asarray(scan_carry.cache.fit_ok))
    np.testing.assert_array_equal(np.asarray(uni_carry.cache.s_fit),
                                  np.asarray(scan_carry.cache.s_fit))
    np.testing.assert_array_equal(np.asarray(uni_carry.cache.s_bal),
                                  np.asarray(scan_carry.cache.s_bal))
    return scan_assign


def _nodes(count, cpu=8, mem="16Gi"):
    return [make_node(f"n{i}")
            .capacity({"cpu": cpu, "memory": mem, "pods": 110}).obj()
            for i in range(count)]


def _pods(count, cpu="1", mem="2Gi"):
    return [make_pod(f"p{i}").req({"cpu": cpu, "memory": mem}).obj()
            for i in range(count)]


class TestUniformScanParity:
    def test_round_robin_empty_cluster(self):
        # identical nodes: greedy round-robins; closed form must reproduce
        # the exact first-index tie-break sequence
        a = _run_both(_nodes(12), _pods(24))
        assert len(set(a)) == 12  # spread over all nodes

    def test_more_pods_than_capacity(self):
        # 4 nodes × 8 cpu, 2-cpu pods → 16 fit, the rest get -1
        a = _run_both(_nodes(4), _pods(20, cpu="2", mem="1Gi"))
        assert (a >= 0).sum() == 16 and (a[16:] == -1).all()

    def test_heterogeneous_capacities(self):
        nodes = [make_node(f"n{i}")
                 .capacity({"cpu": 2 + 3 * i, "memory": "64Gi", "pods": 110})
                 .obj() for i in range(5)]
        _run_both(nodes, _pods(30, cpu="1", mem="1Gi"))

    def test_preloaded_cluster(self):
        # nodes with existing (bound) pods: carry starts non-empty
        nodes = _nodes(6)
        cache = Cache()
        for n in nodes:
            cache.add_node(n)
        api_pods = [make_pod(f"pre{i}").req({"cpu": str(1 + i % 3),
                                             "memory": "1Gi"})
                    .node(f"n{i % 6}").obj() for i in range(9)]
        for p in api_pods:
            cache.add_pod(p)
        snap = Snapshot()
        cache.update_snapshot(snap)
        state = ClusterState()
        state.apply_snapshot(snap, full=True)
        builder = BatchBuilder(state)
        pods = _pods(20, cpu="1", mem="1Gi")
        batch = builder.build(pods)
        na = state.device_arrays()
        xs, table = pod_rows_from_batch(batch)
        cfg = ScoreConfig()
        carry0 = initial_carry(na)
        _, scan_assign = run_batch(cfg, na, carry0, xs, table)
        L = pow2_at_least(len(pods))
        xone = PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[0]),
                     tidx=np.int32(batch.tidx[0]))
        _, packed = run_uniform(
            cfg, na, carry0, xone, table, np.int32(len(pods)), L,
            min(L, na.cap.shape[0]), L + 1)
        packed = np.asarray(packed)
        assert packed[L] and packed[L + 1]
        np.testing.assert_array_equal(packed[:len(pods)],
                                      np.asarray(scan_assign)[:len(pods)])

    def test_best_effort_pods(self):
        # zero requests: NonZeroRequested defaults drive s_fit; s_bal skipped
        _run_both(_nodes(5), [make_pod(f"p{i}").obj() for i in range(15)])

    def test_n_actual_shorter_than_bucket(self):
        # L pads to 32; only the first 20 entries may assign
        state, batch, na, xs, table = _device_state(_nodes(4), _pods(20))
        cfg = ScoreConfig()
        carry0 = initial_carry(na)
        xone = PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[0]),
                     tidx=np.int32(batch.tidx[0]))
        _, packed = run_uniform(cfg, na, carry0, xone, table,
                                np.int32(20), 32,
                                min(32, na.cap.shape[0]), 33)
        packed = np.asarray(packed)
        assert packed[32] and packed[33]
        a32 = packed[:32]
        assert (a32[20:] == -1).all()
        _, scan_assign = run_batch(cfg, na, carry0, xs, table)
        np.testing.assert_array_equal(a32[:20],
                                      np.asarray(scan_assign)[:20])

    def test_chained_chunks_continue_carry(self):
        # splitting one long run across two run_uniform calls must equal one
        # scan over the whole run (the L_MAX chaining in the scheduler)
        state, batch, na, xs, table = _device_state(_nodes(6), _pods(24))
        cfg = ScoreConfig()
        carry = initial_carry(na)
        xone = PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[0]),
                     tidx=np.int32(batch.tidx[0]))
        out = []
        for lo, hi in ((0, 16), (16, 24)):
            chunk = hi - lo
            L = pow2_at_least(chunk)
            carry, packed = run_uniform(cfg, na, carry, xone, table,
                                        np.int32(chunk), L,
                                        min(L, na.cap.shape[0]), L + 1)
            packed = np.asarray(packed)
            assert packed[L] and packed[L + 1]
            out.extend(packed[:chunk])
        _, scan_assign = run_batch(cfg, na, initial_carry(na), xs, table)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(scan_assign)[:24])

    def test_preferred_affinity_fails_closed(self):
        # nonzero preferred-affinity raw counts ⇒ normalization can shift as
        # nodes saturate ⇒ ok must be False (scheduler host-gates this too)
        nodes = [make_node(f"n{i}").capacity({"cpu": 4, "memory": "8Gi",
                                              "pods": 110})
                 .label("tier", "gold" if i % 2 else "silver").obj()
                 for i in range(4)]
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .preferred_node_affinity_in("tier", ["gold"], 5).obj()
                for i in range(8)]
        _run_both(nodes, pods, expect_ok=False)

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_vs_scan(self, seed):
        """Random preloaded clusters + identical pods: whenever ok, the
        closed form must equal the scan bit-exactly; ok=False is allowed
        (the scheduler falls back) but must be rare enough to matter — we
        only require agreement, not ok."""
        rng = random.Random(seed)
        n_nodes = rng.randint(2, 24)
        nodes = [make_node(f"n{i}").capacity(
            {"cpu": rng.randint(2, 32),
             "memory": f"{rng.randint(4, 64)}Gi",
             "pods": rng.randint(3, 20)}).obj() for i in range(n_nodes)]
        cache = Cache()
        for n in nodes:
            cache.add_node(n)
        for i in range(rng.randint(0, 3 * n_nodes)):
            cache.add_pod(make_pod(f"pre{i}").req(
                {"cpu": str(rng.randint(0, 3)),
                 "memory": f"{rng.randint(0, 4)}Gi"})
                .node(f"n{rng.randrange(n_nodes)}").obj())
        snap = Snapshot()
        cache.update_snapshot(snap)
        state = ClusterState()
        state.apply_snapshot(snap, full=True)
        builder = BatchBuilder(state)
        cpu, mem = rng.randint(0, 4), rng.randint(0, 4)
        pods = [make_pod(f"p{i}").req({"cpu": str(cpu), "memory": f"{mem}Gi"})
                .obj() for i in range(rng.randint(16, 48))]
        batch = builder.build(pods)
        na = state.device_arrays()
        xs, table = pod_rows_from_batch(batch)
        cfg = ScoreConfig()
        carry0 = initial_carry(na)
        _, scan_assign = run_batch(cfg, na, carry0, xs, table)
        L = pow2_at_least(len(pods))
        xone = PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[0]),
                     tidx=np.int32(batch.tidx[0]))
        _, packed = run_uniform(
            cfg, na, carry0, xone, table, np.int32(len(pods)), L,
            min(L, na.cap.shape[0]), L + 1)
        packed = np.asarray(packed)
        if packed[L] and packed[L + 1]:
            np.testing.assert_array_equal(
                packed[:len(pods)],
                np.asarray(scan_assign)[:len(pods)])


class TestSchedulerFastPath:
    def _bound_map(self, api):
        return {p.name: p.spec.node_name for p in api.pods.values()
                if p.spec.node_name}

    def test_fast_path_matches_scan_path(self):
        """Same workload through a fast-path scheduler and one with the
        uniform path disabled (RUN_MIN > batch) must bind identically."""
        results = []
        for run_min in (16, 10 ** 9):
            api = APIServer()
            sched = Scheduler(api, batch_size=64)
            for i in range(10):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 16, "memory": "32Gi", "pods": 110}).obj())
            sched.UNIFORM_RUN_MIN = run_min
            for i in range(40):
                api.create_pod(make_pod(f"p{i}").req(
                    {"cpu": "1", "memory": "1Gi"}).obj())
            bound = sched.schedule_pending()
            assert bound == 40
            assert sched.reconcile() == []
            results.append(self._bound_map(api))
        assert results[0] == results[1]

    def test_mixed_signatures_route_correctly(self):
        """Interleaved signatures: long uniform runs use the closed form,
        the stretch in between scans; binds must match the all-scan run."""
        def workload(api):
            for i in range(20):
                api.create_pod(make_pod(f"a{i}").req(
                    {"cpu": "1", "memory": "1Gi"}).obj())
            for i in range(5):  # short runs → scan stretch
                api.create_pod(make_pod(f"b{i}").req(
                    {"cpu": str(1 + i % 2), "memory": "2Gi"}).obj())
            for i in range(20):
                api.create_pod(make_pod(f"c{i}").req(
                    {"cpu": "2", "memory": "1Gi"}).obj())
        results = []
        for run_min in (16, 10 ** 9):
            api = APIServer()
            sched = Scheduler(api, batch_size=64)
            for i in range(8):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 20, "memory": "40Gi", "pods": 110}).obj())
            sched.UNIFORM_RUN_MIN = run_min
            workload(api)
            assert sched.schedule_pending() == 45
            assert sched.reconcile() == []
            results.append(self._bound_map(api))
        assert results[0] == results[1]

    def test_prefer_no_schedule_taints_gate_to_scan(self):
        """PreferNoSchedule taints in the cluster must route to the scan
        (normalization shifts); decisions still match the scan-only run."""
        def cluster(api):
            for i in range(6):
                n = make_node(f"n{i}").capacity(
                    {"cpu": 8, "memory": "16Gi", "pods": 110})
                if i < 2:
                    n = n.taint("burst", "true", "PreferNoSchedule")
                api.create_node(n.obj())
        results = []
        for run_min in (16, 10 ** 9):
            api = APIServer()
            sched = Scheduler(api, batch_size=64)
            cluster(api)
            sched.UNIFORM_RUN_MIN = run_min
            for i in range(24):
                api.create_pod(make_pod(f"p{i}").req(
                    {"cpu": "1", "memory": "1Gi"}).obj())
            assert sched.schedule_pending() == 24
            results.append(self._bound_map(api))
        assert results[0] == results[1]
        # the untainted nodes must win while they have room
        tainted = {f"n{i}" for i in range(2)}
        first_16 = [results[0][f"p{i}"] for i in range(16)]
        assert not tainted & set(first_16)


class TestDepthEscalation:
    def test_shallow_depth_fails_closed(self):
        # 2 nodes × plenty of room, 32 pods → 16 per node > J=8 entries:
        # depth flag must fire; J=L+1 must succeed and match the scan
        state, batch, na, xs, table = _device_state(
            _nodes(2, cpu=64, mem="128Gi"), _pods(32, cpu="1", mem="1Gi"))
        cfg = ScoreConfig()
        carry0 = initial_carry(na)
        xone = PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[0]),
                     tidx=np.int32(batch.tidx[0]))
        _, packed = run_uniform(cfg, na, carry0, xone, table,
                                np.int32(32), 32, 8, 8)
        packed = np.asarray(packed)
        assert packed[32] and not packed[33]
        _, packed = run_uniform(cfg, na, carry0, xone, table,
                                np.int32(32), 32, 8, 33)
        packed = np.asarray(packed)
        assert packed[32] and packed[33]
        _, scan_assign = run_batch(cfg, na, carry0, xs, table)
        np.testing.assert_array_equal(packed[:32],
                                      np.asarray(scan_assign)[:32])

    def test_scheduler_escalates_depth(self):
        # few nodes, many pods: j0 starts deep enough or the ladder climbs —
        # either way binds must match the scan-only scheduler
        results = []
        for run_min in (16, 10 ** 9):
            api = APIServer()
            sched = Scheduler(api, batch_size=256)
            sched.UNIFORM_RUN_MIN = run_min
            for i in range(3):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 100, "memory": "200Gi", "pods": 300}).obj())
            for i in range(200):
                api.create_pod(make_pod(f"p{i}").req(
                    {"cpu": "1", "memory": "1Gi"}).obj())
            assert sched.schedule_pending() == 200
            assert sched.reconcile() == []
            results.append({p.name: p.spec.node_name
                            for p in api.pods.values() if p.spec.node_name})
        assert results[0] == results[1]
