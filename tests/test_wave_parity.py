"""Speculative wave placement ↔ serial-order parity (ISSUE 3 standing gate).

The wave kernels (ops/program.py run_wave / run_wave_scan) must produce
assignments BIT-IDENTICAL to the sequential greedy in every scenario —
the merge tier's conflict detection, the exact minimum-level replay, the
domain-veto champion selection, and the in-dispatch serial repair are all
exactness-critical. The fuzz feeds both wave kernels and the oracle-
verified device scan (run_batch, itself fuzzed against the transliterated
Go-semantics host oracle in tests/test_groups_parity.py) the same seeded
clusters; a smaller direct sweep closes the triangle against the host
oracle framework itself, and scheduler-level tests pin the whole wiring
(gate on ≡ gate off, including the async commit pipeline).

Scenario families: spread (DoNotSchedule / ScheduleAnyway / hostname),
required pod anti-affinity (unique and shared domains, existing pods),
required affinity, mixed interleaved signatures, tainted clusters
(PreferNoSchedule → the norm_live kernel variant), capacity-exhausted
tails, and the worst-case all-conflict wave that must degenerate to the
serial scan without error.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.ops.groups import to_device
from kubernetes_tpu.ops.hostgreedy import static_norm_ok
from kubernetes_tpu.ops.program import (ScoreConfig, WaveXs, initial_carry,
                                        pod_rows_from_batch, run_batch,
                                        run_wave, run_wave_scan,
                                        wave_statics)
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState, pow2_at_least
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


def _setup(nodes, existing):
    cache = Cache()
    for nd in nodes:
        cache.add_node(nd)
    for pod, node_name in existing:
        pod.spec.node_name = node_name
        cache.add_pod(pod)
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    return state, snap


def _statics_for(na, table, rows):
    wt = (list(rows) + [rows[-1]] * 4)[:max(
        1 if len(rows) == 1 else (2 if len(rows) == 2 else 4), len(rows))]
    out = wave_statics(na, table, jnp.asarray(np.array(wt, np.int32)))
    return [tuple(f[k] for f in out) for k in range(len(rows))]


def _anti_term_of(mgr, u):
    terms = [t for t in range(mgr.m_ipa_aa.shape[2])
             if mgr.m_ipa_aa[u, u, t] or mgr.m_ipa_exist[u, u, t]]
    return terms[0] if len(terms) == 1 else -1


def wave_vs_scan(nodes, existing, pods, cfg=ScoreConfig(), merge_on=True):
    """Assert the wave kernels reproduce run_batch's assignments exactly;
    returns (assignments, stats dict)."""
    state, snap = _setup(nodes, existing)
    builder = BatchBuilder(state)
    batch = builder.build(pods)
    assert not batch.host_fallback.any(), "fuzz pods must be tensorizable"
    gd_np, gc_np = builder.groups.build_dev(snap)
    gd, gc = to_device(gd_np), to_device(gc_np)
    na = state.device_arrays()
    xs, table = pod_rows_from_batch(batch)
    fam = builder.groups.families(snap)
    n = len(pods)

    _, scan_out = run_batch(cfg, na, initial_carry(na, gc), xs, table,
                            groups=gd, fam=fam)
    scan_out = np.asarray(scan_out)[:n]

    uniq = list(dict.fromkeys(int(t) for t in batch.tidx[:n]))
    norm_live = not all(
        static_norm_ok(state.ensure_arrays(), builder.table.pref_weight[u])
        for u in uniq)
    stats = {}
    if len(uniq) == 1:
        u = uniq[0]
        B = pow2_at_least(n)
        valid = np.zeros((B,), bool)
        valid[:n] = True
        statics = _statics_for(na, table, [u])[0]
        K = min(B, na.cap.shape[0])
        _, packed = run_wave(
            cfg, na, initial_carry(na, gc), jnp.asarray(valid), table,
            jnp.int32(u), gd, statics, K, 8, fam, norm_live,
            anti_term=_anti_term_of(builder.groups, u), merge_on=merge_on,
            Lw=min(512, B))
        packed = np.asarray(packed)
        wave_out = packed[:n]
        stats = dict(waves=int(packed[B]), confs=int(packed[B + 1]),
                     prefix=int(packed[B + 2]), serial=int(packed[B + 3]))
        assert (wave_out == scan_out).all(), (
            "run_wave diverged", scan_out.tolist(), wave_out.tolist(), stats)
    # the multi-signature kernel must match too (also for 1 signature);
    # > 4 distinct signatures routes to the plain scan in production —
    # nothing to verify here
    if len(uniq) > 4:
        return scan_out, stats
    B = pow2_at_least(n)
    S = 2 if len(uniq) <= 2 else 4
    wt_list = (uniq + [uniq[-1]] * S)[:S]
    slot = {}
    for s, u in enumerate(wt_list):
        slot.setdefault(u, s)
    widx = np.zeros((B,), np.int32)
    for k in range(n):
        widx[k] = slot[int(batch.tidx[k])]
    widx[n:] = widx[n - 1]
    valid = np.zeros((B,), bool)
    valid[:n] = True
    st_list = _statics_for(na, table, wt_list)
    statics = tuple(jnp.stack([s[f] for s in st_list]) for f in range(4))
    wxs = WaveXs(valid=jnp.asarray(valid), widx=jnp.asarray(widx))
    _, packed2 = run_wave_scan(
        cfg, na, initial_carry(na, gc), wxs, table,
        jnp.asarray(np.array(wt_list, np.int32)), gd, statics, fam,
        norm_live)
    ws_out = np.asarray(packed2)[:n]
    assert (ws_out == scan_out).all(), (
        "run_wave_scan diverged", scan_out.tolist(), ws_out.tolist())
    return scan_out, stats


def _nodes(n, zones, cpu=16, taints=(), unique_zone=False):
    out = []
    for i in range(n):
        b = (make_node(f"n{i}")
             .capacity({"cpu": cpu, "memory": "32Gi", "pods": 40})
             .zone(f"z{i if unique_zone else i % zones}")
             .label(HOSTNAME, f"n{i}"))
        for (key, val, eff) in taints:
            b = b.taint(key, val, eff)
        out.append(b.obj())
    return out


class TestWaveFamilies:
    def test_spread_tight_skew(self):
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "a"})
                .obj() for i in range(14)]
        out, stats = wave_vs_scan(_nodes(9, 3), [], pods)
        assert (out >= 0).all()
        # tight skew forces conflicts: the serial tier must engage
        assert stats["serial"] > 0 or stats["confs"] > 0

    def test_spread_slack_skew_single_wave(self):
        pods = [make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"})
                .label("app", "a")
                .spread_constraint(5, ZONE, "DoNotSchedule", {"app": "a"})
                .obj() for i in range(24)]
        out, stats = wave_vs_scan(_nodes(12, 4, cpu=64), [], pods)
        assert (out >= 0).all()
        # balanced fill under slack: the exact min-level replay must
        # accept the whole span without conflicts (zero-conflict extreme)
        assert stats == {} or (stats["confs"] == 0 and stats["serial"] == 0)

    def test_spread_hostname_key(self):
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "h")
                .spread_constraint(2, HOSTNAME, "DoNotSchedule", {"app": "h"})
                .obj() for i in range(16)]
        wave_vs_scan(_nodes(8, 4), [], pods)

    def test_spread_schedule_anyway_routes_wavescan(self):
        # ScheduleAnyway rows are outside the same-signature kernel's
        # maintained state — the multi-signature kernel must cover them
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "s")
                .spread_constraint(2, ZONE, "ScheduleAnyway", {"app": "s"})
                .obj() for i in range(12)]
        wave_vs_scan(_nodes(9, 3), [], pods, merge_on=False)

    def test_anti_affinity_unique_domains(self):
        pods = [make_pod(f"q{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("anti", "y")
                .pod_affinity(ZONE, {"anti": "y"}, anti=True)
                .obj() for i in range(10)]
        out, stats = wave_vs_scan(_nodes(12, 12, unique_zone=True), [], pods)
        assert (out >= 0).all()
        assert stats["confs"] == 0 and stats["serial"] == 0

    def test_anti_affinity_shared_domains_with_existing(self):
        ex = [(make_pod(f"e{i}").req({"cpu": "1", "memory": "1Gi"})
               .label("anti", "y")
               .pod_affinity(ZONE, {"anti": "y"}, anti=True).obj(),
               f"n{i}") for i in range(2)]
        pods = [make_pod(f"q{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("anti", "y")
                .pod_affinity(ZONE, {"anti": "y"}, anti=True)
                .obj() for i in range(10)]
        wave_vs_scan(_nodes(12, 6), ex, pods)

    def test_affinity_routes_to_wavescan(self):
        # self-matching required affinity: the same-signature kernel's
        # merge/serial state can't carry it; run_wave_scan must be exact
        ex = [(make_pod("seed").req({"cpu": "1", "memory": "1Gi"})
               .label("app", "aff").obj(), "n0")]
        pods = [make_pod(f"q{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "aff")
                .pod_affinity(ZONE, {"app": "aff"})
                .obj() for i in range(8)]
        state, snap = _setup(_nodes(6, 3), ex)
        builder = BatchBuilder(state)
        batch = builder.build(pods)
        assert not batch.host_fallback.any()
        gd_np, gc_np = builder.groups.build_dev(snap)
        gd, gc = to_device(gd_np), to_device(gc_np)
        na = state.device_arrays()
        xs, table = pod_rows_from_batch(batch)
        fam = builder.groups.families(snap)
        _, scan_out = run_batch(ScoreConfig(), na, initial_carry(na, gc),
                                xs, table, groups=gd, fam=fam)
        scan_out = np.asarray(scan_out)[:8]
        u = int(batch.tidx[0])
        B = pow2_at_least(8)
        valid = np.zeros((B,), bool)
        valid[:8] = True
        st_list = _statics_for(na, table, [u, u])
        statics = tuple(jnp.stack([s[f] for s in st_list]) for f in range(4))
        wxs = WaveXs(valid=jnp.asarray(valid),
                     widx=jnp.asarray(np.zeros((B,), np.int32)))
        _, packed = run_wave_scan(
            ScoreConfig(), na, initial_carry(na, gc), wxs, table,
            jnp.asarray(np.array([u, u], np.int32)), gd, statics, fam,
            False)
        assert (np.asarray(packed)[:8] == scan_out).all()

    def test_prefer_no_schedule_taints_norm_live(self):
        # PreferNoSchedule taints make the taint normalization shift as
        # nodes saturate: the norm_live kernel variant must stay exact
        nodes = _nodes(8, 4, taints=[("dedic", "x", "PreferNoSchedule")])
        nodes += _nodes(4, 4)[0:0]  # keep list type
        for i in range(4, 8):
            nodes[i].spec.taints = []
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "t")
                .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "t"})
                .obj() for i in range(12)]
        wave_vs_scan(nodes, [], pods)

    def test_capacity_exhausted_tail(self):
        pods = [make_pod(f"t{i}").req({"cpu": "7", "memory": "1Gi"})
                .label("app", "b")
                .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "b"})
                .obj() for i in range(12)]
        out, _ = wave_vs_scan(_nodes(3, 3, cpu=8), [], pods)
        assert (out[-4:] == -1).all()

    def test_all_conflict_wave_degenerates_to_serial(self):
        # worst case: skew 1 over 2 zones with alternating capacity — every
        # placement moves the mask, the merge tier can't hold a prefix, and
        # the whole span must fall through to the in-dispatch serial scan
        # WITHOUT error and with exact results
        nodes = _nodes(4, 2, cpu=6)
        pods = [make_pod(f"c{i}").req({"cpu": "2", "memory": "1Gi"})
                .label("app", "c")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "c"})
                .obj() for i in range(10)]
        out, stats = wave_vs_scan(nodes, [], pods)
        assert stats["serial"] + stats["prefix"] + stats["confs"] > 0

    def test_mixed_signatures_interleaved(self):
        a = [make_pod(f"a{i}").req({"cpu": "1", "memory": "1Gi"})
             .label("app", "a")
             .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "a"})
             .obj() for i in range(6)]
        b = [make_pod(f"b{i}").req({"cpu": "500m", "memory": "512Mi"})
             .label("anti", "y")
             .pod_affinity(HOSTNAME, {"anti": "y"}, anti=True)
             .obj() for i in range(6)]
        inter = [p for pair in zip(a, b) for p in pair]
        wave_vs_scan(_nodes(8, 4), [], inter)


def _fuzz_scenario(rng: random.Random, idx: int):
    """One seeded scenario: (nodes, existing, pods)."""
    zones = rng.choice([2, 3, 4])
    n_nodes = rng.choice([6, 9, 12])
    cpu = rng.choice([8, 16, 24])
    taints = ([("d", "x", "PreferNoSchedule")] if rng.random() < 0.2 else [])
    nodes = _nodes(n_nodes, zones, cpu=cpu, taints=taints)
    if taints:
        # only a subset tainted: normalization varies across nodes
        for nd in nodes[n_nodes // 2:]:
            nd.spec.taints = []

    kind = idx % 5
    n_pods = rng.randint(8, 24)
    existing = []
    if rng.random() < 0.4:
        existing = [(make_pod(f"e{idx}_{k}")
                     .req({"cpu": "1", "memory": "1Gi"})
                     .label("app", "f").obj(), f"n{k % n_nodes}")
                    for k in range(rng.randint(1, 4))]

    def spread(i, skew, action, key=ZONE, label="f"):
        return (make_pod(f"f{idx}_{i}")
                .req({"cpu": f"{rng.choice([250, 500, 1000])}m",
                      "memory": "512Mi"})
                .label("app", label)
                .spread_constraint(skew, key, action, {"app": label}).obj())

    def anti(i, key=ZONE, label="v"):
        return (make_pod(f"g{idx}_{i}")
                .req({"cpu": "500m", "memory": "512Mi"})
                .label("anti", label)
                .pod_affinity(key, {"anti": label}, anti=True).obj())

    if kind == 0:
        skew = rng.choice([1, 2, 5])
        pods = [spread(i, skew, "DoNotSchedule") for i in range(n_pods)]
    elif kind == 1:
        pods = [anti(i, key=rng.choice([ZONE, HOSTNAME]))
                for i in range(n_pods)]
    elif kind == 2:
        skew = rng.choice([1, 3])
        pods = [spread(i, skew, "ScheduleAnyway") for i in range(n_pods)]
    elif kind == 3:
        a = [spread(i, rng.choice([1, 2]), "DoNotSchedule", label="m1")
             for i in range(n_pods // 2)]
        b = [anti(i, label="m2") for i in range(n_pods - n_pods // 2)]
        pods = [p for pair in zip(a, b) for p in pair]
        pods += a[len(b):] + b[len(a):]
    else:
        # spread + anti on the SAME signature
        pods = [(make_pod(f"h{idx}_{i}")
                 .req({"cpu": "500m", "memory": "512Mi"})
                 .label("app", "sa")
                 .spread_constraint(rng.choice([2, 4]), ZONE,
                                    "DoNotSchedule", {"app": "sa"})
                 .pod_affinity(HOSTNAME, {"app": "sa"}, anti=True).obj())
                for i in range(n_pods)]
    return nodes, existing, pods


@pytest.mark.parametrize("block", range(8))
def test_wave_fuzz(block):
    """The standing fuzz gate: ≥200 seeded scenarios, wave ≡ serial scan
    (which is itself oracle-verified), across every constraint family,
    mixed signatures, taints, existing pods and capacity pressure."""
    rng = random.Random(1000 + block)
    for k in range(26):
        idx = block * 26 + k
        nodes, existing, pods = _fuzz_scenario(rng, idx)
        wave_vs_scan(nodes, existing, pods)


def test_wave_vs_host_oracle_direct():
    """Close the triangle: the wave kernel against the actual host oracle
    (framework runtime), not just the scan, on an evolving cluster."""
    from kubernetes_tpu.framework.interface import CycleState
    from kubernetes_tpu.framework.runtime import schedule_pod
    from kubernetes_tpu.framework.types import FitError
    from tests.test_groups_parity import full_framework

    nodes = _nodes(9, 3)
    pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            .label("app", "o")
            .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "o"})
            .obj() for i in range(15)]
    out, _ = wave_vs_scan(nodes, [], pods)

    cache = Cache()
    for nd in nodes:
        cache.add_node(nd)
    fwk = full_framework()
    snap = Snapshot()
    for i, pod in enumerate(pods):
        cache.update_snapshot(snap)
        try:
            result = schedule_pod(fwk, CycleState(), pod,
                                  snap.node_info_list)
            chosen = result.suggested_host
        except FitError:
            chosen = None
        if out[i] < 0:
            assert chosen is None, (i, chosen)
        else:
            assert chosen == f"n{out[i]}", (i, chosen, out[i])
            bound = pod.with_node_name(chosen)
            cache.add_pod(bound)


class TestSchedulerWave:
    def _run(self, gate_on, seed):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler

        rng = random.Random(seed)
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        sched.feature_gates.set("SpeculativeWavePlacement", gate_on)
        sched.wave_min_span = 4
        for i in range(24):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 16, "memory": "32Gi",
                                       "pods": 40})
                            .zone(f"z{i % 4}").label(HOSTNAME, f"n{i}").obj())
        sched.prime()
        for i in range(72):
            k = i % 3
            if k == 0:
                p = (make_pod(f"s{i}")
                     .req({"cpu": "500m", "memory": "512Mi"})
                     .label("app", "sp")
                     .spread_constraint(rng.choice([1, 3]), ZONE,
                                        "DoNotSchedule", {"app": "sp"})
                     .obj())
            elif k == 1:
                p = (make_pod(f"a{i}")
                     .req({"cpu": "500m", "memory": "512Mi"})
                     .label("anti", "y")
                     .pod_affinity(HOSTNAME, {"anti": "y"}, anti=True).obj())
            else:
                p = (make_pod(f"p{i}")
                     .req({"cpu": "250m", "memory": "256Mi"}).obj())
            api.create_pod(p)
            if i % 24 == 23:
                sched.schedule_pending(wait=False)
        sched.schedule_pending()
        return ({p.metadata.name: p.spec.node_name
                 for p in api.pods.values()}, sched)

    def test_scheduler_gate_parity(self):
        on, s_on = self._run(True, seed=3)
        off, s_off = self._run(False, seed=3)
        assert on == off
        # the wave path must actually engage (not silently fall back)
        assert s_on.metrics.wave_placement_waves.value() > 0
        assert s_off.metrics.wave_placement_waves.value() == 0

    def test_same_sig_wave_engages_merge(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler

        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        sched.wave_min_span = 4
        for i in range(12):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 32, "memory": "64Gi",
                                       "pods": 80})
                            .zone(f"z{i % 4}").label(HOSTNAME, f"n{i}").obj())
        sched.prime()
        for i in range(32):
            api.create_pod(make_pod(f"p{i}")
                           .req({"cpu": "500m", "memory": "512Mi"})
                           .label("app", "w")
                           .spread_constraint(5, ZONE, "DoNotSchedule",
                                              {"app": "w"}).obj())
        assert sched.schedule_pending() == 32
        m = sched.metrics
        assert m.wave_placement_waves.value() > 0
        assert m.wave_accepted_prefix.count() > 0
        assert m.drain_phase.count("device") > 0
        assert sched.host_greedy_runs == 0
        # resident carry: the device bookkeeping must match the host cache
        assert sched.reconcile() == []

    def test_wave_respects_min_span(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler

        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        assert sched.wave_min_span > 8
        for i in range(6):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 16, "memory": "32Gi",
                                       "pods": 40})
                            .zone(f"z{i % 3}").label(HOSTNAME, f"n{i}").obj())
        sched.prime()
        for i in range(8):   # below wave_min_span
            api.create_pod(make_pod(f"p{i}")
                           .req({"cpu": "500m", "memory": "512Mi"})
                           .label("app", "w")
                           .spread_constraint(1, ZONE, "DoNotSchedule",
                                              {"app": "w"}).obj())
        assert sched.schedule_pending() == 8
        assert sched.metrics.wave_placement_waves.value() == 0


class TestDonationAndCompileCount:
    def test_run_batch_no_retrace(self):
        """Buffer-donation satellite: repeated dispatches with identical
        shapes must reuse ONE compiled executable (no re-tracing), and the
        CPU backend must select the non-donating variant (donation is
        unimplemented there and would warn every dispatch)."""
        import jax

        from kubernetes_tpu.ops.program import (_run_batch_fn,
                                                _run_wave_same_fn)

        nodes = _nodes(6, 3)
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "d")
                .spread_constraint(3, ZONE, "DoNotSchedule", {"app": "d"})
                .obj() for i in range(8)]
        state, snap = _setup(nodes, [])
        builder = BatchBuilder(state)
        batch = builder.build(pods)
        gd_np, gc_np = builder.groups.build_dev(snap)
        gd, gc = to_device(gd_np), to_device(gc_np)
        na = state.device_arrays()
        xs, table = pod_rows_from_batch(batch)
        fam = builder.groups.families(snap)

        donate = jax.default_backend() != "cpu"
        fn = _run_batch_fn(donate)
        base = fn._cache_size()
        cfg = ScoreConfig()
        for _ in range(3):
            carry = initial_carry(na, gc)
            _, out = run_batch(cfg, na, carry, xs, table, groups=gd,
                               fam=fam)
            np.asarray(out)
        after = fn._cache_size()
        assert after - base <= 1, (base, after)
        # a second round with the SAME shapes must not add cache entries
        carry = initial_carry(na, gc)
        _, out = run_batch(cfg, na, carry, xs, table, groups=gd, fam=fam)
        np.asarray(out)
        assert fn._cache_size() == after
        # same contract for the wave kernel
        wfn = _run_wave_same_fn(donate)
        wbase = wfn._cache_size()
        u = int(batch.tidx[0])
        B = pow2_at_least(len(pods))
        valid = np.zeros((B,), bool)
        valid[:len(pods)] = True
        statics = _statics_for(na, table, [u])[0]
        for _ in range(2):
            carry = initial_carry(na, gc)
            _, packed = run_wave(cfg, na, carry, jnp.asarray(valid), table,
                                 jnp.int32(u), gd, statics,
                                 min(B, na.cap.shape[0]), 8, fam, False,
                                 anti_term=-1, merge_on=True, Lw=B)
            np.asarray(packed)
        assert wfn._cache_size() - wbase <= 1
