"""APIDispatcher unit coverage: retry/backoff classification, flush
ordering (victim DELETEs before binds), the DELETE-outranks-bind leak fix,
is_delete_pending, and the STATUS_PATCH merge path."""

import pytest

from kubernetes_tpu.backend.apiserver import (APIServer, Conflict, NotFound,
                                              ServerTimeout, TooManyRequests,
                                              is_retriable)
from kubernetes_tpu.backend.dispatcher import (APICall, APIDispatcher,
                                               CallType)
from kubernetes_tpu.metrics import SchedulerMetrics
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class ScriptedClient:
    """Records call order; raises scripted errors (a list per op key,
    consumed one per call)."""

    def __init__(self):
        self.calls = []
        self.fail = {}   # key -> list of exceptions to raise, in order

    def _maybe_fail(self, key):
        errs = self.fail.get(key)
        if errs:
            raise errs.pop(0)

    def bind(self, pod, node_name):
        self.calls.append(("bind", pod.uid, node_name))
        self._maybe_fail("bind")

    def delete_pod(self, uid):
        self.calls.append(("delete", uid))
        self._maybe_fail("delete")

    def patch_pod_status(self, pod, condition, nominated_node_name=None):
        self.calls.append(("patch", pod.uid, nominated_node_name))
        self._maybe_fail("patch")


class BulkClient(ScriptedClient):
    def __init__(self):
        super().__init__()
        self.bind_all_failures = []   # one list per bind_all invocation

    def bind_all(self, pairs):
        self.calls.append(("bind_all", tuple(p.uid for p, _ in pairs)))
        if self.bind_all_failures:
            wanted = self.bind_all_failures.pop(0)
            return [(p, e) for p, _o in pairs for uid, e in wanted
                    if p.uid == uid]
        return []


def _dispatcher(client, **kw):
    kw.setdefault("sleep", lambda _s: None)
    return APIDispatcher(client=client, **kw)


def _pod(name, node=""):
    w = make_pod(name)
    if node:
        w = w.node(node)
    return w.obj()


def test_retriable_classification():
    assert is_retriable(ServerTimeout("x"))
    assert is_retriable(TooManyRequests("x"))
    assert not is_retriable(Conflict("x"))
    assert not is_retriable(NotFound("x"))
    assert not is_retriable(RuntimeError("x"))


def test_transient_bind_retries_until_success():
    c = ScriptedClient()
    c.fail["bind"] = [ServerTimeout("t"), TooManyRequests("t")]
    errors = []
    d = _dispatcher(c, on_bind_error=lambda p, n, e: errors.append(e))
    d.metrics = SchedulerMetrics()
    d.add(APICall(CallType.BIND, _pod("a"), node_name="n1"))
    d.flush()
    assert [k for k, *_ in c.calls] == ["bind", "bind", "bind"]
    assert errors == []
    assert d.retries == 2 and d.errors == 0 and d.executed == 1
    assert d.metrics.api_retries.value(CallType.BIND.value) == 2


def test_retry_budget_exhaustion_routes_bind_error():
    c = ScriptedClient()
    c.fail["bind"] = [ServerTimeout("t")] * 10
    errors = []
    d = _dispatcher(c, on_bind_error=lambda p, n, e: errors.append(e),
                    retry_max_attempts=3)
    d.add(APICall(CallType.BIND, _pod("a"), node_name="n1"))
    d.flush()
    assert len([k for k, *_ in c.calls if k == "bind"]) == 3
    assert len(errors) == 1 and isinstance(errors[0], ServerTimeout)
    assert d.errors == 1


def test_terminal_conflict_not_retried():
    c = ScriptedClient()
    c.fail["bind"] = [Conflict("taken")]
    errors = []
    d = _dispatcher(c, on_bind_error=lambda p, n, e: errors.append(e))
    d.add(APICall(CallType.BIND, _pod("a"), node_name="n1"))
    d.flush()
    assert len([k for k, *_ in c.calls if k == "bind"]) == 1
    assert len(errors) == 1 and d.retries == 0


def test_delete_retries_too():
    """A victim DELETE must survive transient errors — otherwise a
    preemptor wave half-commits."""
    c = ScriptedClient()
    c.fail["delete"] = [ServerTimeout("t")]
    d = _dispatcher(c)
    d.add(APICall(CallType.DELETE, _pod("victim")))
    d.flush()
    assert [k for k, *_ in c.calls] == ["delete", "delete"]
    assert d.errors == 0 and d.executed == 1


def test_backoff_grows_exponentially_with_jitter():
    delays = []
    c = ScriptedClient()
    c.fail["bind"] = [ServerTimeout("t")] * 4
    d = APIDispatcher(client=c, sleep=delays.append,
                      retry_max_attempts=5, retry_base_seconds=0.1,
                      retry_max_delay_seconds=100.0)
    d.add(APICall(CallType.BIND, _pod("a"), node_name="n1"))
    d.flush()
    assert len(delays) == 4
    for i, dt in enumerate(delays):
        base = 0.1 * 2 ** i
        assert base * 0.5 <= dt <= base   # equal jitter band


def test_bulk_bind_retries_only_retriable_subset():
    c = BulkClient()
    pods = [_pod(f"p{i}", "n1") for i in range(3)]
    # first bind_all: p0 transient, p1 terminal conflict; retry round clean
    c.bind_all_failures = [[(pods[0].uid, ServerTimeout("t")),
                            (pods[1].uid, Conflict("taken"))]]
    errors = []
    d = _dispatcher(c, on_bind_error=lambda p, n, e: errors.append((p.uid, e)))
    d.add_binds([(p, p) for p in pods])
    d.flush()
    bulk = [args for k, args in c.calls if k == "bind_all"]
    assert bulk[0] == (pods[0].uid, pods[1].uid, pods[2].uid)
    assert bulk[1] == (pods[0].uid,)          # only the transient retried
    assert [uid for uid, _ in errors] == [pods[1].uid]
    assert d.retries == 1 and d.errors == 1 and d.executed == 2


def test_flush_executes_deletes_before_bulk_binds():
    """A preemptor wave's victims must leave the store before the
    preemptors bind (reference relevance ordering end to end)."""
    c = BulkClient()
    d = _dispatcher(c)
    preemptor = _pod("preemptor", "n1")
    d.add_binds([(preemptor, preemptor)])
    d.add(APICall(CallType.DELETE, _pod("victim")))
    d.add(APICall(CallType.STATUS_PATCH, _pod("loser"), condition={"type": "x"}))
    d.flush()
    kinds = [k for k, *_ in c.calls]
    assert kinds.index("delete") < kinds.index("bind_all")
    assert kinds.index("bind_all") < kinds.index("patch")
    assert len(d) == 0


def test_add_bind_superseded_by_delete_routes_bind_error():
    c = ScriptedClient()
    errors = []
    d = _dispatcher(c, on_bind_error=lambda p, n, e: errors.append((p.uid, n, e)))
    victim = _pod("v")
    d.add(APICall(CallType.DELETE, victim))
    d.add(APICall(CallType.BIND, victim, node_name="n1"))
    assert [u for u, _, _ in errors] == [victim.uid]
    assert errors[0][1] == "n1"
    assert isinstance(errors[0][2], Conflict)
    # the DELETE stays queued; no bind ever executes for the victim
    d.flush()
    assert [k for k, *_ in c.calls] == ["delete"]


def test_add_binds_superseded_by_delete_routes_bind_error():
    c = BulkClient()
    errors = []
    d = _dispatcher(c, on_bind_error=lambda p, n, e: errors.append(p.uid))
    victim = _pod("v", "n1")
    other = _pod("o", "n2")
    d.add(APICall(CallType.DELETE, victim))
    d.add_binds([(victim, victim), (other, other)])
    assert errors == [victim.uid]
    d.flush()
    bulk = [args for k, args in c.calls if k == "bind_all"]
    assert bulk == [(other.uid,)]


def test_is_delete_pending_lifecycle():
    c = ScriptedClient()
    d = _dispatcher(c)
    victim = _pod("v")
    assert not d.is_delete_pending(victim.uid)
    d.add(APICall(CallType.DELETE, victim))
    assert d.is_delete_pending(victim.uid)
    # a pending BIND is not a pending delete
    other = _pod("o")
    d.add(APICall(CallType.BIND, other, node_name="n1"))
    assert not d.is_delete_pending(other.uid)
    d.flush()
    assert not d.is_delete_pending(victim.uid)


def test_status_patch_merge_carries_nominated_node_name():
    """call_queue.go Merge: the newer condition wins but an unset
    nominated_node_name must not drop the pending call's."""
    c = ScriptedClient()
    d = _dispatcher(c)
    pod = _pod("p")
    d.add(APICall(CallType.STATUS_PATCH, pod,
                  condition={"type": "PodScheduled", "reason": "old"},
                  nominated_node_name="n7"))
    d.add(APICall(CallType.STATUS_PATCH, pod,
                  condition={"type": "PodScheduled", "reason": "new"}))
    d.flush()
    assert c.calls == [("patch", pod.uid, "n7")]


def test_status_patch_merge_explicit_clear_wins():
    """'' clears the nomination (preemption demotion) — it must NOT be
    treated like unset and resurrected from the pending call."""
    c = ScriptedClient()
    d = _dispatcher(c)
    pod = _pod("p")
    d.add(APICall(CallType.STATUS_PATCH, pod, condition={"type": "x"},
                  nominated_node_name="n7"))
    d.add(APICall(CallType.STATUS_PATCH, pod, condition={"type": "x"},
                  nominated_node_name=""))
    d.flush()
    assert c.calls == [("patch", pod.uid, "")]


def test_status_patch_merge_carries_condition():
    c = ScriptedClient()
    d = _dispatcher(c)
    pod = _pod("p")
    d.add(APICall(CallType.STATUS_PATCH, pod,
                  condition={"type": "PodScheduled", "reason": "keep"}))
    d.add(APICall(CallType.STATUS_PATCH, pod, nominated_node_name="n3"))
    d.flush()
    # nominated from the newer call, condition carried from the pending
    assert c.calls == [("patch", pod.uid, "n3")]


def test_status_patch_merge_against_apiserver():
    """End to end against the real store: the merged patch lands both the
    nomination carry-over and the newest condition."""
    api = APIServer()
    api.create_node(make_node("n1").obj())
    pod = _pod("p")
    api.create_pod(pod)
    d = _dispatcher(api)
    d.add(APICall(CallType.STATUS_PATCH, pod,
                  condition={"type": "PodScheduled", "status": "False",
                             "reason": "Unschedulable"},
                  nominated_node_name="n1"))
    d.add(APICall(CallType.STATUS_PATCH, pod,
                  condition={"type": "PodScheduled", "status": "False",
                             "reason": "SchedulerError"}))
    d.flush()
    stored = api.get_pod(pod.uid)
    assert stored.status.nominated_node_name == "n1"
    assert [c["reason"] for c in stored.status.conditions] == ["SchedulerError"]


def test_metrics_outcome_counters():
    c = ScriptedClient()
    c.fail["patch"] = [Conflict("x")]
    d = _dispatcher(c)
    d.metrics = SchedulerMetrics()
    d.add(APICall(CallType.STATUS_PATCH, _pod("a"), condition={"type": "x"}))
    d.add(APICall(CallType.DELETE, _pod("b")))
    d.flush()
    m = d.metrics.api_dispatcher_calls
    assert m.value(CallType.STATUS_PATCH.value, "error") == 1
    assert m.value(CallType.DELETE.value, "success") == 1
