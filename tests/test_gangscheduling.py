"""Gang scheduling: WorkloadManager + GangScheduling plugin + WaitOnPermit.

Mirrors the reference behaviors (gangscheduling.go:120-251,
workloadmanager.go:32-129): PreEnqueue gates below quorum, Reserve marks
assumed, Permit parks at Wait until assumed+assigned ≥ MinCount then
releases the whole gang atomically, and timeouts reject every parked
member, releasing their assumed resources.
"""

from kubernetes_tpu.api.types import ObjectMeta, PodGroup, Workload
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _cluster(n_nodes=4, cpu=8, device_gangs=True):
    api = APIServer()
    clock = FakeClock()
    sched = Scheduler(api, batch_size=64, clock=clock)
    if not device_gangs:
        # legacy path: gangs ride per-pod placement + the Permit barrier
        sched.feature_gates.set("GangDevicePlacement", False)
        sched.gang_device_enabled = False
    sched._clock_handle = clock
    for i in range(n_nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": cpu, "memory": "16Gi", "pods": 110}).obj())
    return api, sched


def _workload(api, name="job", min_count=3):
    api.create_workload(Workload(metadata=ObjectMeta(name=name),
                                 pod_groups=[PodGroup(name="workers",
                                                      min_count=min_count)]))


def _gang_pod(name, ref="job", cpu="1"):
    return make_pod(name).req({"cpu": cpu, "memory": "1Gi"}).workload(ref).obj()


class TestPreEnqueueQuorum:
    def test_gated_until_workload_exists(self):
        api, sched = _cluster()
        api.create_pod(_gang_pod("g0"))
        assert sched.schedule_pending() == 0
        n, summary = sched.queue.pending_pods()
        assert "unschedulablePods:1" in summary

    def test_gated_until_min_count_pods(self):
        api, sched = _cluster()
        _workload(api, min_count=3)
        api.create_pod(_gang_pod("g0"))
        api.create_pod(_gang_pod("g1"))
        assert sched.schedule_pending() == 0      # 2 < 3: both gated
        api.create_pod(_gang_pod("g2"))           # quorum of known pods
        assert sched.schedule_pending() == 3      # whole gang binds together
        bound = [p.spec.node_name for p in api.pods.values()]
        assert all(bound)

    def test_non_gang_pods_unaffected(self):
        api, sched = _cluster()
        api.create_pod(make_pod("plain").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1


class TestAllOrNothing:
    def test_partial_gang_rejects_atomically(self):
        """Capacity admits only 2 of 3 members: the device verdict
        rejects the WHOLE gang in one dispatch — nothing binds, nothing
        parks at Permit, no member holds partial resources."""
        api, sched = _cluster(n_nodes=2, cpu=1)
        _workload(api, min_count=3)
        for i in range(3):
            api.create_pod(_gang_pod(f"g{i}", cpu="1"))
        assert sched.schedule_pending() == 0
        assert len(sched._waiting_pods) == 0
        assert api.binding_count == 0
        assert sched.metrics.gang_dispatch.value("rejected") == 1.0
        # the capacity was never held: ordinary pods use it immediately
        api.create_pod(make_pod("plain0").req({"cpu": "1", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("plain1").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 2

    def test_partial_gang_holds_at_permit_legacy(self):
        """Gate off: capacity admits only 2 of 3 members — nothing binds,
        the two placeable pods park at Permit holding their resources
        (the reference's Permit-barrier dance)."""
        api, sched = _cluster(n_nodes=2, cpu=1, device_gangs=False)
        _workload(api, min_count=3)
        for i in range(3):
            api.create_pod(_gang_pod(f"g{i}", cpu="1"))
        assert sched.schedule_pending() == 0
        assert len(sched._waiting_pods) == 2
        assert api.binding_count == 0

    def test_timeout_rejects_all_and_releases_resources(self):
        api, sched = _cluster(n_nodes=2, cpu=1, device_gangs=False)
        _workload(api, min_count=3)
        for i in range(3):
            api.create_pod(_gang_pod(f"g{i}", cpu="1"))
        sched.schedule_pending()
        assert len(sched._waiting_pods) == 2
        sched._clock_handle.t += 400.0            # past the 300s gang timeout
        sched.flush_queues()
        assert len(sched._waiting_pods) == 0
        assert api.binding_count == 0
        # the freed capacity is usable again by ordinary pods
        api.create_pod(make_pod("plain0").req({"cpu": "1", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("plain1").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 2

    def test_gang_completes_when_capacity_arrives(self):
        api, sched = _cluster(n_nodes=2, cpu=1)
        _workload(api, min_count=3)
        for i in range(3):
            api.create_pod(_gang_pod(f"g{i}", cpu="1"))
        sched.schedule_pending()
        assert api.binding_count == 0
        # a third node arrives: the remaining member schedules, quorum hits,
        # the whole gang binds
        api.create_node(make_node("n2").capacity(
            {"cpu": 1, "memory": "16Gi", "pods": 110}).obj())
        sched._clock_handle.t += 15.0
        sched.flush_queues()
        assert sched.schedule_pending() == 3
        assert api.binding_count == 3

    def test_two_gangs_independent(self):
        api, sched = _cluster(n_nodes=6, cpu=1)
        _workload(api, "job-a", min_count=2)
        _workload(api, "job-b", min_count=3)
        for i in range(2):
            api.create_pod(_gang_pod(f"a{i}", ref="job-a"))
        for i in range(2):
            api.create_pod(_gang_pod(f"b{i}", ref="job-b"))  # below quorum
        assert sched.schedule_pending() == 2      # only gang A binds
        assert api.pods["default/a0"].spec.node_name
        assert not api.pods["default/b0"].spec.node_name
        api.create_pod(_gang_pod("b2", ref="job-b"))
        assert sched.schedule_pending() == 3      # gang B completes


class TestWorkloadArrivalUngates:
    def test_pods_before_workload(self):
        api, sched = _cluster()
        for i in range(3):
            api.create_pod(_gang_pod(f"g{i}"))
        assert sched.schedule_pending() == 0      # gated: no Workload yet
        _workload(api, min_count=3)               # arrival un-gates the gang
        assert sched.schedule_pending() == 3


class TestWorkloadManagerState:
    def test_sets_track_lifecycle(self):
        api, sched = _cluster()
        _workload(api, min_count=2)
        api.create_pod(_gang_pod("g0"))
        api.create_pod(_gang_pod("g1"))
        info = sched.workload_manager.pod_group_info(api.pods["default/g0"])
        assert len(info.all_pods) == 2 and len(info.unscheduled) == 2
        sched.schedule_pending()
        info = sched.workload_manager.pod_group_info(api.pods["default/g0"])
        assert len(info.assigned) == 2 and not info.unscheduled
        api.delete_pod("default/g0")
        info = sched.workload_manager.pod_group_info(api.pods["default/g1"])
        assert len(info.all_pods) == 1

    def test_expired_deadline_rejects_immediately_on_retry(self):
        """After the group deadline passes, retries must not re-park for
        another full timeout while holding assumed resources."""
        api, sched = _cluster(n_nodes=2, cpu=1, device_gangs=False)
        _workload(api, min_count=3)
        for i in range(3):
            api.create_pod(_gang_pod(f"g{i}", cpu="1"))
        sched.schedule_pending()
        assert len(sched._waiting_pods) == 2
        sched._clock_handle.t += 400.0
        sched.flush_queues()          # deadline sweep rejects both
        assert not sched._waiting_pods
        sched._clock_handle.t += 20.0
        sched.flush_queues()          # backoff expires; pods retry
        sched.schedule_pending()
        # expired group deadline: no pod may park again
        assert not sched._waiting_pods
        assert api.binding_count == 0
