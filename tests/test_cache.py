"""Cache tests (reference backend/cache/cache_test.go essentials)."""

import pytest

from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def bound_pod(name, node, cpu="1"):
    return make_pod(name).req({"cpu": cpu}).node(node).obj()


class TestAssumeFlow:
    def test_assume_confirm(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        p = bound_pod("p1", "n1")
        c.assume_pod(p)
        assert c.is_assumed_pod(p)
        assert c.get_node_info("n1").requested["cpu"] == 1000
        c.add_pod(p)  # informer confirms
        assert not c.is_assumed_pod(p)
        assert c.get_node_info("n1").requested["cpu"] == 1000
        assert len(c.get_node_info("n1").pods) == 1

    def test_forget(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        p = bound_pod("p1", "n1")
        c.assume_pod(p)
        c.forget_pod(p)
        assert not c.is_assumed_pod(p)
        assert c.get_node_info("n1").requested.get("cpu", 0) == 0

    def test_double_assume_raises(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        p = bound_pod("p1", "n1")
        c.assume_pod(p)
        with pytest.raises(KeyError):
            c.assume_pod(p)

    def test_expiry(self):
        clock = FakeClock()
        c = Cache(ttl=30.0, clock=clock)
        c.add_node(make_node("n1").obj())
        p = bound_pod("p1", "n1")
        c.assume_pod(p)
        c.finish_binding(p)
        clock.t = 10.0
        assert c.cleanup_expired_assumed_pods() == []
        clock.t = 31.0
        assert [x.uid for x in c.cleanup_expired_assumed_pods()] == [p.uid]
        assert c.pod_count() == 0

    def test_no_expiry_with_zero_ttl(self):
        clock = FakeClock()
        c = Cache(ttl=0.0, clock=clock)
        c.add_node(make_node("n1").obj())
        p = bound_pod("p1", "n1")
        c.assume_pod(p)
        c.finish_binding(p)
        clock.t = 1e9
        assert c.cleanup_expired_assumed_pods() == []

    def test_pod_before_node(self):
        c = Cache()
        p = bound_pod("p1", "nX")
        c.add_pod(p)
        assert c.get_node_info("nX").requested["cpu"] == 1000
        c.remove_pod(p)
        assert c.get_node_info("nX") is None  # imputed node garbage-collected


class TestSnapshot:
    def test_incremental_dirty_tracking(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        c.add_node(make_node("n2").obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        assert snap.dirty_nodes == {"n1", "n2"}
        assert len(snap.node_info_list) == 2

        c.update_snapshot(snap)
        assert snap.dirty_nodes == set()  # nothing changed

        c.add_pod(bound_pod("p1", "n1"))
        c.update_snapshot(snap)
        assert snap.dirty_nodes == {"n1"}
        assert snap.get("n1").requested["cpu"] == 1000

    def test_snapshot_isolation(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        c.add_pod(bound_pod("p1", "n1"))
        # snapshot unchanged until refreshed
        assert snap.get("n1").requested.get("cpu", 0) == 0
        c.update_snapshot(snap)
        assert snap.get("n1").requested["cpu"] == 1000

    def test_node_removal(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        c.add_node(make_node("n2").obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        c.remove_node(c.get_node_info("n2").node)
        c.update_snapshot(snap)
        assert snap.get("n2") is None
        assert [ni.name for ni in snap.node_info_list] == ["n1"]

    def test_affinity_list_membership(self):
        c = Cache()
        c.add_node(make_node("n1").obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        assert snap.have_pods_with_affinity_list == []
        p = (make_pod("p1").node("n1")
             .pod_affinity("topology.kubernetes.io/zone", {"app": "x"}).obj())
        c.add_pod(p)
        c.update_snapshot(snap)
        assert [ni.name for ni in snap.have_pods_with_affinity_list] == ["n1"]
        c.remove_pod(p)
        c.update_snapshot(snap)
        assert snap.have_pods_with_affinity_list == []

    def test_removed_node_with_pods_not_schedulable(self):
        # a node deleted while pods remain keeps its entry for pod removal
        # bookkeeping but must not appear in the schedulable list
        c = Cache()
        c.add_node(make_node("n1").obj())
        c.add_node(make_node("n2").obj())
        p = bound_pod("p1", "n2")
        c.add_pod(p)
        snap = Snapshot()
        c.update_snapshot(snap)
        c.remove_node(c.get_node_info("n2").node)
        c.update_snapshot(snap)
        assert [ni.name for ni in snap.node_info_list] == ["n1"]
        # once its last pod is removed the entry disappears entirely
        c.remove_pod(p)
        c.update_snapshot(snap)
        assert c.get_node_info("n2") is None

    def test_zone_round_robin_order(self):
        c = Cache()
        for name, zone in (("a1", "z1"), ("a2", "z1"), ("b1", "z2"), ("b2", "z2")):
            c.add_node(make_node(name).zone(zone).obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        order = [ni.name for ni in snap.node_info_list]
        # round-robin across zones (node_tree.go), not insertion order
        assert order == ["a1", "b1", "a2", "b2"]
