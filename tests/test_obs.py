"""Observability layer (ISSUE 10): shadow-oracle audit, decision
provenance (explain), and the SLO burn-rate engine.

The standing gates this file establishes:
- the audit at 100% sampling finds ZERO divergences on clean scheduling,
  and a deliberately perturbed decision IS caught, counted, ledgered and
  visible through /debug/audit;
- `explain_row`'s reconstructed winner matches the actual run_batch
  argmax bit-for-bit across a seeded fuzz of mixed drains, and the
  margin matches an independent eager evaluation;
- the drain ledger's hash chain breaks on tampering;
- the /debug/audit, /debug/explain and /debug/slo endpoints stay
  well-formed under concurrent drain traffic.
"""

import json
import os
import pickle
import threading
import urllib.request

import numpy as np
import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.obs.audit import DrainLedger, AuditRecord
from kubernetes_tpu.obs.slo import (DEFAULT_OBJECTIVES, SLOEngine,
                                    parse_objectives)
from kubernetes_tpu.ops.program import (ScoreConfig, explain_row,
                                        initial_carry, pod_rows_from_batch,
                                        run_batch)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.server import SchedulerServer
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _audited_scheduler(api, rate=1.0, sync=True, **kw):
    sched = Scheduler(api, batch_size=kw.pop("batch_size", 64), **kw)
    assert sched.audit is not None, "ShadowOracleAudit gate should be on"
    sched.audit.sample_rate = rate
    sched.audit.synchronous = sync
    return sched


def _basic_cluster(api, nodes=3):
    # strictly heterogeneous capacities: once any pod is placed, scores
    # are strict (no argmax ties), so a perturbed decision cannot hide
    # inside the oracle's tie set
    for i in range(nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 8 + 4 * i, "memory": "16Gi", "pods": 40})
            .zone(f"z{i % 2}").obj())


def _perturb_last(out, n_nodes):
    """Flip the LAST assigned pod's node (by then load has
    differentiated the scores, so the flip is out of the argmax set)."""
    for i in range(len(out) - 1, -1, -1):
        if out[i] >= 0:
            out[i] = (out[i] + 1) % n_nodes
            return True
    return False


# ---------------------------------------------------------------------------
# SLO engine


class TestSLOEngine:
    def test_burn_rates_and_windows(self):
        clock = FakeClock()
        slo = SLOEngine(clock=clock)
        # 1% bad over the 5m window with a 1% budget → burn 1.0
        slo.observe("attempt_latency", good=990, bad=10)
        burns = slo.burn_rates()
        assert burns["attempt_latency"]["5m"] == pytest.approx(1.0)
        assert burns["attempt_latency"]["1h"] == pytest.approx(1.0)
        # age the events past the 5m window but not the 1h window
        clock.t += 600
        slo.observe("attempt_latency", good=100, bad=0)
        burns = slo.burn_rates()
        assert burns["attempt_latency"]["5m"] == 0.0
        assert 0.0 < burns["attempt_latency"]["1h"] < 1.0

    def test_breaches_ladder(self):
        slo = SLOEngine(clock=FakeClock())
        # 50% error rate on a 1% budget → burn 50 ≫ every threshold
        slo.observe("device_fallback", good=10, bad=10)
        breaches = slo.breaches()
        assert {b["window"] for b in breaches} == {"5m", "1h", "6h"}
        assert all(b["sli"] == "device_fallback" for b in breaches)

    def test_no_traffic_is_silent(self):
        slo = SLOEngine(clock=FakeClock())
        assert slo.breaches() == []
        assert all(b == 0.0 for per in slo.burn_rates().values()
                   for b in per.values())

    def test_objective_overrides_and_validation(self):
        objs = parse_objectives({"attempt_latency": {
            "objective": 0.9, "thresholdSeconds": 0.25,
            "maxBurn": {"5m": 2.0}}})
        o = objs["attempt_latency"]
        assert o.objective == 0.9 and o.threshold_s == 0.25
        assert o.max_burn["5m"] == 2.0 and o.max_burn["6h"] == 1.0
        with pytest.raises(ValueError):
            parse_objectives({"nope": {}})
        with pytest.raises(ValueError):
            parse_objectives({"divergence": {"objective": 1.5}})
        with pytest.raises(ValueError):
            parse_objectives({"divergence": {"maxBurn": {"2d": 1}}})

    def test_config_knob_reaches_engine(self):
        from kubernetes_tpu.config import KubeSchedulerConfiguration
        cfg = KubeSchedulerConfiguration(
            slo_objectives={"e2e_latency": {"thresholdSeconds": 9.0}})
        cfg.validate()
        sched = Scheduler(APIServer(), config=cfg)
        assert sched.slo.threshold("e2e_latency") == 9.0
        with pytest.raises(ValueError):
            KubeSchedulerConfiguration(
                slo_objectives={"bogus": {}}).validate()

    def test_burn_rate_gauge_exposed(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        sched.slo.observe("attempt_latency", good=1)
        text = sched.metrics.exposition()
        assert 'scheduler_slo_burn_rate{sli="attempt_latency"' in text
        assert 'window="5m"' in text


# ---------------------------------------------------------------------------
# hash-chained drain ledger


def _rec(i):
    return AuditRecord(drain_id=i, profile_name="p", strategy="L",
                       weights={}, pods=[], nodes=[],
                       fingerprints={"podTableRows": f"h{i}"})


class TestDrainLedger:
    def test_chain_links_and_verifies(self):
        led = DrainLedger(capacity=8)
        recs = [led.append(_rec(i)) for i in range(5)]
        assert led.verify()
        for a, b in zip(recs, recs[1:]):
            assert b.prev_hash == a.hash
        assert led.head == recs[-1].hash

    def test_tamper_breaks_chain(self):
        led = DrainLedger(capacity=8)
        for i in range(4):
            led.append(_rec(i))
        assert led.verify()
        led.ring[1].fingerprints["podTableRows"] = "edited"
        assert not led.verify()

    def test_ring_eviction_keeps_window_valid(self):
        led = DrainLedger(capacity=3)
        for i in range(10):
            led.append(_rec(i))
        assert len(led.ring) == 3
        assert led.verify()
        assert led.appended == 10


class TestDrainLedgerStreaming:
    """The standby-facing streaming surface (ISSUE 12): seq cursors,
    tail/lag, chain splice for the failover handoff, and thread safety
    of append/verify under a concurrent tail subscriber."""

    def test_seq_tail_lag_and_head(self):
        led = DrainLedger(capacity=8)
        for i in range(5):
            led.append(_rec(i))
        assert [r.seq for r in led.tail(0)] == [1, 2, 3, 4, 5]
        assert [r.seq for r in led.tail(3)] == [4, 5]
        assert led.lag(3) == 2 and led.lag(5) == 0
        assert led.cursor() == 5
        assert led.head_hash() == led.head
        # a laggard whose cursor fell behind the ring gets what is still
        # retained; lag() reports the true arrears
        led2 = DrainLedger(capacity=3)
        for i in range(10):
            led2.append(_rec(i))
        assert [r.seq for r in led2.tail(0)] == [8, 9, 10]
        assert led2.lag(0) == 10

    def test_splice_continues_a_foreign_chain(self):
        """Failover handoff: the successor splices its empty ledger onto
        the dead leader's head so verify() holds ACROSS schedulers."""
        a = DrainLedger(capacity=8)
        for i in range(4):
            a.append(_rec(i))
        b = DrainLedger(capacity=8)
        b.splice(a.head_hash(), seq=a.cursor())
        rec = b.append(_rec(99))
        assert rec.prev_hash == a.head_hash()
        assert rec.seq == 5
        assert b.verify()
        with pytest.raises(ValueError):
            b.splice("other")   # non-empty: its chain already continues

    def test_concurrent_append_vs_tail_and_verify(self):
        """Thread-safety gate: one appender (the leader's audit worker)
        races a tail subscriber (the standby) that interleaves verify(),
        tail() and lag(). verify() must never observe a half-linked
        chain, tail seqs must be strictly increasing, and the subscriber
        must land exactly on the final cursor."""
        led = DrainLedger(capacity=64)
        n = 400
        errors, seen = [], []
        stop = threading.Event()

        def tailer():
            cursor = 0
            try:
                while not stop.is_set() or led.lag(cursor):
                    if not led.verify():
                        errors.append("verify() saw a broken chain")
                        return
                    for r in led.tail(cursor):
                        if r.seq <= cursor:
                            errors.append(f"tail not monotonic at {r.seq}")
                            return
                        cursor = r.seq
                        seen.append(r.seq)
            except Exception as e:          # pragma: no cover
                errors.append(repr(e))

        t = threading.Thread(target=tailer)
        t.start()
        for i in range(n):
            led.append(_rec(i))
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive()
        assert errors == []
        assert led.verify()
        assert seen and seen[-1] == n
        assert all(a < b for a, b in zip(seen, seen[1:]))


# ---------------------------------------------------------------------------
# shadow-oracle audit end to end


class TestShadowAudit:
    def test_clean_schedule_zero_divergence(self):
        api = APIServer()
        sched = _audited_scheduler(api)
        _basic_cluster(api)
        for i in range(6):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("big").req(
            {"cpu": "100", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        sched.audit.flush()
        m = sched.metrics
        for kind in ("assignment", "reason", "verdict"):
            assert m.oracle_divergence.value(kind) == 0
        assert m.shadow_audit_drains.value("clean") >= 1
        assert m.shadow_audit_drains.value("divergent") == 0
        d = sched.audit.dump()
        assert d["chainValid"]
        assert all(r["outcome"] == "clean" for r in d["records"])
        # the failed pod's reason histogram was diffed too (full replay)
        assert not any(r["truncated"] for r in d["records"])

    def test_perturbed_assignment_is_caught(self):
        api = APIServer()
        sched = _audited_scheduler(api)
        _basic_cluster(api)

        def perturb(pd, out):
            _perturb_last(out, 3)
        sched._test_assignment_perturb = perturb
        for i in range(4):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        sched.audit.flush()
        m = sched.metrics
        assert m.oracle_divergence.value("assignment") >= 1
        assert m.shadow_audit_drains.value("divergent") >= 1
        d = sched.audit.dump(details=True)
        diffs = [r["diffs"] for r in d["records"] if r["diffs"]]
        assert diffs and "assignment" in diffs[0]
        # SLO divergence SLI burns through every window
        assert any(b["sli"] == "divergence" for b in sched.slo.breaches())
        # the flight entry carries the full diff
        audited = [r for r in sched.flight.dump() if r["audit"]]
        assert audited and audited[-1]["audit"]["outcome"] == "divergent"

    def test_replay_prefix_cap_truncates(self):
        api = APIServer()
        sched = _audited_scheduler(api)
        sched.audit.max_replay_pods = 2
        _basic_cluster(api)
        for i in range(6):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "250m", "memory": "512Mi"}).obj())
        sched.schedule_pending()
        sched.audit.flush()
        recs = sched.audit.ledger.records()
        assert recs and recs[-1].truncated
        assert recs[-1].outcome == "clean"
        # clean records drop their replay payload (memory bound)
        assert recs[-1].nodes == [] and recs[-1].oracle == {}

    def test_sampling_rate_accumulator(self):
        api = APIServer()
        sched = _audited_scheduler(api, rate=0.5)
        wants = [sched.audit.want() for _ in range(8)]
        assert wants == [False, True] * 4

    def test_persisted_record_and_cli_roundtrip(self, tmp_path):
        import tools.audit_replay as ar
        api = APIServer()
        sched = _audited_scheduler(api)
        sched.audit.dirpath = str(tmp_path)
        _basic_cluster(api)
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("big").req(
            {"cpu": "100", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        sched.audit.flush()
        paths = sorted(tmp_path.glob("drain_*.pkl"))
        assert paths
        # clean record replays clean (exit 0)
        assert ar.main([str(paths[0])]) == 0
        # a tampered device decision → divergence (exit 2) — note the
        # hash chain covers the INPUT fingerprints, not the outcome
        with open(paths[0], "rb") as f:
            payload = pickle.load(f)
        # tamper the LAST bound pod (loaded cluster → strict scores, so
        # the edit cannot hide inside the oracle's argmax tie set)
        victim = next(u for u, _p, _pi in reversed(payload["pods"])
                      if payload["device"].get(u) is not None)
        payload["device"][victim] = "n2" \
            if payload["device"][victim] != "n2" else "n1"
        bad = tmp_path / "tampered_decision.pkl"
        with open(bad, "wb") as f:
            pickle.dump(payload, f)
        assert ar.main([str(bad)]) == 2
        # a tampered INPUT fingerprint breaks the hash (exit 3)
        with open(paths[0], "rb") as f:
            payload = pickle.load(f)
        payload["fingerprints"]["carry"] = "0" * 64
        forged = tmp_path / "tampered_input.pkl"
        with open(forged, "wb") as f:
            pickle.dump(payload, f)
        assert ar.main([str(forged)]) == 3


# ---------------------------------------------------------------------------
# explain_row parity (the bit-for-bit criterion)


def _fuzz_state(rng, n_nodes):
    cache = Cache()
    for i in range(n_nodes):
        w = (make_node(f"n{i}")
             .capacity({"cpu": int(rng.randint(2, 16)),
                        "memory": f"{rng.randint(4, 32)}Gi", "pods": 110})
             .zone(f"z{i % 3}")
             .label("kubernetes.io/hostname", f"n{i}"))
        if i % 4 == 1:
            w = w.label("disk", "ssd")
        cache.add_node(w.obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    return state


def _fuzz_pods(rng, n_pods):
    pods = []
    for i in range(n_pods):
        w = make_pod(f"p{i}").req(
            {"cpu": f"{rng.randint(1, 8) * 250}m",
             "memory": f"{rng.randint(1, 8) * 256}Mi"})
        if i % 5 == 0:
            w = w.node_selector(
                {"topology.kubernetes.io/zone": f"z{i % 3}"})
        if i % 3 == 0:
            w = w.preferred_node_affinity_in("disk", ["ssd"], weight=7)
        pods.append(w.obj())
    return pods


class TestExplainRowParity:
    def test_winner_and_margin_match_run_batch_fuzz(self):
        """Seeded fuzz of mixed drains: for every pod, the explain_row
        winner at the pre-pod carry equals the actual run_batch argmax
        bit-for-bit, and the margin matches an independent eager
        evaluation of the scan-step formula."""
        from kubernetes_tpu.ops.program import PodXs, _eval_pod, \
            _gather_row
        cfg = ScoreConfig()
        for seed in range(6):
            rng = np.random.RandomState(100 + seed)
            state = _fuzz_state(rng, int(rng.randint(8, 20)))
            builder = BatchBuilder(state)
            n = int(rng.randint(6, 16))
            batch = builder.build(_fuzz_pods(rng, n))
            assert not batch.host_fallback.any()
            xs, table = pod_rows_from_batch(batch)
            na = state.device_arrays()
            _final, assigns = run_batch(cfg, na, initial_carry(na), xs,
                                        table)
            assigns = np.asarray(assigns)
            carry = initial_carry(na)
            for i in range(n):
                t = int(batch.tidx[i])
                idx, totals, cols, n_feas = explain_row(
                    cfg, na, carry, table, t, k=4)
                idx = np.asarray(idx)
                totals = np.asarray(totals)
                cols = np.asarray(cols)
                # independent eager reference at the same carry
                pod = _gather_row(table, PodXs(
                    valid=np.bool_(True), sig=np.int32(0),
                    tidx=np.int32(t)))
                feas, tot, _p = _eval_pod(cfg, na, carry, pod)
                masked = np.where(np.asarray(feas), np.asarray(tot), -1)
                if assigns[i] < 0:
                    assert totals[0] < 0 or n_feas == 0
                else:
                    assert int(idx[0]) == int(assigns[i]), \
                        f"seed {seed} pod {i}"
                    assert int(totals[0]) == int(masked[int(idx[0])])
                    # per-plugin columns sum to the total
                    assert int(cols[0].sum()) == int(totals[0])
                    order = np.argsort(-masked, kind="stable")
                    if len(order) > 1 and totals[1] >= 0:
                        assert int(totals[0] - totals[1]) == int(
                            masked[order[0]] - masked[order[1]])
                # advance the reference carry by one pod (the real scan)
                one = PodXs(
                    valid=np.array([batch.valid[i]]),
                    sig=np.array([batch.sig[i]], np.int32),
                    tidx=np.array([batch.tidx[i]], np.int32))
                carry = run_batch(cfg, na, carry, one, table)[0]

    def test_exact_explain_matches_bind_scheduler_level(self):
        """Scheduler-level: every bound pod of audited drains (groups
        included) explains to its actual bind via the ledger replay."""
        api = APIServer()
        sched = _audited_scheduler(api, batch_size=128)
        for i in range(6):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 16, "memory": "32Gi", "pods": 60})
                .zone(f"z{i % 3}").obj())
        from kubernetes_tpu.obs.explain import explain_pod
        pods = []
        for i in range(24):
            w = make_pod(f"p{i}").req({"cpu": "500m", "memory": "1Gi"})
            if i % 2 == 0:
                w = (w.label("app", "web").spread_constraint(
                    1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "web"}))
            pods.append(w.obj())
        for p in pods:
            api.create_pod(p)
        sched.schedule_pending()
        sched.audit.flush()
        assert sched.metrics.shadow_audit_drains.value("divergent") == 0
        checked = 0
        for i in range(24):
            uid = f"default/p{i}"
            if not api.pods[uid].spec.node_name:
                continue
            out = explain_pod(sched, uid, k=3)
            assert out.get("mode") == "exact", out
            assert out["matchesBind"] is True
            assert out["winner"]["node"] == api.pods[uid].spec.node_name
            assert "rendered" in out
            checked += 1
        assert checked >= 20

    def test_current_state_mode_without_ledger(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        if sched.audit is not None:
            sched.audit.sample_rate = 0.0   # never sampled → no ledger
        _basic_cluster(api)
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        from kubernetes_tpu.obs.explain import explain_pod
        out = explain_pod(sched, "default/p0", k=3)
        assert out["mode"] == "current_state"
        assert out["winner"] is not None
        assert out["selfExcluded"]["resources"] is True
        assert out["boundNode"] == api.pods["default/p0"].spec.node_name
        missing = explain_pod(sched, "default/ghost")
        assert "error" in missing


# ---------------------------------------------------------------------------
# endpoints (incl. under concurrent drain traffic)


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestObsEndpoints:
    def test_audit_explain_slo_endpoints(self):
        api = APIServer()
        sched = _audited_scheduler(api)
        _basic_cluster(api)
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        sched.audit.flush()
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/audit?details=1")
            assert code == 200
            d = json.loads(body)
            assert d["chainValid"] and d["records"]
            assert d["records"][-1]["outcome"] == "clean"

            code, body = _get(srv.port, "/debug/explain?pod=default/p0")
            assert code == 200
            out = json.loads(body)
            assert out["mode"] == "exact" and out["matchesBind"]

            code, body = _get(srv.port, "/debug/explain")
            assert code == 400

            code, body = _get(srv.port,
                              "/debug/explain?pod=default/ghost")
            assert code == 404

            code, body = _get(srv.port, "/debug/slo")
            assert code == 200
            slo = json.loads(body)
            assert "burnRates" in slo and "objectives" in slo
            assert slo["breaches"] == []
        finally:
            srv.stop()

    def test_endpoints_under_concurrent_drains(self):
        """Satellite gate: the three debug surfaces stay well-formed
        while drains dispatch/commit on another thread."""
        api = APIServer()
        sched = _audited_scheduler(api, sync=False, batch_size=64)
        _basic_cluster(api, nodes=4)
        srv = SchedulerServer(sched).start()
        stop = threading.Event()
        errors: list = []

        def traffic():
            try:
                for j in range(12):
                    for i in range(8):
                        api.create_pod(make_pod(f"t{j}-{i}").req(
                            {"cpu": "100m", "memory": "64Mi"}).obj())
                    sched.schedule_pending()
            except Exception as e:   # surface scheduling-thread failures
                errors.append(e)
            finally:
                stop.set()

        t = threading.Thread(target=traffic)
        t.start()
        try:
            polls = 0
            while not stop.is_set() or polls < 3:
                for path in ("/debug/audit", "/debug/slo",
                             "/debug/flightrecorder?limit=4"):
                    code, body = _get(srv.port, path)
                    assert code == 200
                    json.loads(body)
                # exact-mode explain for an already-committed pod (every
                # drain is sampled, so committed pods are in the ledger)
                if polls >= 1:
                    code, body = _get(
                        srv.port, "/debug/explain?pod=default/t0-0")
                    if code == 200:
                        assert json.loads(body)["winner"] is not None
                polls += 1
                if stop.is_set():
                    break
        finally:
            t.join(timeout=60)
            srv.stop()
        assert not errors, errors
        sched.audit.flush()
        assert sched.metrics.shadow_audit_drains.value("divergent") == 0
        assert sched.audit.ledger.verify()


# ---------------------------------------------------------------------------
# metric families (satellite: pre-seeded exposition)


class TestObsMetricFamilies:
    def test_new_families_preseeded(self):
        from kubernetes_tpu.metrics import SchedulerMetrics
        text = SchedulerMetrics().exposition()
        for needle in (
                'scheduler_oracle_divergence_total{kind="assignment"} 0',
                'scheduler_oracle_divergence_total{kind="reason"} 0',
                'scheduler_oracle_divergence_total{kind="verdict"} 0',
                'scheduler_shadow_audit_drains_total{outcome="clean"} 0',
                'scheduler_shadow_audit_drains_total{outcome="divergent"} 0',
                "scheduler_audit_replay_seconds_count 0",
                "scheduler_explain_seconds_count 0",
                'scheduler_slo_burn_rate{sli="divergence",window="6h"} 0'):
            assert needle in text, needle


# ---------------------------------------------------------------------------
# the 100%-sampling sweep (slow): representative harness workloads must
# audit clean end to end — the bench-sweep acceptance in test form


@pytest.mark.slow
def test_audit_sweep_harness_workloads():
    from kubernetes_tpu.perf.harness import run_config
    cfg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "kubernetes_tpu", "perf", "configs",
        "performance-config.yaml")
    os.environ["KTPU_AUDIT_SAMPLE"] = "1.0"
    try:
        for case, wl in (("SchedulingBasic", "500Nodes_1000Pods"),
                         ("TopologySpreading", "500Nodes"),
                         ("SchedulingNodeAffinity", "500Nodes")):
            got = run_config(cfg, case, wl)
            assert got, f"{case}/{wl} not found"
            item = got[0][0]
            slo = item.extras.get("slo", {})
            assert slo.get("divergence_total", 0) == 0, (case, slo)
            assert slo.get("audited", 0) >= 1, (case, slo)
    finally:
        os.environ.pop("KTPU_AUDIT_SAMPLE", None)
