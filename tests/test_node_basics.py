"""NodeName / NodeUnschedulable / TaintToleration / NodePorts /
SchedulingGates / PrioritySort oracle tests."""

from kubernetes_tpu.framework.interface import Code, CycleState
from kubernetes_tpu.framework.types import NodeInfo, PodInfo, QueuedPodInfo
from kubernetes_tpu.plugins.node_basics import (NodeName, NodePorts,
                                                NodeUnschedulable,
                                                PrioritySort, SchedulingGates,
                                                TaintToleration)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def ni(node):
    return NodeInfo(node=node)


class TestNodeName:
    def test_match(self):
        p = NodeName()
        pod = make_pod().node("n1").obj()
        assert p.filter(CycleState(), pod, ni(make_node("n1").obj())).is_success()
        st = p.filter(CycleState(), pod, ni(make_node("n2").obj()))
        assert st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_empty_matches_all(self):
        p = NodeName()
        assert p.filter(CycleState(), make_pod().obj(), ni(make_node("n2").obj())).is_success()


class TestNodeUnschedulable:
    def test_unschedulable_rejected(self):
        p = NodeUnschedulable()
        node = make_node("n1").unschedulable().obj()
        st = p.filter(CycleState(), make_pod().obj(), ni(node))
        assert st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_toleration_lets_through(self):
        p = NodeUnschedulable()
        node = make_node("n1").unschedulable().obj()
        pod = make_pod().toleration(key="node.kubernetes.io/unschedulable",
                                    operator="Exists", effect="NoSchedule").obj()
        assert p.filter(CycleState(), pod, ni(node)).is_success()


class TestTaintToleration:
    def test_untolerated_noschedule(self):
        p = TaintToleration()
        node = make_node("n1").taint("k", "v", "NoSchedule").obj()
        st = p.filter(CycleState(), make_pod().obj(), ni(node))
        assert st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_tolerated(self):
        p = TaintToleration()
        node = make_node("n1").taint("k", "v", "NoSchedule").obj()
        pod = make_pod().toleration(key="k", operator="Equal", value="v",
                                    effect="NoSchedule").obj()
        assert p.filter(CycleState(), pod, ni(node)).is_success()

    def test_exists_empty_key_tolerates_everything(self):
        p = TaintToleration()
        node = make_node("n1").taint("k", "v", "NoExecute").obj()
        pod = make_pod().toleration(operator="Exists").obj()
        assert p.filter(CycleState(), pod, ni(node)).is_success()

    def test_prefer_no_schedule_not_filtered_but_scored(self):
        p = TaintToleration()
        node = make_node("n1").taint("k", "v", "PreferNoSchedule").obj()
        pod = make_pod().obj()
        cs = CycleState()
        assert p.filter(cs, pod, ni(node)).is_success()
        p.pre_score(cs, pod, [])
        score, _ = p.score(cs, pod, ni(node))
        assert score == 1

    def test_normalize_reversed(self):
        p = TaintToleration()
        scores = [2, 0, 1]
        p.normalize_scores(CycleState(), make_pod().obj(), scores)
        assert scores == [0, 100, 50]  # more intolerable taints → lower


class TestNodePorts:
    def run(self, pod, node_info):
        p = NodePorts()
        cs = CycleState()
        p.pre_filter(cs, pod, [])
        return p.filter(cs, pod, node_info)

    def test_no_conflict(self):
        n = ni(make_node("n1").obj())
        pod = make_pod().host_port(8080).obj()
        assert self.run(pod, n).is_success()

    def test_conflict(self):
        n = ni(make_node("n1").obj())
        n.add_pod(PodInfo.of(make_pod().host_port(8080).obj()))
        pod = make_pod().host_port(8080).obj()
        st = self.run(pod, n)
        assert st.code == Code.UNSCHEDULABLE

    def test_wildcard_ip_conflicts(self):
        n = ni(make_node("n1").obj())
        n.add_pod(PodInfo.of(make_pod().host_port(8080, ip="10.0.0.1").obj()))
        pod = make_pod().host_port(8080).obj()  # 0.0.0.0 wildcard
        assert self.run(pod, n).code == Code.UNSCHEDULABLE

    def test_different_protocol_ok(self):
        n = ni(make_node("n1").obj())
        n.add_pod(PodInfo.of(make_pod().host_port(8080, protocol="UDP").obj()))
        pod = make_pod().host_port(8080).obj()
        assert self.run(pod, n).is_success()


class TestSchedulingGates:
    def test_gated(self):
        p = SchedulingGates()
        pod = make_pod().scheduling_gate("wait-for-quota").obj()
        assert p.pre_enqueue(pod).code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert p.pre_enqueue(make_pod().obj()).is_success()


class TestPrioritySort:
    def test_priority_then_timestamp(self):
        p = PrioritySort()
        hi = QueuedPodInfo(PodInfo.of(make_pod().priority(10).obj()), timestamp=2.0)
        lo = QueuedPodInfo(PodInfo.of(make_pod().priority(1).obj()), timestamp=1.0)
        assert p.less(hi, lo) and not p.less(lo, hi)
        a = QueuedPodInfo(PodInfo.of(make_pod().priority(5).obj()), timestamp=1.0)
        b = QueuedPodInfo(PodInfo.of(make_pod().priority(5).obj()), timestamp=2.0)
        assert p.less(a, b) and not p.less(b, a)


class TestNodeDeclaredFeatures:
    def test_requires_declared_features(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.testing.wrappers import make_node, make_pod
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("plain").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 50}).obj())
        api.create_node(make_node("fancy").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 50})
            .declare_features("UserNamespaces", "RecursiveReadOnlyMounts").obj())
        api.create_pod(make_pod("needs").req({"cpu": "1", "memory": "1Gi"})
                       .require_features("UserNamespaces").obj())
        api.create_pod(make_pod("plain-pod").req(
            {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 2
        assert api.pods["default/needs"].spec.node_name == "fancy"

    def test_unsatisfied_is_unresolvable(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.testing.wrappers import make_node, make_pod
        class Clock:
            t = 0.0
            def __call__(self):
                return self.t

        clock = Clock()
        api = APIServer()
        sched = Scheduler(api, batch_size=64, clock=clock)
        api.create_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 50}).obj())
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"})
                       .require_features("FutureFeature").obj())
        assert sched.schedule_pending() == 0
        qpi = sched.queue.unschedulable_pods["default/p"]
        assert "NodeDeclaredFeatures" in qpi.unschedulable_plugins
        # a node declaring the feature un-gates it (past the backoff)
        api.create_node(make_node("n1").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 50})
            .declare_features("FutureFeature").obj())
        clock.t += 15.0
        sched.flush_queues()
        assert sched.schedule_pending() == 1

    def test_feature_update_on_existing_node_requeues(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.testing.wrappers import make_node, make_pod

        class Clock:
            t = 0.0
            def __call__(self):
                return self.t

        clock = Clock()
        api = APIServer()
        sched = Scheduler(api, batch_size=64, clock=clock)
        api.create_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 50}).obj())
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"})
                       .require_features("F").obj())
        assert sched.schedule_pending() == 0
        # the EXISTING node gains the feature (kubelet upgrade)
        api.update_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 50})
            .declare_features("F").obj())
        clock.t += 15.0
        sched.flush_queues()
        assert sched.schedule_pending() == 1
