"""Volume plugin family: binding state machine + limits + restrictions + zone.

Mirrors the reference behaviors (volumebinding/binder.go:285,406,479;
nodevolumelimits/csi.go; volumerestrictions; volumezone): WaitForFirstConsumer
end-to-end (filter → reserve → prebind → PVC bound), unbound-immediate
rejection, PV node-affinity routing, smallest-fitting-PV selection, dynamic
provisioning, CSI attach limits, RWO cross-node exclusivity, and zone labels.
"""

from kubernetes_tpu.api.types import (BINDING_IMMEDIATE,
                                      BINDING_WAIT_FOR_FIRST_CONSUMER,
                                      LabelSelectorRequirement, NodeSelector,
                                      NodeSelectorTerm, ObjectMeta,
                                      PersistentVolume, PersistentVolumeClaim,
                                      StorageClass)
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

GB = 1024 ** 3


def _cluster(n_nodes=3, **caps):
    api = APIServer()
    sched = Scheduler(api, batch_size=64)
    caps = caps or {"cpu": 8, "memory": "16Gi", "pods": 110}
    for i in range(n_nodes):
        api.create_node(make_node(f"n{i}").capacity(caps)
                        .zone(f"z{i}").obj())
    return api, sched


def _sc(api, name="fast", mode=BINDING_WAIT_FOR_FIRST_CONSUMER,
        provisioner=""):
    api.create_storage_class(StorageClass(
        metadata=ObjectMeta(name=name), provisioner=provisioner,
        volume_binding_mode=mode))


def _pv(api, name, size_gb, sc="fast", node=None, zone=None, driver="",
        labels=None):
    affinity = None
    if node is not None:
        affinity = NodeSelector(terms=(NodeSelectorTerm(
            match_fields=(LabelSelectorRequirement(
                key="metadata.name", operator="In", values=(node,)),)),))
    pv = PersistentVolume(metadata=ObjectMeta(name=name,
                                              labels=dict(labels or {})),
                          capacity_bytes=size_gb * GB,
                          storage_class_name=sc, node_affinity=affinity,
                          csi_driver=driver)
    if zone is not None:
        pv.metadata.labels["topology.kubernetes.io/zone"] = zone
    api.create_pv(pv)
    return pv


def _pvc(api, name, size_gb=1, sc="fast", ns="default"):
    pvc = PersistentVolumeClaim(
        metadata=ObjectMeta(name=name, namespace=ns),
        storage_class_name=sc, requested_bytes=size_gb * GB)
    api.create_pvc(pvc)
    return pvc


class TestVolumeBinding:
    def test_wait_for_first_consumer_end_to_end(self):
        """WFFC: pod lands on the PV's node; PreBind binds the claim."""
        api, sched = _cluster()
        _sc(api)
        _pv(api, "pv-local", 10, node="n2")
        pvc = _pvc(api, "data")
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/db"].spec.node_name == "n2"
        assert pvc.is_bound() and pvc.volume_name == "pv-local"
        assert api.get_pv("pv-local").claim_ref == pvc.uid

    def test_unbound_immediate_is_unresolvable(self):
        api, sched = _cluster()
        _sc(api, mode=BINDING_IMMEDIATE)
        _pvc(api, "data")
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 0
        qpi = sched.queue.unschedulable_pods["default/db"]
        assert "VolumeBinding" in qpi.unschedulable_plugins

    def test_bound_claim_routes_to_pv_node(self):
        api, sched = _cluster()
        _sc(api)
        pv = _pv(api, "pv0", 10, node="n1")
        pvc = _pvc(api, "data")
        api.bind_pvc(pvc, pv)
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/db"].spec.node_name == "n1"

    def test_smallest_fitting_pv_wins(self):
        api, sched = _cluster(n_nodes=1)
        _sc(api)
        _pv(api, "pv-big", 100, node="n0")
        _pv(api, "pv-small", 2, node="n0")
        pvc = _pvc(api, "data", size_gb=1)
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 1
        assert pvc.volume_name == "pv-small"

    def test_no_matching_pv_no_provisioner_unschedulable(self):
        api, sched = _cluster()
        _sc(api)
        _pvc(api, "data", size_gb=50)
        _pv(api, "pv-small", 1, node="n0")   # too small
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 0

    def test_dynamic_provisioning(self):
        api, sched = _cluster()
        _sc(api, provisioner="csi.example.com")
        pvc = _pvc(api, "data", size_gb=5)
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 1
        assert pvc.is_bound()
        pv = api.get_pv(pvc.volume_name)
        assert pv.capacity_bytes == 5 * GB
        node = api.pods["default/db"].spec.node_name
        # the provisioned PV is pinned to the chosen node
        from kubernetes_tpu.plugins.volumebinding import pv_reaches_node
        from kubernetes_tpu.framework.types import NodeInfo
        ni = NodeInfo(node=api.nodes[node])
        assert pv_reaches_node(pv, ni)

    def test_two_pods_cannot_share_one_available_pv(self):
        """The reserved-PV set (AssumeCache analog) must keep a second pod
        in the same drain from matching an already-claimed PV."""
        api, sched = _cluster(n_nodes=2)
        _sc(api)
        _pv(api, "pv0", 10, node="n0")
        _pvc(api, "data-a")
        _pvc(api, "data-b")
        api.create_pod(make_pod("a").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data-a").obj())
        api.create_pod(make_pod("b").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data-b").obj())
        assert sched.schedule_pending() == 1   # only one claim can bind
        bound = [n for n in ("default/a", "default/b")
                 if api.pods[n].spec.node_name]
        assert len(bound) == 1

    def test_missing_pvc_is_unresolvable(self):
        api, sched = _cluster()
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("ghost").obj())
        assert sched.schedule_pending() == 0


class TestNodeVolumeLimits:
    def test_csi_attach_limit(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 110,
             "attachable-volumes-csi-ebs.csi.aws.com": 2}).obj())
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"})
                .csi_volume("ebs.csi.aws.com").obj())
        assert sched.schedule_pending() == 2   # third exceeds the limit
        pending = (list(sched.queue.unschedulable_pods.values())
                   or [sched.queue.backoff_q.get(u)
                       for u in sched.queue.backoff_q._items])
        assert pending and "NodeVolumeLimitsCSI" in pending[0].unschedulable_plugins


class TestVolumeRestrictions:
    def test_rwo_is_node_exclusive(self):
        api, sched = _cluster(n_nodes=2, cpu=2, memory="4Gi", pods=10)
        _sc(api)
        pv = _pv(api, "pv0", 10, node=None)   # reachable anywhere
        pvc = _pvc(api, "shared")
        api.bind_pvc(pvc, pv)
        # holder lands somewhere; a second RWO user must co-locate — here
        # the holder's node is FULL, so the second pod stays pending
        api.create_pod(make_pod("holder").req({"cpu": "2", "memory": "1Gi"})
                       .pvc("shared").obj())
        assert sched.schedule_pending() == 1
        holder_node = api.pods["default/holder"].spec.node_name
        api.create_pod(make_pod("second").req({"cpu": "2", "memory": "1Gi"})
                       .pvc("shared").obj())
        assert sched.schedule_pending() == 0   # other node vetoed; holder full
        qpi = sched.queue.unschedulable_pods["default/second"]
        assert "VolumeRestrictions" in qpi.unschedulable_plugins

    def test_rwo_same_node_allowed(self):
        api, sched = _cluster(n_nodes=2)
        _sc(api)
        pv = _pv(api, "pv0", 10, node=None)
        pvc = _pvc(api, "shared")
        api.bind_pvc(pvc, pv)
        api.create_pod(make_pod("holder").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("shared").obj())
        assert sched.schedule_pending() == 1
        api.create_pod(make_pod("second").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("shared").obj())
        assert sched.schedule_pending() == 1
        assert (api.pods["default/second"].spec.node_name
                == api.pods["default/holder"].spec.node_name)


class TestVolumeZone:
    def test_pv_zone_restricts_nodes(self):
        api, sched = _cluster(n_nodes=3)   # zones z0 z1 z2
        _sc(api)
        pv = _pv(api, "pv0", 10, zone="z1")
        pvc = _pvc(api, "data")
        api.bind_pvc(pvc, pv)
        api.create_pod(make_pod("db").req({"cpu": "1", "memory": "1Gi"})
                       .pvc("data").obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/db"].spec.node_name == "n1"
