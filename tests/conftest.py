"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip hardware isn't available in CI; all sharding tests run against
XLA's host-platform device partitioning (the same mechanism the driver's
dryrun_multichip uses).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
