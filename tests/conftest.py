"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip hardware isn't available in CI; all sharding tests run against
XLA's host-platform device partitioning (the same mechanism the driver's
dryrun_multichip uses).
"""

import os
import sys

# Force CPU unconditionally: the ambient environment may pin JAX to a real
# accelerator (e.g. a tunneled TPU), and running the suite there pays a remote
# compile per distinct shape — the round-1 "recompilation storm" was exactly
# this. Parity/semantics tests are platform-independent; bench.py is the TPU
# path. The env var alone is NOT enough: accelerator site hooks may call
# jax.config.update("jax_platforms", ...) at interpreter start, so we update
# the config directly after import.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: repeated suite runs skip identical compiles.
_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
