"""Sharded (multi-device) batch program == single-device program.

Runs on the 8-device virtual CPU mesh from conftest — the same mechanism the
driver's dryrun_multichip uses."""

import jax
import numpy as np
import pytest

# parallel/sharding.py needs SOME shard_map API: jax.shard_map (0.5+) or
# jax.experimental.shard_map (older). Without either, report the whole
# module as skipped instead of 10 collection/runtime failures.
if not hasattr(jax, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _probe  # noqa: F401
    except ImportError:
        pytest.skip("no shard_map API in this jax build",
                    allow_module_level=True)

from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.ops.program import (ScoreConfig, initial_carry,
                                        pod_rows_from_batch, run_batch)
from kubernetes_tpu.parallel.sharding import (make_mesh, run_batch_sharded,
                                              shard_node_arrays)
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def build_state(n_nodes):
    """Deliberately NON-uniform across node index ranges: PreferNoSchedule
    taint counts and labels differ per region of the node axis, so any
    shard-local normalization (instead of a global max) changes decisions —
    the round-2 review caught exactly that bug."""
    cache = Cache()
    rng = np.random.RandomState(7)
    for i in range(n_nodes):
        w = (make_node(f"n{i}")
             .capacity({"cpu": int(rng.randint(2, 16)),
                        "memory": f"{rng.randint(4, 32)}Gi", "pods": 110})
             .zone(f"z{i % 3}")
             .label("kubernetes.io/hostname", f"n{i}"))
        # cluster tail carries escalating PreferNoSchedule taint counts
        for t in range(i * 3 // n_nodes):
            w = w.taint(f"soft{t}", "x", "PreferNoSchedule")
        if i % 4 == 1:
            w = w.label("disk", "ssd")
        cache.add_node(w.obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    return state


def build_pods(n_pods):
    rng = np.random.RandomState(11)
    pods = []
    for i in range(n_pods):
        w = make_pod(f"p{i}").req({"cpu": f"{rng.randint(1, 8)*250}m",
                                   "memory": f"{rng.randint(1, 8)*256}Mi"})
        if i % 5 == 0:
            w = w.node_selector({"topology.kubernetes.io/zone": f"z{i % 3}"})
        if i % 3 == 0:
            # weights chosen so per-shard maxima differ from the global max
            w = w.preferred_node_affinity_in("disk", ["ssd"], weight=7)
            w = w.preferred_node_affinity_in(
                "topology.kubernetes.io/zone", [f"z{i % 3}"], weight=3)
        if i % 7 == 0:
            w = w.toleration(key="soft0", operator="Equal", value="x",
                             effect="PreferNoSchedule")
        pods.append(w.obj())
    return pods


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_matches_single_device(n_devices):
    if len(jax.devices()) < n_devices:
        pytest.skip("not enough virtual devices")
    state = build_state(24)
    builder = BatchBuilder(state)
    batch = builder.build(build_pods(16))
    assert not batch.host_fallback.any()
    xs, table = pod_rows_from_batch(batch)
    cfg = ScoreConfig()

    na = state.device_arrays()
    carry0 = initial_carry(na)
    single_carry, single_assign = run_batch(cfg, na, carry0, xs, table)

    mesh = make_mesh(n_devices)
    na_sh = shard_node_arrays(mesh, na)
    sh_carry, sh_assign = run_batch_sharded(cfg, mesh, na_sh,
                                            initial_carry(na_sh), xs, table)

    np.testing.assert_array_equal(np.asarray(single_assign),
                                  np.asarray(sh_assign))
    for name in ("used", "nonzero_used", "npods", "ports"):
        np.testing.assert_array_equal(np.asarray(getattr(single_carry, name)),
                                      np.asarray(getattr(sh_carry, name)),
                                      err_msg=name)


def test_sharded_respects_infeasibility():
    state = build_state(8)
    builder = BatchBuilder(state)
    pods = [make_pod("huge").req({"cpu": "512"}).obj()]
    batch = builder.build(pods)
    xs, table = pod_rows_from_batch(batch)
    mesh = make_mesh(4)
    na = shard_node_arrays(mesh, state.device_arrays())
    _, assign = run_batch_sharded(ScoreConfig(), mesh, na,
                                  initial_carry(na), xs, table)
    assert int(np.asarray(assign)[0]) == -1


def build_group_pods(n_pods):
    """Spread + inter-pod affinity pods: exercise the group-kernel
    collectives (global domain min, distinct count, tv broadcast)."""
    pods = []
    for i in range(n_pods):
        w = make_pod(f"g{i}").req({"cpu": "250m", "memory": "256Mi"})
        if i % 3 == 0:
            w = (w.label("app", "spread")
                 .spread_constraint(1, "topology.kubernetes.io/zone",
                                    "DoNotSchedule", {"app": "spread"}))
        elif i % 3 == 1:
            w = (w.label("app", "anti")
                 .pod_affinity("topology.kubernetes.io/zone",
                               {"app": "anti"}, anti=True))
        else:
            w = (w.label("app", "soft")
                 .preferred_pod_affinity("topology.kubernetes.io/zone",
                                         {"app": "spread"}, weight=40))
        pods.append(w.obj())
    return pods


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_group_kernels_match_single_device(n_devices):
    from kubernetes_tpu.ops.groups import to_device
    from kubernetes_tpu.parallel.sharding import (shard_group_carry,
                                                  shard_groups)
    if len(jax.devices()) < n_devices:
        pytest.skip("not enough virtual devices")
    cache = Cache()
    for i in range(16):
        cache.add_node(make_node(f"n{i}")
                       .capacity({"cpu": 8, "memory": "16Gi", "pods": 110})
                       .zone(f"z{i % 3}")
                       .label("kubernetes.io/hostname", f"n{i}").obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    builder = BatchBuilder(state)
    batch = builder.build(build_group_pods(12))
    assert not batch.host_fallback.any()
    gd_np, gc_np = builder.groups.build_dev(snap)
    xs, table = pod_rows_from_batch(batch)
    cfg = ScoreConfig()

    na = state.device_arrays()
    gd, gc = to_device(gd_np), to_device(gc_np)
    single_carry, single_assign = run_batch(
        cfg, na, initial_carry(na, gc), xs, table, groups=gd)

    mesh = make_mesh(n_devices)
    na_sh = shard_node_arrays(mesh, na)
    gd_sh = shard_groups(mesh, to_device(gd_np))
    gc_sh = shard_group_carry(mesh, to_device(gc_np))
    sh_carry, sh_assign = run_batch_sharded(
        cfg, mesh, na_sh, initial_carry(na_sh, gc_sh), xs, table,
        groups=gd_sh)

    np.testing.assert_array_equal(np.asarray(single_assign),
                                  np.asarray(sh_assign))
    for name in ("spr_f_cnt", "spr_s_cnt", "ipa_veto", "ipa_a_cnt",
                 "ipa_aa_cnt", "ipa_score"):
        np.testing.assert_array_equal(
            np.asarray(getattr(single_carry.groups, name)),
            np.asarray(getattr(sh_carry.groups, name)), err_msg=name)


@pytest.mark.parametrize("n_devices", [4])
def test_sharded_image_locality_matches_single_device(n_devices):
    """Image spread ratios are cluster-wide: images clustered on ONE shard
    must still produce the single-device assignment (the num_with/total
    reduction needs a psum, not a shard-local sum)."""
    if len(jax.devices()) < n_devices:
        pytest.skip("not enough virtual devices")
    MB = 1024 * 1024
    cache = Cache()
    for i in range(16):
        w = make_node(f"n{i}").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 110})
        if i < 4:  # all images land on the first shard
            w = w.image("app:v1", 700 * MB)
        cache.add_node(w.obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    builder = BatchBuilder(state)
    pods = []
    for i in range(8):
        p = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj()
        p.spec.containers[0].image = "app:v1"
        pods.append(p)
    batch = builder.build(pods)
    assert not batch.host_fallback.any()
    xs, table = pod_rows_from_batch(batch)
    cfg = ScoreConfig()
    na = state.device_arrays()
    _, single_assign = run_batch(cfg, na, initial_carry(na), xs, table)
    mesh = make_mesh(n_devices)
    na_sh = shard_node_arrays(mesh, na)
    _, sh_assign = run_batch_sharded(cfg, mesh, na_sh,
                                     initial_carry(na_sh), xs, table)
    np.testing.assert_array_equal(np.asarray(single_assign),
                                  np.asarray(sh_assign))


def test_scheduler_mesh_mode_matches_single_device():
    """Scheduler(mesh=...) runs every segment through the sharded program;
    bind decisions must match the single-device scheduler exactly,
    including group constraints and mid-stream arrivals."""
    if len(jax.devices()) < 4:
        pytest.skip("not enough virtual devices")
    from kubernetes_tpu.backend.apiserver import APIServer
    from kubernetes_tpu.scheduler import Scheduler

    def run(mesh):
        api = APIServer()
        sched = Scheduler(api, batch_size=32, mesh=mesh)
        for i in range(8):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 8, "memory": "16Gi", "pods": 40})
                            .zone(f"z{i % 2}")
                            .label("kubernetes.io/hostname", f"n{i}").obj())
        total = 0
        for wave in range(2):
            for i in range(10):
                w = make_pod(f"p{wave}-{i}").req(
                    {"cpu": f"{250 * (1 + i % 3)}m", "memory": "512Mi"})
                if i % 3 == 0:
                    w = w.label("app", "s").spread_constraint(
                        1, "topology.kubernetes.io/zone", "DoNotSchedule",
                        {"app": "s"})
                api.create_pod(w.obj())
            total += sched.schedule_pending()
        assert sched.reconcile() == []
        return total, {p.name: p.spec.node_name for p in api.pods.values()}

    single = run(None)
    sharded = run(make_mesh(4))
    assert single == sharded
    assert single[0] == 20


def test_mesh_incremental_group_row_scatter():
    """A NEW spread signature arriving while the sharded carry is resident
    takes the incremental row scatter (ops/groups.py scatter_new_rows with
    mesh) instead of a wholesale reseed; decisions must still match
    single-device exactly."""
    if len(jax.devices()) < 4:
        pytest.skip("not enough virtual devices")
    from kubernetes_tpu.backend.apiserver import APIServer
    from kubernetes_tpu.scheduler import Scheduler

    def run(mesh):
        api = APIServer()
        sched = Scheduler(api, batch_size=32, mesh=mesh)
        for i in range(8):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 16, "memory": "32Gi", "pods": 40})
                            .zone(f"z{i % 2}")
                            .label("kubernetes.io/hostname", f"n{i}").obj())
        # wave 1: spread signature A mixed with plain pods (multi-sig →
        # scan path, group tensors seeded)
        for i in range(8):
            w = make_pod(f"a{i}").req({"cpu": "500m", "memory": "512Mi"})
            if i % 2 == 0:
                w = w.label("app", "a").spread_constraint(
                    2, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "a"})
            api.create_pod(w.obj())
        sched.schedule_pending()
        # wave 2: NEW spread signature B while the carry is resident →
        # incremental row scatter (sharded when mesh is set)
        for i in range(8):
            w = make_pod(f"b{i}").req({"cpu": "250m", "memory": "256Mi"})
            if i % 2 == 0:
                w = w.label("app", "b").spread_constraint(
                    1, "kubernetes.io/hostname", "ScheduleAnyway",
                    {"app": "b"})
            api.create_pod(w.obj())
        sched.schedule_pending()
        assert sched.reconcile() == []
        return {p.name: p.spec.node_name for p in api.pods.values()}

    assert run(None) == run(make_mesh(4))


def test_mesh_drain_phase_ledger_and_audit_coverage():
    """ISSUE 10 satellite: run_batch_sharded was the only JIT entry with
    no drain_phase/h2d attribution — the mesh-placed uploads must now
    land in the compile ledger's h2d phases, the sharded dispatch must
    show up under the drain-phase histogram, and the shadow audit must
    replay mesh drains clean (decisions are bit-identical by contract)."""
    if len(jax.devices()) < 4:
        pytest.skip("not enough virtual devices")
    from kubernetes_tpu.backend.apiserver import APIServer
    from kubernetes_tpu.perf.ledger import GLOBAL as ledger
    from kubernetes_tpu.scheduler import Scheduler

    h2d_before = ledger.h2d.get("host_snapshot", 0)
    calls_before = (ledger.kernels["run_batch_sharded"].calls
                    if "run_batch_sharded" in ledger.kernels else 0)
    api = APIServer()
    sched = Scheduler(api, batch_size=32, mesh=make_mesh(4))
    assert sched.audit is not None
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    for i in range(8):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": 4 + 2 * i, "memory": "16Gi",
                                   "pods": 40})
                        .zone(f"z{i % 2}").obj())
    for i in range(12):
        api.create_pod(make_pod(f"p{i}").req(
            {"cpu": f"{250 * (1 + i % 3)}m", "memory": "512Mi"}).obj())
    assert sched.schedule_pending() == 12
    # ledger: the sharded kernel dispatched and its uploads were billed
    assert ledger.kernels["run_batch_sharded"].calls > calls_before
    assert ledger.h2d.get("host_snapshot", 0) > h2d_before
    # drain spans: the mesh upload ran under the host_snapshot phase
    assert sched.metrics.drain_phase.count("host_snapshot") >= 1
    # the audit replayed the sharded drain against the host oracle
    m = sched.metrics
    assert m.shadow_audit_drains.value("clean") >= 1
    assert m.shadow_audit_drains.value("divergent") == 0
    for kind in ("assignment", "reason", "verdict"):
        assert m.oracle_divergence.value(kind) == 0


def test_mesh_host_greedy_parity():
    """The host greedy serves same-signature group drains under a mesh
    too (the staging arrays are host-resident regardless of device
    sharding); decisions match single-device."""
    if len(jax.devices()) < 4:
        pytest.skip("not enough virtual devices")
    from kubernetes_tpu.backend.apiserver import APIServer
    from kubernetes_tpu.scheduler import Scheduler

    def run(mesh):
        api = APIServer()
        sched = Scheduler(api, batch_size=64, mesh=mesh)
        # force the host greedy (the feature under test): the wave path
        # would otherwise take the single-device drain
        sched.feature_gates.set("SpeculativeWavePlacement", False)
        for i in range(8):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 16, "memory": "32Gi", "pods": 40})
                            .zone(f"z{i % 4}")
                            .label("kubernetes.io/hostname", f"n{i}").obj())
        for i in range(24):   # >= UNIFORM_RUN_MIN, single signature
            api.create_pod(make_pod(f"p{i}")
                           .req({"cpu": "500m", "memory": "512Mi"})
                           .label("app", "s")
                           .spread_constraint(1, "topology.kubernetes.io/zone",
                                              "DoNotSchedule", {"app": "s"})
                           .obj())
        assert sched.schedule_pending() == 24
        # the feature under test must actually engage — a silent fallback
        # to the scan would make this parity check vacuous
        assert sched.host_greedy_runs > 0
        assert sched.reconcile() == []
        return {p.name: p.spec.node_name for p in api.pods.values()}

    assert run(None) == run(make_mesh(4))
