"""Sharded-mesh drain toolchain == single-device toolchain (ISSUE 16).

PR 8's drain compiler gave the single-device backend tiered execution
(closed-form uniform, speculative waves, gang dispatch, batched
preemption dry-run); the node-sharded mesh ran everything through the
scan. This file is the acceptance gate for porting those tiers onto the
mesh: for every drain kind the mesh scheduler must produce bind
decisions BIT-IDENTICAL to the single-device scheduler — same pods on
the same nodes, same rejections, same nominations — while actually
dispatching the sharded kernels (asserted through the compile ledger,
so a silent fallback to `run_batch_sharded` can't make the parity
vacuous). The seeded fuzz sweeps mixed workloads across all kinds, and
the shadow-oracle audit at 100% sampling closes the loop: the host
oracle replays every mesh drain with zero divergence.
"""

import random

import jax
import numpy as np
import pytest

if not hasattr(jax, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _probe  # noqa: F401
    except ImportError:
        pytest.skip("no shard_map API in this jax build",
                    allow_module_level=True)

from kubernetes_tpu.api.types import ObjectMeta, PodGroup, Workload
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.parallel.sharding import make_mesh
from kubernetes_tpu.perf.ledger import GLOBAL as LEDGER
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"

pytestmark = pytest.mark.skipif(len(jax.devices()) < 4,
                                reason="not enough virtual devices")


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _kcalls(name):
    rec = LEDGER.kernels.get(name)
    return rec.calls if rec is not None else 0


def _sched(api, mesh, batch_size=64):
    clock = Clock()
    s = Scheduler(api, batch_size=batch_size, clock=clock, mesh=mesh)
    s.dispatcher.sleep = lambda _s: None
    s._clock = clock
    return s


def _nodes(api, n, zones=3, rng=None, soft_taints=True):
    """Deliberately heterogeneous across the node axis (same shape as
    tests/test_sharding.py build_state): capacities, zones, escalating
    PreferNoSchedule taints and an ssd label band all vary by node index,
    so shard-local normalization or a shard-local top-K would change
    decisions. `soft_taints=False` keeps the cluster closed-form
    eligible (prefer-taints bar the uniform tier entirely)."""
    rng = rng or np.random.RandomState(7)
    for i in range(n):
        w = (make_node(f"n{i}")
             .capacity({"cpu": int(rng.randint(4, 16)),
                        "memory": f"{rng.randint(8, 32)}Gi", "pods": 110})
             .zone(f"z{i % zones}")
             .label("kubernetes.io/hostname", f"n{i}"))
        if soft_taints:
            for t in range(i * 3 // max(n, 1)):
                w = w.taint(f"soft{t}", "x", "PreferNoSchedule")
        if i % 4 == 1:
            w = w.label("disk", "ssd")
        api.create_node(w.obj())


def _binds(api):
    inner = getattr(api, "inner", api)
    return {p.metadata.name: (p.spec.node_name,
                              p.status.nominated_node_name)
            for p in inner.pods.values()}


def _settle(api, sched, rounds=4):
    total = sched.schedule_pending()
    for _ in range(rounds):
        sched._clock.t += 400.0
        sched.flush_queues()
        total += sched.schedule_pending()
    return total


# ---------------------------------------------------------------------------
# per-tier parity, each asserting its sharded kernel actually dispatched


class TestTierParity:
    def test_uniform_tier_parity(self):
        """One signature × 32 pods ≥ uniform_min: the closed-form uniform
        tier — previously single-device-only — must run its sharded twin
        and bind identically. Heterogeneous capacities make the
        shard-local top-K union argument load-bearing (per-shard maxima
        differ from the global ranking)."""
        def run(mesh):
            api = APIServer()
            sched = _sched(api, mesh)
            _nodes(api, 24, soft_taints=False)
            for i in range(32):
                api.create_pod(
                    make_pod(f"p{i}")
                    .req({"cpu": "500m", "memory": "512Mi"})
                    .obj())
            before = _kcalls("run_uniform_sharded")
            assert sched.schedule_pending() == 32
            if mesh is not None:
                assert _kcalls("run_uniform_sharded") > before
            assert sched.reconcile() == []
            return _binds(api)

        assert run(None) == run(make_mesh(4))

    def test_wavescan_tier_parity(self):
        """Interleaved signatures over a ≥ wave_min_span window: the
        speculative wave (wavescan flavor — the merge wave stays
        single-device) must plan on the mesh and bind identically."""
        def run(mesh, grouped):
            api = APIServer()
            sched = _sched(api, mesh)
            _nodes(api, 16)
            for i in range(32):
                w = make_pod(f"p{i}").req(
                    {"cpu": f"{250 * (1 + i % 4)}m", "memory": "512Mi"})
                if grouped and i % 4 == 0:
                    w = w.label("app", "s").spread_constraint(
                        2, ZONE, "DoNotSchedule", {"app": "s"})
                api.create_pod(w.obj())
            before = _kcalls("run_plan_sharded")
            bound = sched.schedule_pending()
            if mesh is not None:
                assert _kcalls("run_plan_sharded") > before
            assert sched.reconcile() == []
            return bound, _binds(api)

        for grouped in (False, True):
            single = run(None, grouped)
            sharded = run(make_mesh(4), grouped)
            assert single == sharded, f"grouped={grouped}"
            assert single[0] == 32

    def test_wavescan_ports_parity(self):
        """Host-port pods thread the port-conflict surface through the
        sharded wave: first-come wins, duplicates stay pending — same
        verdicts as single-device."""
        def run(mesh):
            api = APIServer()
            sched = _sched(api, mesh)
            _nodes(api, 8)
            for i in range(28):
                w = make_pod(f"p{i}").req(
                    {"cpu": f"{250 * (1 + i % 3)}m", "memory": "256Mi"})
                if i % 3 == 0:
                    w = w.host_port(8000 + i % 2)
                api.create_pod(w.obj())
            sched.schedule_pending()
            assert sched.reconcile() == []
            return _binds(api)

        assert run(None) == run(make_mesh(4))

    def test_gang_uniform_tier_parity(self):
        """A same-signature gang takes the closed-form gang tier; the
        whole-gang accept verdict and every member placement must match
        single-device, in one sharded dispatch."""
        def run(mesh):
            api = APIServer()
            sched = _sched(api, mesh)
            _nodes(api, 8)
            api.create_workload(Workload(
                metadata=ObjectMeta(name="train"),
                pod_groups=[PodGroup(name="workers", min_count=12)]))
            for i in range(12):
                api.create_pod(make_pod(f"train-{i}")
                               .req({"cpu": "1", "memory": "1Gi"})
                               .workload("train").obj())
            before = _kcalls("run_gang_sharded")
            bound = sched.schedule_pending()
            if mesh is not None:
                assert _kcalls("run_gang_sharded") > before
            assert sched.reconcile() == []
            return bound, _binds(api)

        single = run(None)
        assert single == run(make_mesh(4))
        assert single[0] == 12

    def test_gang_scan_tier_parity_with_contiguity(self):
        """Mixed-signature gang members force the gang scan tier; a
        nonzero contiguity weight engages the replicated domain counter
        (the psum-broadcast domcnt) — placements must still match."""
        def run(mesh):
            api = APIServer()
            sched = _sched(api, mesh)
            sched.gang_contiguity_weight = 3
            _nodes(api, 12, zones=3)
            api.create_workload(Workload(
                metadata=ObjectMeta(name="mix"),
                pod_groups=[PodGroup(name="workers", min_count=8)]))
            for i in range(8):
                api.create_pod(make_pod(f"mix-{i}")
                               .req({"cpu": f"{1 + i % 3}", "memory": "1Gi"})
                               .workload("mix").obj())
            before = _kcalls("run_gang_sharded")
            bound = sched.schedule_pending()
            if mesh is not None:
                assert _kcalls("run_gang_sharded") > before
            assert sched.reconcile() == []
            return bound, _binds(api)

        single = run(None)
        assert single == run(make_mesh(4))
        assert single[0] == 8

    def test_gang_reject_atomic_on_mesh(self):
        """An infeasible gang rejected by the sharded tier binds nothing,
        parks nothing and holds nothing — the single-device atomicity
        contract, unchanged by the mesh."""
        api = APIServer()
        sched = _sched(api, make_mesh(4))
        for i in range(2):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 1, "memory": "16Gi", "pods": 110}).obj())
        api.create_workload(Workload(
            metadata=ObjectMeta(name="big"),
            pod_groups=[PodGroup(name="workers", min_count=3)]))
        for i in range(3):
            api.create_pod(make_pod(f"big-{i}")
                           .req({"cpu": "1", "memory": "1Gi"})
                           .workload("big").obj())
        assert sched.schedule_pending() == 0
        assert api.binding_count == 0
        assert not sched._waiting_pods
        assert not sched.cache.assumed_pods

    def test_preemption_dry_run_parity(self):
        """A saturated cluster + a high-priority preemptor: the batched
        dry-run gathers candidate rows host-side under a mesh (the kernel
        is row-local) — victim choice and nomination must match the
        single-device batched path."""
        def run(mesh):
            api = APIServer()
            sched = _sched(api, mesh)
            for i in range(4):
                api.create_node(make_node(f"n{i}")
                                .capacity({"cpu": 4, "memory": "16Gi",
                                           "pods": 110})
                                .zone(f"z{i % 2}").obj())
            uid = 0
            for i in range(4):
                for pr in (0, 5, 10):
                    p = (make_pod(f"v{uid}").req({"cpu": "1",
                                                  "memory": "1Gi"})
                         .priority(pr).label("app", "a").obj())
                    api.create_pod(p)
                    api.bind(p, f"n{i}")
                    uid += 1
            api.create_pod(make_pod("preemptor")
                           .req({"cpu": "2", "memory": "2Gi"})
                           .priority(100).obj())
            before = _kcalls("dry_run")
            _settle(api, sched)
            assert _kcalls("dry_run") > before
            return _binds(api)

        assert run(None) == run(make_mesh(4))


class TestShardedScatter:
    def test_dirty_row_scatter_exact_at_shard_boundaries(self):
        """Regression: out-of-shard dirty indices used to clip in-range
        and collide with real writes at each shard's boundary rows — XLA
        scatter picks an arbitrary duplicate winner, silently dropping
        updates. Scatter every boundary row plus pad duplicates; the
        sharded copy must equal the host staging exactly."""
        from kubernetes_tpu.backend.cache import Cache, Snapshot
        from kubernetes_tpu.parallel.sharding import (scatter_rows_sharded,
                                                      shard_node_arrays)
        from kubernetes_tpu.state.tensorize import ClusterState, NodeArrays

        cache = Cache()
        for i in range(16):
            cache.add_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 110}).obj())
        snap = Snapshot()
        cache.update_snapshot(snap)
        state = ClusterState()
        state.apply_snapshot(snap, full=True)
        a = state.ensure_arrays()
        mesh = make_mesh(4)
        dev = shard_node_arrays(mesh, a)
        # mutate the host rows the scatter must carry over: every shard
        # boundary (first/last row of each 4-row shard)
        idx = np.array([0, 3, 4, 7, 8, 11, 12, 15], np.int64)
        for r in idx:
            a.used[r] = r + 1
            a.npods[r] = 2 * r + 1
        D = 16  # pow2 pad, repeating idx[0] (the production pad rule)
        pidx = np.full((D,), idx[0], np.int64)
        pidx[:len(idx)] = idx
        rows = NodeArrays(*(x[pidx] for x in a))
        out = scatter_rows_sharded(mesh, dev, pidx.astype(np.int32), rows)
        np.testing.assert_array_equal(np.asarray(out.used), a.used)
        np.testing.assert_array_equal(np.asarray(out.npods), a.npods)
        np.testing.assert_array_equal(np.asarray(out.cap), a.cap)


class TestShardedClusterProbe:
    def test_probe_bit_parity_mesh_vs_single(self):
        """cluster_probe_sharded all-gathers the node shards and runs
        the identical reduction: every output element must equal the
        single-device probe bit-for-bit (which test_cluster_probe.py in
        turn holds against a numpy oracle)."""
        from kubernetes_tpu.backend.cache import Cache, Snapshot
        from kubernetes_tpu.ops.program import cluster_probe, initial_carry
        from kubernetes_tpu.parallel.sharding import (cluster_probe_sharded,
                                                      shard_node_arrays)
        from kubernetes_tpu.state.tensorize import ClusterState, NodeArrays

        rng = np.random.RandomState(29)
        cache = Cache()
        for i in range(16):
            cache.add_node(make_node(f"n{i}").capacity(
                {"cpu": int(rng.randint(4, 32)),
                 "memory": f"{int(rng.randint(8, 64))}Gi",
                 "pods": 110}).obj())
        snap = Snapshot()
        cache.update_snapshot(snap)
        state = ClusterState()
        state.apply_snapshot(snap, full=True)
        a = state.ensure_arrays()
        # non-trivial carry: random usage on a third of the rows
        for r in range(0, 16, 3):
            a.used[r, 0] = min(int(a.cap[r, 0]), r + 1)
            a.npods[r] = r
        import jax.numpy as jnp
        dev_single = NodeArrays(*(jnp.asarray(x) for x in a))
        carry = initial_carry(dev_single)
        mesh = make_mesh(4)
        dev = shard_node_arrays(mesh, a)
        scarry = initial_carry(dev)
        dom = np.asarray(rng.randint(0, 3, size=a.cap.shape[0]), np.int32)
        single = cluster_probe(dev_single, carry, jnp.asarray(dom), 3)
        sharded = cluster_probe_sharded(mesh, dev, scarry,
                                        jnp.asarray(dom), 3)
        for got, want in zip(sharded, single):
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
        assert _kcalls("cluster_probe_sharded") > 0


# ---------------------------------------------------------------------------
# seeded fuzz across drain kinds


def _fuzz_workload(api, rng):
    """A mixed drain: a uniform run, interleaved wave signatures, spread
    groups, a gang and a preemptor — every tier in one queue."""
    _nodes(api, rng.randint(8, 20), zones=rng.randint(2, 4),
           rng=np.random.RandomState(rng.randint(0, 1000)))
    n_uni = rng.randint(16, 24)
    for i in range(n_uni):
        api.create_pod(make_pod(f"u{i}")
                       .req({"cpu": "250m", "memory": "256Mi"}).obj())
    for i in range(rng.randint(24, 32)):
        w = make_pod(f"w{i}").req(
            {"cpu": f"{250 * (1 + i % rng.randint(2, 5))}m",
             "memory": "256Mi"})
        if i % 5 == 0:
            w = w.label("app", "s").spread_constraint(
                rng.randint(1, 3), ZONE, "DoNotSchedule", {"app": "s"})
        if i % 7 == 0:
            w = w.preferred_node_affinity_in("disk", ["ssd"],
                                             weight=rng.randint(1, 10))
        api.create_pod(w.obj())
    if rng.random() < 0.7:
        size = rng.randint(3, 8)
        api.create_workload(Workload(
            metadata=ObjectMeta(name="g"),
            pod_groups=[PodGroup(name="workers",
                                 min_count=rng.randint(2, size + 1))]))
        for i in range(size):
            api.create_pod(make_pod(f"g-{i}")
                           .req({"cpu": "500m", "memory": "512Mi"})
                           .workload("g").obj())
    if rng.random() < 0.5:
        api.create_pod(make_pod("pre")
                       .req({"cpu": "2", "memory": "2Gi"})
                       .priority(100).obj())


class TestSeededFuzzParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_mixed_drain_kinds_bit_identical(self, seed):
        def run(mesh):
            api = APIServer()
            sched = _sched(api, mesh)
            _fuzz_workload(api, random.Random(seed))
            _settle(api, sched)
            assert sched.reconcile() == []
            return _binds(api)

        assert run(None) == run(make_mesh(4)), f"seed={seed}"


# ---------------------------------------------------------------------------
# the independent referee: host-oracle replay of every mesh drain


class TestShadowOracleOnMesh:
    def test_zero_divergence_at_full_sampling(self):
        """Every non-gang mesh drain (uniform, wavescan, scan, preemption
        overlays excluded by capture rules) replayed synchronously by the
        host oracle: zero divergence across assignment, reason and
        verdict — the ISSUE 16 acceptance line."""
        api = APIServer()
        sched = _sched(api, make_mesh(4))
        assert sched.audit is not None
        sched.audit.sample_rate = 1.0
        sched.audit.synchronous = True
        _nodes(api, 16)
        for i in range(32):  # uniform drain
            api.create_pod(make_pod(f"u{i}")
                           .req({"cpu": "250m", "memory": "256Mi"}).obj())
        assert sched.schedule_pending() == 32
        for i in range(28):  # wavescan drain
            w = make_pod(f"w{i}").req(
                {"cpu": f"{250 * (1 + i % 4)}m", "memory": "256Mi"})
            if i % 4 == 0:
                w = w.label("app", "s").spread_constraint(
                    2, ZONE, "DoNotSchedule", {"app": "s"})
            api.create_pod(w.obj())
        assert sched.schedule_pending() == 28
        m = sched.metrics
        assert m.shadow_audit_drains.value("clean") >= 2
        assert m.shadow_audit_drains.value("divergent") == 0
        for kind in ("assignment", "reason", "verdict"):
            assert m.oracle_divergence.value(kind) == 0
