"""Fleet observatory (ISSUE 19): telemetry federation, cross-shard
journey stitching, incident forensics.

- the FleetAggregator merges N instances' series/SLO/probe into ONE
  cluster view — counters sum, log2 histograms merge losslessly, the
  fleet burns one error budget per SLI;
- the ISSUE 19 bugfix regression: a warm standby's mirrored series are
  visible (role="standby") but EXCLUDED from cluster merges and the
  federated SLO burn — they would double-count the active's stream;
- the IncidentWatchdog captures bounded evidence bundles on breach and
  `tools/incident_dump.py` re-verifies the embedded audit chains
  offline (exit 2 on tamper);
- /debug/fleet and the /debug/ index serve it all, and the index test
  keeps DEBUG_ENDPOINTS in lockstep with the do_GET handler chain;
- the slow tier holds the PR-13-shape overhead gate at 5k nodes.
"""

import importlib.util
import json
import os
import re
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.config import KubeSchedulerConfiguration
from kubernetes_tpu.ha import ShardManager, ShardScheduler, StandbyScheduler
from kubernetes_tpu.obs.federation import FleetAggregator
from kubernetes_tpu.obs.incident import TRIGGERS, IncidentWatchdog
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "incident_dump", os.path.join(REPO, "tools", "incident_dump.py"))
incident_dump = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(incident_dump)

SEED = int(os.environ.get("TEST_SEED", "20260807"))


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _no_sleep(sched):
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _audited(sched):
    assert sched.audit is not None, "ShadowOracleAudit gate must be on"
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    return sched


def _nodes(api, n=6, cpu=32, mem="64Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _create(api, n, prefix="p", ns="default"):
    for i in range(n):
        api.create_pod(make_pod(f"{prefix}{i}", namespace=ns).req(
            {"cpu": "250m", "memory": "512Mi"}).obj())


def _shard(client, identity, clock):
    inst = ShardScheduler(client, identity=identity, clock=clock,
                          batch_size=32)
    _audited(_no_sleep(inst.scheduler))
    return inst


def _fleet(api, clock, identities=("sched-a", "sched-b")):
    insts = [_shard(api, ident, clock) for ident in identities]
    mgr = ShardManager(api, instances=insts, clock=clock)
    mgr.wire_ledgers()
    return insts, mgr


def _drive(api, insts, clock, want_bound, mgr=None, max_rounds=80):
    for _ in range(max_rounds):
        for inst in insts:
            inst.tick()
            inst.scheduler.schedule_pending()
            clock.t += 5.0
            inst.scheduler.flush_queues()
        if mgr is not None:
            mgr.sync_all()
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if bound >= want_bound:
            return
    raise AssertionError("fleet did not quiesce")


# -- federated series ----------------------------------------------------------


def test_fleet_exposition_injects_shard_and_role_labels():
    """The fleet exposition is every member's scrape with shard/role
    labels injected, HELP/TYPE once per family — scrape-shaped, so the
    cross-process step only swaps the transport."""
    api = APIServer()
    _nodes(api, n=4)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    _create(api, 4, prefix="pa", ns="ns-a")
    _drive(api, (a, b), clock, want_bound=4, mgr=mgr)

    text = mgr.fleet.exposition()
    assert 'shard="sched-a"' in text and 'shard="sched-b"' in text
    assert 'role="active"' in text
    # HELP/TYPE once per family even with two members contributing
    assert text.count("# TYPE scheduler_schedule_attempts_total ") == 1
    # one concrete re-labeled sample: sched-a committed the 4 binds
    line = next(ln for ln in text.splitlines()
                if ln.startswith("scheduler_schedule_attempts_total")
                and 'shard="sched-a"' in ln and 'result="scheduled"' in ln)
    assert line.endswith(" 4")


def test_cluster_series_sums_counters_and_merges_histograms():
    """Counters sum per label set across active members; histograms
    merge bucket-wise (identical log2 layout per family), so the
    cluster-level count equals the sum of per-shard counts."""
    api = APIServer()
    _nodes(api, n=4)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    _create(api, 3, prefix="pa", ns="ns-a")
    _create(api, 3, prefix="pb", ns="ns-b")
    _drive(api, (a, b), clock, want_bound=6, mgr=mgr)

    series = mgr.fleet.cluster_series()
    per_shard = sum(
        inst.scheduler.metrics.schedule_attempts.value(
            "scheduled", "default-scheduler")
        for inst in (a, b))
    assert per_shard == 6
    merged = series["counters"]["scheduler_schedule_attempts_total"]
    key = next(k for k in merged if "scheduled" in k)
    assert merged[key] == 6.0

    hist = series["histograms"]["scheduler_scheduling_attempt_duration_seconds"]
    want = sum(sum(inst.scheduler.metrics.attempt_duration._totals.values())
               for inst in (a, b))
    assert hist["count"] == want and want >= 2   # ≥1 attempt per shard
    assert hist["shards"] == 2
    assert sum(hist["counts"]) == hist["count"]


def test_federated_slo_burns_one_budget_across_actives():
    """Two actives' burn rings merge epoch-wise: the federated engine's
    totals are the sums, and a breach that only shows at cluster level
    (each shard under threshold, fleet over) is detected."""
    clock = Clock()
    api = APIServer()
    a = _no_sleep(Scheduler(api, batch_size=8, clock=clock))
    b = _no_sleep(Scheduler(api, batch_size=8, clock=clock))
    a.journey.instance, b.journey.instance = "sched-a", "sched-b"
    fleet = FleetAggregator([a, b])

    # 2% bad on each shard against a 98.0%-target SLI would pass alone
    # at 2× headroom; together they still merge to exactly the sum
    a.slo.observe("e2e_latency", good=490, bad=10)
    b.slo.observe("e2e_latency", good=480, bad=20)
    eng = fleet.federated_slo()
    assert eng._totals["e2e_latency"] == [970, 30]
    snap = eng.snapshot(compact=True)
    assert snap is not None
    ring = eng._buckets["e2e_latency"]
    assert sum(cell[1] for cell in ring) == 970
    assert sum(cell[2] for cell in ring) == 30


def test_standby_mirror_excluded_from_cluster_merge_and_burn():
    """THE ISSUE 19 bugfix regression: a warm standby mirrors the
    active's SLI streams (it ingests the same watch echoes), so its
    series must appear in the federated exposition (role="standby") but
    NEVER in cluster_series / the federated SLO burn — else every event
    double-counts and the cluster budget burns twice as fast."""
    clock = Clock()
    api = APIServer()
    active = _audited(_no_sleep(Scheduler(api, batch_size=8, clock=clock)))
    active.journey.instance = "sched-active"
    standby = StandbyScheduler(
        api, identity="sched-standby", clock=clock,
        scheduler=_audited(_no_sleep(Scheduler(api, batch_size=8,
                                               clock=clock))))
    assert standby.scheduler.ha_role == "standby"
    fleet = FleetAggregator([active, standby])

    active.metrics.api_retries.inc("bind", by=3.0)
    standby.scheduler.metrics.api_retries.inc("bind", by=3.0)  # the mirror
    active.slo.observe("e2e_latency", good=90, bad=10)
    standby.scheduler.slo.observe("e2e_latency", good=90, bad=10)

    # visible in the series view, labeled as the mirror it is
    text = fleet.exposition()
    assert 'shard="sched-standby",role="standby"' in text
    # ...but the cluster merge and the burn see the ACTIVE stream once
    merged = fleet.cluster_series()["counters"]["scheduler_api_retries_total"]
    key = next(k for k in merged if "bind" in k)
    assert merged[key] == 3.0
    eng = fleet.federated_slo()
    assert eng._totals["e2e_latency"] == [90, 10]
    # promotion flips the role: the former standby now contributes
    standby.scheduler.promote()
    eng2 = fleet.federated_slo()
    assert eng2._totals["e2e_latency"] == [180, 20]


def test_fleet_probe_is_capacity_weighted():
    """Per-shard cluster_probe snapshots merge weighted by validNodes:
    a 3×-bigger slice moves the fleet index 3× as far."""
    clock = Clock()
    a = _no_sleep(Scheduler(APIServer(), batch_size=8, clock=clock))
    b = _no_sleep(Scheduler(APIServer(), batch_size=8, clock=clock))
    a.journey.instance, b.journey.instance = "sched-a", "sched-b"
    a._last_probe = {"validNodes": 30,
                     "resources": {"cpu": {"frag": 0.2}},
                     "domains": {"spread": 0.1}}
    b._last_probe = {"validNodes": 10,
                     "resources": {"cpu": {"frag": 0.6}},
                     "domains": {"spread": 0.5}}
    probe = FleetAggregator([a, b]).fleet_probe()
    assert probe["validNodes"] == 40
    assert probe["resources"]["cpu"]["frag"] == pytest.approx(0.3)
    assert probe["domains"]["spread"] == pytest.approx(0.2)
    assert set(probe["shards"]) == {"sched-a", "sched-b"}


# -- incident forensics --------------------------------------------------------


def test_watchdog_divergence_capture_verifies_offline(tmp_path):
    """Injected divergence growth → ONE bundle captured (edge-detected:
    a second check without growth captures nothing), written to
    incidentDir, offline-verified by tools/incident_dump.py; a tampered
    copy exits 2."""
    api = APIServer()
    _nodes(api, n=4)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    wd = mgr.attach_watchdog(dirpath=str(tmp_path))
    assert mgr.watchdog is wd
    _create(api, 4, prefix="pa", ns="ns-a")
    _drive(api, (a, b), clock, want_bound=4, mgr=mgr)
    assert wd.check() == []                   # healthy fleet: no capture

    before = a.scheduler.metrics.incidents.value("divergence")
    a.scheduler.metrics.oracle_divergence.inc("assignment")
    captured = wd.check()
    assert [c["trigger"] for c in captured] == ["divergence"]
    assert wd.check() == []                   # no growth → no re-capture
    assert a.scheduler.metrics.incidents.value("divergence") == before + 1

    path = captured[0]["path"]
    assert os.path.exists(path)
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["schema"] == "tpu-scheduler-incident/v1"
    assert bundle["signals"]["delta"] == 1.0
    # real evidence: per-instance flight windows + audit slices with
    # records from the drains above, and the shard-map history
    assert any(bundle["flight"].values())
    assert any((s["dump"].get("records") or [])
               for s in bundle["audit"].values())
    assert bundle["shardMap"]["current"]["numShards"] == 2
    assert bundle["shardMap"]["history"]

    assert incident_dump.main([path]) == 0
    assert incident_dump.main([path, "--verify-only"]) == 0

    # tamper with one audit record: the offline verifier must exit 2
    name = next(n for n, s in bundle["audit"].items()
                if s["dump"].get("records"))
    bundle["audit"][name]["dump"]["records"][0]["profile"] = "edited"
    tampered = tmp_path / "tampered.json"
    tampered.write_text(json.dumps(bundle, default=str))
    assert incident_dump.main([str(tampered)]) == 2
    assert incident_dump.main(["/nonexistent/bundle.json"]) == 1


def test_watchdog_fence_storm_and_retention(tmp_path):
    """A fenced-write burst over threshold trips fence_storm; retention
    keeps only the newest max_bundles files."""
    api = APIServer()
    _nodes(api, n=2)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    wd = mgr.attach_watchdog(dirpath=str(tmp_path), max_bundles=2,
                             fence_storm_threshold=4)
    a.scheduler.metrics.fenced_writes_rejected.inc(by=4.0)
    assert [c["trigger"] for c in wd.check()] == ["fence_storm"]
    for _ in range(3):
        wd.capture("divergence", {})
    files = sorted(fn for fn in os.listdir(tmp_path)
                   if fn.startswith("incident-"))
    assert len(files) == 2                    # retention pruned the rest
    assert files[-1].endswith("-divergence.json")


def test_incident_triggers_preseeded_in_exposition():
    """Every watchdog trigger is a pre-seeded series: dashboards can
    alert on rate() before the first incident ever fires."""
    sched = Scheduler(APIServer(), batch_size=8)
    text = sched.metrics.exposition()
    for trigger in TRIGGERS:
        assert f'scheduler_incidents_total{{trigger="{trigger}"}} 0' \
            in text, trigger


def test_fleet_observatory_gate_off_degrades(tmp_path):
    """With FleetObservatory off the manager carries no federation
    plane (pre-19 behavior); with it on but IncidentForensics off,
    attach_watchdog is a no-op; incidentDir in the config arms the
    watchdog at construction when both gates are on."""
    clock = Clock()
    api = APIServer()

    def _inst(gates, **cfg_kw):
        cfg = KubeSchedulerConfiguration(feature_gates=gates, **cfg_kw)
        inst = ShardScheduler(api, identity="sched-a", clock=clock,
                              batch_size=8, config=cfg)
        _no_sleep(inst.scheduler)
        return inst

    off = ShardManager(api, instances=[
        _inst({"FleetObservatory": False})], clock=clock)
    assert off.fleet is None and off.stitcher is None
    assert off.attach_watchdog(dirpath=str(tmp_path)) is None
    off.tick_all()                            # no watchdog poll, no crash
    assert off.debug()["incidents"] is None

    no_forensics = ShardManager(api, instances=[
        _inst({"IncidentForensics": False})], clock=clock)
    assert no_forensics.fleet is not None
    assert no_forensics.attach_watchdog(dirpath=str(tmp_path)) is None

    armed = ShardManager(api, instances=[
        _inst({}, incident_dir=str(tmp_path))], clock=clock)
    assert armed.watchdog is not None
    assert armed.watchdog.dirpath == str(tmp_path)


# -- serving -------------------------------------------------------------------


def test_debug_fleet_endpoint_and_index():
    """/debug/fleet serves the federated view (and ?exposition=1 the
    merged scrape); /debug/ lists every registered endpoint with its
    availability; without a manager /debug/fleet 404s."""
    from kubernetes_tpu.server import DEBUG_ENDPOINTS, SchedulerServer

    api = APIServer()
    _nodes(api, n=2)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0})

    srv = SchedulerServer(a.scheduler, shard_manager=mgr).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/debug/fleet") as r:
            fleet = json.loads(r.read())
        assert set(fleet["members"]) == {"sched-a", "sched-b"}
        assert fleet["members"]["sched-a"]["role"] == "active"
        assert "slo" in fleet and "probe" in fleet
        with urllib.request.urlopen(f"{base}/debug/fleet?exposition=1") as r:
            text = r.read().decode()
        assert 'shard="sched-b"' in text
        with urllib.request.urlopen(f"{base}/debug/") as r:
            index = json.loads(r.read())
        listed = {e["path"] for e in index["endpoints"]}
        assert listed == {p for p, _d in DEBUG_ENDPOINTS}
        by_path = {e["path"]: e for e in index["endpoints"]}
        assert by_path["/debug/fleet"]["available"] is True
        assert all(e["description"] for e in index["endpoints"])
    finally:
        srv.stop()

    solo = SchedulerServer(a.scheduler).start()   # no manager
    try:
        base = f"http://127.0.0.1:{solo.port}"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/fleet")
        assert err.value.code == 404
        with urllib.request.urlopen(f"{base}/debug") as r:
            index = json.loads(r.read())
        assert {e["path"]: e["available"]
                for e in index["endpoints"]}["/debug/fleet"] is False
    finally:
        solo.stop()


def test_debug_index_lockstep_with_handler_chain():
    """Source-level lint: every `/debug/...` route the do_GET chain
    matches must be described in DEBUG_ENDPOINTS and vice versa — a new
    endpoint cannot land invisible to the index."""
    from kubernetes_tpu.server import DEBUG_ENDPOINTS

    with open(os.path.join(REPO, "kubernetes_tpu", "server.py")) as f:
        source = f.read()
    handler = source[source.index("def do_GET"):source.index("def _query")]
    routed = set(re.findall(r'"(/debug/[a-z]+)"', handler))
    declared = {p for p, _d in DEBUG_ENDPOINTS}
    assert routed == declared, (
        f"do_GET routes {sorted(routed - declared)} missing from "
        f"DEBUG_ENDPOINTS; {sorted(declared - routed)} declared but "
        "not routed")


def test_stitched_pod_served_from_manager_server():
    """/debug/pod on a manager-attached server returns the STITCHED
    cross-shard view (instances list present), not one ledger's slice."""
    from kubernetes_tpu.server import SchedulerServer

    api = APIServer()
    _nodes(api, n=4)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    _create(api, 2, prefix="pa", ns="ns-a")
    _drive(api, (a, b), clock, want_bound=2, mgr=mgr)
    uid = next(iter(api.pods))

    srv = SchedulerServer(a.scheduler, shard_manager=mgr).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/pod?uid={uid}") as r:
            view = json.loads(r.read())
    finally:
        srv.stop()
    # stitched shape: fragments from BOTH instances (owner scheduled it,
    # the peer parked it), with the renderer legend attached
    assert set(view["instances"]) == {"sched-a", "sched-b"}
    assert view["notes"] and view["transitions"]
    assert all("instance" in tr for tr in view["transitions"])


def test_fleet_chrome_trace_has_per_shard_tracks():
    api = APIServer()
    _nodes(api, n=4)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    _create(api, 2, prefix="pb", ns="ns-b")
    _drive(api, (a, b), clock, want_bound=2, mgr=mgr)
    trace = mgr.stitcher.chrome_trace()
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("name") == "process_name"}
    assert {"shard:sched-a", "shard:sched-b"} <= names


# -- overhead gate (slow tier) -------------------------------------------------


@pytest.mark.slow
class TestFleetObservatoryOverheadGate:
    def test_overhead_within_5_percent_at_5k_nodes(self):
        """ISSUE 19 acceptance: SchedulingBasic-shaped 5k-node drains
        with FleetObservatory+IncidentForensics (plus the journey rails
        they ride on) ON stay within 5% of gates-OFF throughput (median
        of 3 measured passes each — the PR 13 gate shape)."""

        def _feed(api, n, start=0):
            api.create_pods([make_pod(f"p{start + i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj()
                for i in range(n)])

        def one_pass(gate_on):
            cfg = KubeSchedulerConfiguration(feature_gates={
                "PodJourneyTracing": gate_on,
                "FleetObservatory": gate_on,
                "IncidentForensics": gate_on})
            api = APIServer()
            sched = Scheduler(api, batch_size=8192, config=cfg)
            fleet = FleetAggregator([sched])
            from kubernetes_tpu.obs.stitch import JourneyStitcher
            wd = (IncidentWatchdog(fleet, JourneyStitcher([sched]),
                                   metrics=sched.metrics)
                  if gate_on else None)
            for i in range(5000):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
            sched.prime()
            t0 = time.perf_counter()
            created = 0
            while created < 10000:
                _feed(api, 512, start=created)
                created += 512
                sched.schedule_pending(wait=False)
                if wd is not None:
                    wd.check()                # the watchdog rides along
            sched.schedule_pending()
            dt = time.perf_counter() - t0
            assert sched.scheduled_count == created
            return created / dt

        one_pass(True)    # warm every executable outside the measurement
        off = sorted(one_pass(False) for _ in range(3))[1]
        on = sorted(one_pass(True) for _ in range(3))[1]
        assert on >= 0.95 * off, (
            f"fleet-observatory overhead gate: on={on:.0f} off={off:.0f} "
            f"pods/s ({on / off - 1:+.1%})")
