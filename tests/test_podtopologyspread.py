"""PodTopologySpread parity tests (modeled on reference
pkg/scheduler/framework/plugins/podtopologyspread/filtering_test.go and
scoring_test.go canonical cases)."""

from kubernetes_tpu.framework.interface import Code, CycleState
from kubernetes_tpu.framework.types import NodeInfo, PodInfo
from kubernetes_tpu.plugins.podtopologyspread import (
    LABEL_HOSTNAME, LABEL_ZONE, PodTopologySpread)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def mk_cluster():
    """2 zones: zoneA{node-a,node-b} zoneB{node-x,node-y}; hostname labels."""
    nodes = {}
    for name, zone in (("node-a", "zoneA"), ("node-b", "zoneA"),
                       ("node-x", "zoneB"), ("node-y", "zoneB")):
        n = make_node(name).zone(zone).label(LABEL_HOSTNAME, name).obj()
        nodes[name] = NodeInfo(node=n)
    return nodes


def place(nodes, node_name, pod):
    nodes[node_name].add_pod(PodInfo.of(pod))


def run_filter(plugin, pod, nodes):
    state = CycleState()
    nis = list(nodes.values())
    _, status = plugin.pre_filter(state, pod, nis)
    if not status.is_success():
        return {ni.name: status for ni in nis}, state
    return {ni.name: plugin.filter(state, pod, ni) for ni in nis}, state


class TestFilter:
    def test_zone_spread_max_skew_1(self):
        nodes = mk_cluster()
        # 2 matching pods in zoneA, 1 in zoneB → min=1; skew of zoneA would be
        # 2+1-1=2 > 1 → only zoneB feasible.
        for node, i in (("node-a", 0), ("node-b", 1), ("node-x", 2)):
            place(nodes, node, make_pod(f"p{i}").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_ZONE, "DoNotSchedule", {"foo": ""}).obj())
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        assert not statuses["node-a"].is_success()
        assert not statuses["node-b"].is_success()
        assert statuses["node-x"].is_success()
        assert statuses["node-y"].is_success()

    def test_hostname_spread(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("p0").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_HOSTNAME, "DoNotSchedule", {"foo": ""}).obj())
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        # min = 0 (3 empty nodes); node-a would get skew 1+1-0=2 > 1
        assert not statuses["node-a"].is_success()
        for n in ("node-b", "node-x", "node-y"):
            assert statuses[n].is_success()

    def test_missing_topology_label_unresolvable(self):
        nodes = mk_cluster()
        bare = make_node("node-bare").obj()  # no zone label
        nodes["node-bare"] = NodeInfo(node=bare)
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_ZONE, "DoNotSchedule", {"foo": ""}).obj())
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        assert statuses["node-bare"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_self_match_counts(self):
        nodes = mk_cluster()
        # 1 matching pod in zoneA, 0 in zoneB → min=0. A pod that matches its
        # own selector adds selfMatch=1: zoneA skew = 1+1-0 = 2 > 1.
        place(nodes, "node-a", make_pod("p0").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_ZONE, "DoNotSchedule", {"foo": ""}).obj())
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        assert not statuses["node-a"].is_success()
        assert statuses["node-x"].is_success()

    def test_non_matching_selector_ignores_self(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("p0").label("foo", "").obj())
        # incoming pod does NOT match the selector → selfMatch=0, zoneA skew
        # = 1+0-0 = 1 ≤ 1 → all feasible.
        pod = (make_pod("incoming")
               .spread_constraint(1, LABEL_ZONE, "DoNotSchedule", {"foo": ""}).obj())
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        assert all(s.is_success() for s in statuses.values())

    def test_min_domains_forces_spread(self):
        nodes = mk_cluster()
        # minDomains=3 but only 2 zone domains exist → global min treated as
        # 0 (filtering.go:66-77). 1 matching pod in each zone; skew anywhere
        # = 1+1-0 = 2 > 1 → nothing fits.
        place(nodes, "node-a", make_pod("p0").label("foo", "").obj())
        place(nodes, "node-x", make_pod("p1").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_ZONE, "DoNotSchedule", {"foo": ""},
                                  min_domains=3).obj())
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        assert all(not s.is_success() for s in statuses.values())

    def test_add_remove_pod_extensions(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("p0").label("foo", "").obj())
        place(nodes, "node-a", make_pod("p1").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(2, LABEL_ZONE, "DoNotSchedule", {"foo": ""}).obj())
        pl = PodTopologySpread()
        state = CycleState()
        pl.pre_filter(state, pod, list(nodes.values()))
        assert not pl.filter(state, pod, nodes["node-a"]).is_success()
        # removing one victim from node-a brings zoneA down to 1 match:
        # skew = 1+1-0 = 2 ≤ 2 → fits.
        victim = nodes["node-a"].pods[0]
        pl.remove_pod(state, pod, victim, nodes["node-a"])
        assert pl.filter(state, pod, nodes["node-a"]).is_success()
        pl.add_pod(state, pod, victim, nodes["node-a"])
        assert not pl.filter(state, pod, nodes["node-a"]).is_success()


class TestScore:
    def run(self, pod, nodes):
        pl = PodTopologySpread()
        state = CycleState()
        nis = list(nodes.values())
        status = pl.pre_score(state, pod, nis)
        assert status.is_success(), status
        scores = []
        for ni in nis:
            s, st = pl.score(state, pod, ni)
            assert st.is_success()
            scores.append(s)
        pl.normalize_scores(state, pod, scores, node_names=[ni.name for ni in nis])
        return dict(zip(nodes.keys(), scores))

    def test_prefers_less_crowded_zone(self):
        nodes = mk_cluster()
        for node, i in (("node-a", 0), ("node-b", 1), ("node-x", 2)):
            place(nodes, node, make_pod(f"p{i}").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_ZONE, "ScheduleAnyway", {"foo": ""}).obj())
        scores = self.run(pod, nodes)
        assert scores["node-x"] > scores["node-a"]
        assert scores["node-y"] > scores["node-b"]
        assert scores["node-a"] == scores["node-b"]

    def test_hostname_scoring_prefers_empty_nodes(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("p0").label("foo", "").obj())
        place(nodes, "node-a", make_pod("p1").label("foo", "").obj())
        place(nodes, "node-b", make_pod("p2").label("foo", "").obj())
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_HOSTNAME, "ScheduleAnyway", {"foo": ""}).obj())
        scores = self.run(pod, nodes)
        assert scores["node-x"] == scores["node-y"] == 100
        assert scores["node-b"] > scores["node-a"]

    def test_skip_without_soft_constraints(self):
        nodes = mk_cluster()
        pod = (make_pod("incoming").label("foo", "")
               .spread_constraint(1, LABEL_ZONE, "DoNotSchedule", {"foo": ""}).obj())
        pl = PodTopologySpread()
        status = pl.pre_score(CycleState(), pod, list(nodes.values()))
        assert status.is_skip()


class TestNodeTaintsPolicyHonor:
    """nodeTaintsPolicy: Honor (common.go:43-57) — tainted nodes are excluded
    from the count domains and from feasibility. Round-1 regression: this
    path crashed with a TypeError."""

    def test_honor_excludes_tainted_node(self):
        from kubernetes_tpu.api.types import (TopologySpreadConstraint,
                                              LabelSelector, Taint)
        nodes = mk_cluster()
        nodes["node-a"].node.spec.taints.append(
            Taint(key="dedicated", value="gpu", effect="NoSchedule"))
        pod = make_pod("incoming").label("foo", "").obj()
        pod.spec.topology_spread_constraints.append(TopologySpreadConstraint(
            max_skew=1, topology_key=LABEL_HOSTNAME,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector.of({"foo": ""}),
            node_taints_policy="Honor"))
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        # must not crash; tainted node-a is excluded from domains but the
        # other three hosts are feasible (0 pods everywhere → skew ok)
        assert statuses["node-b"].is_success()
        assert statuses["node-x"].is_success()
        assert statuses["node-y"].is_success()

    def test_honor_with_toleration_keeps_node(self):
        from kubernetes_tpu.api.types import (TopologySpreadConstraint,
                                              LabelSelector, Taint)
        nodes = mk_cluster()
        nodes["node-a"].node.spec.taints.append(
            Taint(key="dedicated", value="gpu", effect="NoSchedule"))
        pod = (make_pod("incoming").label("foo", "")
               .toleration(key="dedicated", operator="Equal", value="gpu",
                           effect="NoSchedule").obj())
        pod.spec.topology_spread_constraints.append(TopologySpreadConstraint(
            max_skew=1, topology_key=LABEL_HOSTNAME,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector.of({"foo": ""}),
            node_taints_policy="Honor"))
        statuses, _ = run_filter(PodTopologySpread(), pod, nodes)
        assert statuses["node-a"].is_success()
