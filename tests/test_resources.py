"""Quantity parsing + pod request flattening (reference semantics:
component-helpers resource.PodRequests, scheduler util non-zero defaults)."""

from kubernetes_tpu.api import resources as res
from kubernetes_tpu.testing.wrappers import make_pod


def test_parse_quantity_cpu():
    assert res.parse_quantity("100m", res.CPU) == 100
    assert res.parse_quantity("2", res.CPU) == 2000
    assert res.parse_quantity("1.5", res.CPU) == 1500
    assert res.parse_quantity(2, res.CPU) == 2000
    assert res.parse_quantity(0.5, res.CPU) == 500


def test_parse_quantity_memory():
    assert res.parse_quantity("1Gi", res.MEMORY) == 2**30
    assert res.parse_quantity("500Mi", res.MEMORY) == 500 * 2**20
    assert res.parse_quantity("1G", res.MEMORY) == 10**9
    assert res.parse_quantity("128", res.MEMORY) == 128
    assert res.parse_quantity(1024, res.MEMORY) == 1024


def test_pod_requests_sums_containers():
    pod = (make_pod("p").req({"cpu": "100m", "memory": "1Gi"})
           .container({"cpu": "200m", "memory": "1Gi"}).obj())
    req = res.pod_requests(pod)
    assert req[res.CPU] == 300
    assert req[res.MEMORY] == 2 * 2**30


def test_pod_requests_init_container_max():
    pod = (make_pod("p").req({"cpu": "100m"})
           .init_req({"cpu": "1"}).obj())
    req = res.pod_requests(pod)
    assert req[res.CPU] == 1000  # init max dominates


def test_pod_requests_overhead_added():
    pod = make_pod("p").req({"cpu": "100m"}).overhead({"cpu": "50m"}).obj()
    assert res.pod_requests(pod)[res.CPU] == 150


def test_nonmissing_defaults_per_container():
    # two containers, both missing requests → two sets of defaults
    pod = make_pod("p").container({}).obj()  # c0 empty + c1 empty
    req = res.pod_requests_nonmissing(pod)
    assert req[res.CPU] == 2 * res.DEFAULT_MILLI_CPU_REQUEST
    assert req[res.MEMORY] == 2 * res.DEFAULT_MEMORY_REQUEST


def test_nonmissing_defaults_partial():
    pod = make_pod("p").req({"cpu": "250m"}).obj()
    req = res.pod_requests_nonmissing(pod)
    assert req[res.CPU] == 250
    assert req[res.MEMORY] == res.DEFAULT_MEMORY_REQUEST


def test_resource_table_interning():
    t = res.ResourceTable()
    assert t.index[res.CPU] == res.CPU_IDX
    gpu = t.intern("example.com/gpu")
    assert gpu == 4
    assert t.intern("example.com/gpu") == 4
    vec = t.vector({"cpu": 500, "example.com/gpu": 2})
    assert vec[res.CPU_IDX] == 500 and vec[gpu] == 2
