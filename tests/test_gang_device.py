"""Gang placement engine (ISSUE 7): whole-gang all-or-nothing device
dispatch vs the serial Permit-barrier oracle.

The standing gates this file establishes:

- **Fuzzed parity**: for seeded random clusters + gangs, the device gang
  verdict (accept/reject) AND the accepted placements are identical to
  the serial Permit-barrier path (GangDevicePlacement off), including
  min-count-not-met and partial-feasibility rejection; the closed-form
  uniform tier and the scan tier agree with each other on the same
  scenarios.
- **Atomicity**: a rejected gang binds nothing, parks nothing and holds
  no resources; an accepted gang binds in ONE device dispatch
  (FlightRecorder run_kind=gang, zero Permit waits).
- **Gang-preempts-gang**: a higher-priority gang rejected on a full
  cluster preempts a lower-priority gang's members and lands, with the
  same end state as the serial path.
- **Chaos**: seeded API faults leave gang assignments identical to the
  fault-free run (the ISSUE 2 gate extended to gang drains).
- **Queue index**: a member-pod event re-runs PreEnqueue only for that
  gang's gated members (queue.gated_by_ref satellite).
"""

import random

from kubernetes_tpu.api.types import ObjectMeta, PodGroup, Workload
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.chaos import ChaosAPIServer, ChaosConfig
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _sched(api, device_gangs=True, batch_size=64, contig=0):
    clock = Clock()
    s = Scheduler(api, batch_size=batch_size, clock=clock)
    s.dispatcher.sleep = lambda _s: None
    s._clock = clock
    if contig:
        s.gang_contiguity_weight = contig
    if not device_gangs:
        s.feature_gates.set("GangDevicePlacement", False)
        s.gang_device_enabled = False
    return s


def _workload(api, name, min_count):
    api.create_workload(Workload(metadata=ObjectMeta(name=name),
                                 pod_groups=[PodGroup(name="workers",
                                                      min_count=min_count)]))


def _gang(api, name, size, min_count, cpu="1", priority=0):
    _workload(api, name, min_count)
    for i in range(size):
        api.create_pod(make_pod(f"{name}-{i}")
                       .req({"cpu": cpu, "memory": "1Gi"})
                       .workload(name).priority(priority).obj())


def _assignments(api):
    inner = getattr(api, "inner", api)
    return {uid: p.spec.node_name for uid, p in inner.pods.items()}


def _settle(api, sched, rounds=6):
    """Drive to a fixed point: expired gang deadlines sweep, backoffs and
    unschedulable leftovers flush, rejected gangs re-attempt."""
    sched.schedule_pending()
    for _ in range(rounds):
        sched._clock.t += 400.0
        sched.flush_queues()
        sched.schedule_pending()


# ---------------------------------------------------------------------------
# atomicity + observability


class TestGangDeviceBasics:
    def test_accept_is_one_dispatch_no_permit(self):
        api = APIServer()
        for i in range(8):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        _gang(api, "train", size=12, min_count=12)
        assert sched.schedule_pending() == 12
        gang_drains = [r for r in sched.flight.dump()
                       if "gang" in r["kinds"]]
        assert len(gang_drains) == 1 and gang_drains[0]["bound"] == 12
        assert sched.metrics.gang_dispatch.value("placed") == 1.0
        # zero Permit waits on the accept path
        assert sched.metrics.permit_wait_duration.count("allowed") == 0
        assert sched.metrics.permit_wait_duration.count("rejected") == 0
        assert not sched._waiting_pods

    def test_reject_is_atomic_and_holds_nothing(self):
        api = APIServer()
        for i in range(2):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 1, "memory": "16Gi", "pods": 110}).obj())
        sched = _sched(api)
        _gang(api, "train", size=3, min_count=3)
        assert sched.schedule_pending() == 0
        assert api.binding_count == 0
        assert not sched._waiting_pods
        assert not sched.cache.assumed_pods
        assert sched.metrics.gang_dispatch.value("rejected") == 1.0
        # the FailedScheduling surface: infeasible members carry the
        # reference-format reasons histogram; unwound members the gang
        # verdict
        msgs = [e.message for e in sched.events.events(
            reason="FailedScheduling")]
        assert any("nodes are available" in m and "Insufficient" in m
                   for m in msgs), msgs
        assert any("gang 'train' rejected" in m for m in msgs), msgs
        # freed capacity is immediately usable
        api.create_pod(make_pod("plain").req(
            {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1

    def test_min_count_partial_accept(self):
        """size 5, minCount 3, capacity 3: the gang lands (3 bind), the
        two surplus members fail individually — all in one dispatch."""
        api = APIServer()
        for i in range(3):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 1, "memory": "16Gi", "pods": 110}).obj())
        sched = _sched(api)
        _gang(api, "train", size=5, min_count=3)
        assert sched.schedule_pending() == 3
        assert sched.metrics.gang_dispatch.value("placed") == 1.0
        bound = [u for u, n in _assignments(api).items() if n]
        assert len(bound) == 3

    def test_quorum_wait_metric(self):
        api = APIServer()
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        _workload(api, "train", min_count=3)
        api.create_pod(make_pod("train-0").req(
            {"cpu": "1", "memory": "1Gi"}).workload("train").obj())
        sched._clock.t += 2.0
        api.create_pod(make_pod("train-1").req(
            {"cpu": "1", "memory": "1Gi"}).workload("train").obj())
        assert sched.metrics.gang_quorum_wait.count() == 0
        sched._clock.t += 3.0
        api.create_pod(make_pod("train-2").req(
            {"cpu": "1", "memory": "1Gi"}).workload("train").obj())
        assert sched.metrics.gang_quorum_wait.count() == 1
        assert abs(sched.metrics.gang_quorum_wait.sum() - 5.0) < 1e-6

    def test_host_port_gang_falls_back_and_still_binds(self):
        """A gang whose members carry host ports (sig 0) degrades to the
        Permit-barrier path — and still binds there."""
        api = APIServer()
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        _workload(api, "svc", min_count=3)
        for i in range(3):
            api.create_pod(make_pod(f"svc-{i}")
                           .req({"cpu": "1", "memory": "1Gi"})
                           .workload("svc").host_port(8000 + i).obj())
        assert sched.schedule_pending() == 3
        assert sched.metrics.gang_dispatch.value("fallback") >= 1.0
        assert sched.metrics.gang_dispatch.value("placed") == 0.0

    def test_contiguity_packs_topology_domains(self):
        """Tesserae-style packing: with the contiguity column live, a
        gang concentrates into fewer zones than the balance-driven
        default spreads it across."""
        def build(contig):
            api = APIServer()
            for i in range(16):
                api.create_node(make_node(f"n{i}")
                                .capacity({"cpu": 2, "memory": "32Gi",
                                           "pods": 110})
                                .zone(f"z{i % 4}").obj())
            sched = _sched(api, contig=contig)
            _gang(api, "train", size=8, min_count=8)
            assert sched.schedule_pending() == 8
            zones = set()
            for uid, node in _assignments(api).items():
                if node and uid.endswith(tuple(f"-{k}" for k in range(8))):
                    zones.add(int(node[1:]) % 4)
            return zones
        spread_zones = build(0)
        packed_zones = build(8)
        assert len(packed_zones) < len(spread_zones)
        # one zone (4 nodes × 2 cpu) holds all 8 members: perfect packing
        assert len(packed_zones) == 1


class TestGangSanitizerRails:
    def test_gang_drain_under_transfer_guard(self):
        """Both run_gang tiers are staged-entry clean: a gang drain
        completes under ambient jax.transfer_guard('disallow') with the
        SanitizerRails gate on and zero device fallbacks."""
        import jax
        for contig in (0, 2):   # closed-form tier, then scan tier
            api = APIServer()
            sched = _sched(api, contig=contig)
            sched.rails.enable(True)
            try:
                for i in range(8):
                    api.create_node(make_node(f"n{i}").capacity(
                        {"cpu": 8, "memory": "32Gi", "pods": 110})
                        .zone(f"z{i % 2}").obj())
                _gang(api, "g", size=6, min_count=6)
                with jax.transfer_guard("disallow"):
                    assert sched.schedule_pending() == 6
                assert sched.device_fallbacks == 0
                assert sched.metrics.gang_dispatch.value("placed") == 1.0
            finally:
                sched.rails.enable(False)


# ---------------------------------------------------------------------------
# queue satellite: gated-gang index


class TestGatedGangIndex:
    def _counting(self, sched):
        calls = []
        inner = sched.queue.pre_enqueue

        def counted(pod):
            calls.append(pod.uid)
            return inner(pod)
        sched.queue.pre_enqueue = counted
        return calls

    def test_member_event_reevaluates_only_its_gang(self):
        api = APIServer()
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        # two gangs below quorum: both fully gated
        _workload(api, "a", min_count=3)
        _workload(api, "b", min_count=3)
        for i in range(2):
            api.create_pod(make_pod(f"a-{i}").req(
                {"cpu": "1", "memory": "1Gi"}).workload("a").obj())
        for i in range(2):
            api.create_pod(make_pod(f"b-{i}").req(
                {"cpu": "1", "memory": "1Gi"}).workload("b").obj())
        assert sched.queue.gated_refs() == {"a", "b"}
        calls = self._counting(sched)
        # a's quorum-completing member must re-run PreEnqueue for a's
        # gated members ONLY — b's stay untouched
        api.create_pod(make_pod("a-2").req(
            {"cpu": "1", "memory": "1Gi"}).workload("a").obj())
        assert not any(uid.startswith("default/b-") for uid in calls), calls
        assert sched.queue.gated_refs() == {"b"}
        assert sched.schedule_pending() == 3

    def test_index_cleared_on_delete(self):
        api = APIServer()
        sched = _sched(api)
        _workload(api, "a", min_count=2)
        api.create_pod(make_pod("a-0").req(
            {"cpu": "1", "memory": "1Gi"}).workload("a").obj())
        assert sched.queue.gated_refs() == {"a"}
        api.delete_pod("default/a-0")
        assert sched.queue.gated_refs() == set()


# ---------------------------------------------------------------------------
# fuzzed parity vs the serial Permit-barrier oracle


def _fuzz_scenario(rng):
    """One seeded scenario: cluster + pre-bound fillers + gangs."""
    n_nodes = rng.randint(3, 16)
    cpu = rng.randint(2, 8)
    nodes = [(f"n{i}", cpu) for i in range(n_nodes)]
    bound = []
    for i in range(rng.randint(0, n_nodes)):
        node = rng.randrange(n_nodes)
        bound.append((f"pre-{i}", f"n{node}", rng.randint(1, max(cpu // 2, 1))))
    gangs = []
    for g in range(rng.randint(1, 3)):
        size = rng.randint(2, 8)
        min_count = rng.randint(1, size)
        gangs.append((f"gang{g}", size, min_count, rng.randint(1, 3)))
    return nodes, bound, gangs


def _run_fuzz(nodes, bound, gangs, device_gangs, uniform=True):
    api = APIServer()
    for name, cpu in nodes:
        api.create_node(make_node(name).capacity(
            {"cpu": cpu, "memory": "64Gi", "pods": 110}).obj())
    sched = _sched(api, device_gangs=device_gangs)
    if not uniform:
        # force the scan tier (the closed-form tier needs the gate)
        sched.feature_gates.set("OpportunisticBatching", False)
    for name, node, cpu in bound:
        api.create_pod(make_pod(name).req(
            {"cpu": cpu, "memory": "1Gi"}).node(node).obj())
    for name, size, min_count, cpu in gangs:
        _gang(api, name, size=size, min_count=min_count, cpu=str(cpu))
    _settle(api, sched)
    return api, sched


class TestGangParityFuzz:
    def test_single_gang_parity(self):
        """Device verdict + placements == serial Permit-barrier oracle,
        per seeded scenario with one gang (min-count-not-met and
        partial-feasibility rejection included by construction)."""
        mismatches = []
        rejects = accepts = 0
        for seed in range(40):
            rng = random.Random(1000 + seed)
            nodes, bound, gangs = _fuzz_scenario(rng)
            gangs = gangs[:1]
            dev_api, dev = _run_fuzz(nodes, bound, gangs, device_gangs=True)
            host_api, _ = _run_fuzz(nodes, bound, gangs, device_gangs=False)
            a, b = _assignments(dev_api), _assignments(host_api)
            if a != b:
                mismatches.append((seed, a, b))
            if dev.metrics.gang_dispatch.value("rejected"):
                rejects += 1
            if dev.metrics.gang_dispatch.value("placed"):
                accepts += 1
        assert not mismatches, mismatches[:3]
        # the fuzz must actually exercise both verdicts
        assert rejects >= 3 and accepts >= 10, (rejects, accepts)

    def test_uniform_and_scan_tiers_agree(self):
        """The closed-form tier and the scan tier are the same function:
        identical verdicts and placements on every scenario."""
        for seed in range(20):
            rng = random.Random(2000 + seed)
            nodes, bound, gangs = _fuzz_scenario(rng)
            u_api, _ = _run_fuzz(nodes, bound, gangs, device_gangs=True,
                                 uniform=True)
            s_api, _ = _run_fuzz(nodes, bound, gangs, device_gangs=True,
                                 uniform=False)
            assert _assignments(u_api) == _assignments(s_api), seed

    def test_multi_gang_decisions_match(self):
        """Several gangs per scenario: per-gang accept/reject decisions
        match the serial oracle; when every gang lands in both runs the
        placements match exactly."""
        for seed in range(25):
            rng = random.Random(3000 + seed)
            nodes, bound, gangs = _fuzz_scenario(rng)
            dev_api, dev = _run_fuzz(nodes, bound, gangs, device_gangs=True)
            host_api, _ = _run_fuzz(nodes, bound, gangs, device_gangs=False)
            a, b = _assignments(dev_api), _assignments(host_api)
            bound_a = {u for u, n in a.items() if n}
            bound_b = {u for u, n in b.items() if n}
            for name, size, min_count, _cpu in gangs:
                landed_a = sum(1 for u in bound_a
                               if u.startswith(f"default/{name}-"))
                landed_b = sum(1 for u in bound_b
                               if u.startswith(f"default/{name}-"))
                assert (landed_a >= min_count) == (landed_b >= min_count), \
                    (seed, name, landed_a, landed_b)
            if bound_a == bound_b and len(bound_a) == sum(
                    g[1] for g in gangs) + len(bound):
                assert a == b, seed


# ---------------------------------------------------------------------------
# gang preempts gang


class TestGangPreemptsGang:
    def _scenario(self, device_gangs):
        api = APIServer()
        for i in range(3):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 4, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api, device_gangs=device_gangs)
        # low-priority training gang fills the cluster
        _gang(api, "low", size=6, min_count=6, cpu="2", priority=0)
        _settle(api, sched, rounds=2)
        assert sum(1 for n in _assignments(api).values() if n) == 6
        # a higher-priority gang needs whole nodes: it must preempt
        _gang(api, "high", size=3, min_count=3, cpu="4", priority=100)
        _settle(api, sched, rounds=8)
        return api, sched

    def test_high_priority_gang_preempts_and_lands(self):
        api, sched = self._scenario(device_gangs=True)
        final = _assignments(api)
        high = [u for u, n in final.items()
                if n and u.startswith("default/high-")]
        assert len(high) == 3, final
        assert sched.preemption_attempts > 0

    def test_end_state_matches_serial_oracle(self):
        dev_api, _ = self._scenario(device_gangs=True)
        host_api, _ = self._scenario(device_gangs=False)
        dev_high = {u: n for u, n in _assignments(dev_api).items()
                    if u.startswith("default/high-") and n}
        host_high = {u: n for u, n in _assignments(host_api).items()
                     if u.startswith("default/high-") and n}
        assert len(dev_high) == len(host_high) == 3
        # the surviving low-priority members match too
        dev_low = {u for u, n in _assignments(dev_api).items()
                   if u.startswith("default/low-") and n}
        host_low = {u for u, n in _assignments(host_api).items()
                    if u.startswith("default/low-") and n}
        assert dev_low == host_low


# ---------------------------------------------------------------------------
# chaos gate: faults leave gang assignments identical


def _run_gang_chaos_workload(api):
    sched = _sched(api, batch_size=32)
    _gang(api, "train-a", size=8, min_count=8)
    sched.schedule_pending()
    _gang(api, "train-b", size=6, min_count=4, cpu="2")
    _gang(api, "too-big", size=40, min_count=40, cpu="3")  # must reject
    _settle(api, sched, rounds=3)
    return sched


class TestWorkloadGenerator:
    def test_trace_is_deterministic_and_spec_shared(self):
        from kubernetes_tpu.testing.workloads import GangWorkloadGenerator

        def shapes(seed):
            gen = GangWorkloadGenerator(seed=seed)
            specs = gen.training_gangs(5, size=(8, 64), min_count_frac=0.75)
            return [(s.size, s.min_count) for s in specs]
        assert shapes(42) == shapes(42)
        assert shapes(42) != shapes(43)
        gen = GangWorkloadGenerator(seed=1)
        spec = gen.training_gangs(1, size=16)[0]
        assert spec.min_count == 16
        pods = gen.gang_pods(spec)
        assert len(pods) == 16
        # the spec OBJECT is shared → one signature row per gang
        assert all(p.spec is pods[0].spec for p in pods)
        assert all(p.spec.workload_ref == spec.ref for p in pods)
        assert len({p.uid for p in pods}) == 16

    def test_trace_interleaves_and_streams_chunks(self):
        from kubernetes_tpu.testing.workloads import GangWorkloadGenerator
        gen = GangWorkloadGenerator(seed=3)
        specs = gen.training_gangs(3, size=8, priority=10)
        pre = gen.training_gangs(1, size=4, priority=200,
                                 prefix="preemptor")
        events = list(gen.trace(specs, inference_count=12,
                                preemptor_gangs=pre, chunk=16))
        kinds = [k for k, _ in events]
        assert kinds.count("workload") == 4
        pods = [p for k, chunk in events if k == "pods" for p in chunk]
        assert len(pods) == 3 * 8 + 12 + 4
        # preemptor gangs arrive last
        assert pods[-1].spec.workload_ref == "preemptor-0"
        assert all(len(c) <= 16 for k, c in events if k == "pods")


class TestGangChaos:
    def test_seeded_faults_leave_gang_assignments_identical(self):
        clean_api = APIServer()
        for i in range(6):
            clean_api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        _run_gang_chaos_workload(clean_api)
        clean = _assignments(clean_api)
        assert sum(1 for n in clean.values() if n) == 14

        chaos = ChaosAPIServer(config=ChaosConfig(
            seed=11,
            error_rates={"bind": 0.15, "patch": 0.15, "delete": 0.15},
            latency_rate=0.2, latency_seconds=(0.001, 0.02)))
        for i in range(6):
            chaos.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _run_gang_chaos_workload(chaos)
        assert _assignments(chaos.inner) == clean
        assert chaos.injected_errors["bind"] > 0
        assert sched.dispatcher.errors == 0
        assert not sched.cache.assumed_pods


class TestGangResyncContinuity:
    """resync() must not drop gang state (ISSUE 12 satellite): the fresh
    queue re-derives gated_by_ref, but the quorum-wait clocks and Permit
    deadlines live OUTSIDE it and must be carried across the rebuild."""

    def test_gated_gang_survives_resync_and_binds_on_quorum(self):
        """Ordering-contract guard: a half-arrived gang stays gated
        through a resync (wm registers every pod BEFORE add_bulk re-runs
        PreEnqueue), then binds the moment quorum arrives."""
        api = APIServer()
        for i in range(8):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        _workload(api, "train", min_count=8)
        for i in range(5):                     # below quorum: gates
            api.create_pod(make_pod(f"train-{i}")
                           .req({"cpu": "1", "memory": "1Gi"})
                           .workload("train").obj())
        assert sched.schedule_pending() == 0
        assert ("train", "") in {r[:2] for r in sched.queue.gated_refs()} \
            or sched.queue.gated_refs()        # still gated, shape-agnostic
        sched.resync()
        # the rebuilt queue must re-gate (not strand, not leak) the gang
        assert sched.schedule_pending() == 0
        assert all(not p.spec.node_name for p in api.pods.values())
        for i in range(5, 8):                  # quorum arrives after resync
            api.create_pod(make_pod(f"train-{i}")
                           .req({"cpu": "1", "memory": "1Gi"})
                           .workload("train").obj())
        assert sched.schedule_pending() == 8
        assert not sched.queue.gated_refs()

    def test_quorum_wait_clock_survives_resync(self):
        """The regression this satellite fixes: resync() used to rebuild
        the queue without carrying `_gang_gated_since`, silently dropping
        the gang_quorum_wait observation for any gang that ungated after
        a resync. The wait must be measured from the ORIGINAL gate time,
        not from the resync (and not lost entirely)."""
        api = APIServer()
        for i in range(8):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        _workload(api, "train", min_count=6)
        for i in range(3):
            api.create_pod(make_pod(f"train-{i}")
                           .req({"cpu": "1", "memory": "1Gi"})
                           .workload("train").obj())
        sched.schedule_pending()               # gates at t=0
        sched._clock.t = 5.0
        sched.resync()                         # mid-wait watch-loss relist
        sched._clock.t = 10.0
        for i in range(3, 6):                  # quorum: ungates at t=10
            api.create_pod(make_pod(f"train-{i}")
                           .req({"cpu": "1", "memory": "1Gi"})
                           .workload("train").obj())
        assert sched.schedule_pending() == 6
        m = sched.metrics.gang_quorum_wait
        assert m.count() == 1
        assert m.sum() >= 10.0                 # from t=0, not the resync

    def test_permit_deadline_survives_resync(self):
        """A surviving group's Permit deadline must not restart from
        zero across a resync (the reference's podGroupInfo outlives any
        one informer relist)."""
        api = APIServer()
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        sched = _sched(api)
        _gang(api, "train", size=4, min_count=4)
        # start the group's Permit clock, as the serial barrier would
        info = sched.workload_manager.pod_group_infos[
            ("default", "train", "")]
        info.scheduling_timeout(sched._clock.t)
        deadline = info.scheduling_deadline
        assert deadline is not None
        sched._clock.t = 7.0
        sched.resync()
        fresh = sched.workload_manager.pod_group_infos[
            ("default", "train", "")]
        assert fresh is not info               # the manager WAS rebuilt
        assert fresh.scheduling_deadline == deadline
