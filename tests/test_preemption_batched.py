"""Batched device preemption dry-run — parity vs the host Evaluator.

The tentpole acceptance gate: across fuzzed (cluster, preemptor) cases —
including PDB-violating victims, priority ties, spread-constrained
preemptors and pending nominations — the batched kernel
(ops/program.py dry_run_select_victims) must produce candidate lists with
victim sets IDENTICAL to the host oracle loop (framework/preemption.py
select_victims_on_node per candidate), which itself mirrors
default_preemption.go:583 + preemption.go filterPodsWithPDBViolation.
"""

import random

import pytest

from kubernetes_tpu.api.types import (LabelSelector, ObjectMeta,
                                      PodDisruptionBudget)
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.framework.types import Diagnosis, PodInfo, QueuedPodInfo
from kubernetes_tpu.plugins.defaultpreemption import DefaultPreemption
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def _evaluator(sched):
    prof = next(iter(sched.profiles.values()))
    dp = next(p for p in prof.framework.plugins
              if isinstance(p, DefaultPreemption))
    return dp._evaluator


def _canon(candidates):
    return [(c.node_name, [pi.pod.uid for pi in c.victims],
             c.num_pdb_violations) for c in candidates]


def _run_both(sched, pod, require_batched=True):
    """dry_run via the batched kernel AND the host loop; returns both."""
    sched.cache.update_snapshot(sched.snapshot)
    nodes = sched.snapshot.node_info_list
    ev = _evaluator(sched)
    diagnosis = Diagnosis()
    potential = ev.nodes_where_preemption_might_help(nodes, diagnosis)
    num = ev.get_num_candidates(len(potential))
    pdbs = ev.pdb_lister() if ev.pdb_lister is not None else []
    batched = ev._dry_run_batched(pod, potential, num, nodes, pdbs)
    if require_batched:
        assert batched is not None, "case unexpectedly fell back to host"
    ctx, ev.device_ctx = ev.device_ctx, None
    try:
        host = ev.dry_run_preemption(CycleState(), pod, potential, num,
                                     all_nodes=nodes)
    finally:
        ev.device_ctx = ctx
    return batched, host


def _fuzz_cluster(rng, spread=False, pdb=False, nominate=False):
    api = APIServer()
    sched = Scheduler(api, batch_size=64)
    n_nodes = rng.randint(3, 8)
    zones = rng.randint(1, 3)
    for i in range(n_nodes):
        api.create_node(
            make_node(f"n{i}")
            .capacity({"cpu": rng.choice([4, 6, 8]), "memory": "16Gi",
                       "pods": rng.choice([4, 110])})
            .zone(f"z{i % zones}")
            .obj())
    # bound pods: random priorities WITH ties, random sizes, some labeled
    uid = 0
    for i in range(n_nodes):
        for _ in range(rng.randint(0, 4)):
            w = make_pod(f"p{uid}").req(
                {"cpu": str(rng.choice([1, 2, 3])), "memory": "1Gi"})
            w = w.priority(rng.choice([0, 0, 5, 5, 10, 50]))
            if rng.random() < 0.6:
                w = w.label("app", rng.choice(["a", "b"]))
            if spread and rng.random() < 0.6:
                w = w.label("sp", "yes")
            p = w.obj()
            api.create_pod(p)
            api.bind(p, f"n{i}")
            uid += 1
    if pdb:
        for j, sel in enumerate(rng.sample([{"app": "a"}, {"app": "b"},
                                            {"app": "a"}], rng.randint(1, 2))):
            api.create_pdb(PodDisruptionBudget(
                metadata=ObjectMeta(name=f"pdb{j}"),
                selector=LabelSelector.of(match_labels=sel),
                min_available=rng.choice([1, 2, "50%", "100%"])))
    # the preemptor: mid priority so some pods are victims and some not
    w = make_pod("preemptor").req(
        {"cpu": str(rng.choice([2, 4, 6])), "memory": "2Gi"}).priority(
            rng.choice([7, 20, 100]))
    if spread:
        w = w.label("sp", "yes").spread_constraint(
            rng.choice([1, 2]), ZONE, "DoNotSchedule", {"sp": "yes"})
    preemptor = w.obj()
    if nominate:
        # a pending ≥-priority nomination occupies part of a node
        nom = make_pod("nominated").req({"cpu": "2", "memory": "1Gi"}) \
            .priority(200).obj()
        qpi = QueuedPodInfo(pod_info=PodInfo.of(nom))
        sched.queue.nominator.add(qpi, f"n{rng.randrange(n_nodes)}")
    return api, sched, preemptor


class TestBatchedParity:
    @pytest.mark.parametrize("seed", range(80))
    def test_basic_parity(self, seed):
        rng = random.Random(seed)
        api, sched, pod = _fuzz_cluster(rng)
        batched, host = _run_both(sched, pod)
        assert _canon(batched) == _canon(host)

    @pytest.mark.parametrize("seed", range(80, 140))
    def test_pdb_parity(self, seed):
        rng = random.Random(seed)
        api, sched, pod = _fuzz_cluster(rng, pdb=True)
        batched, host = _run_both(sched, pod)
        assert _canon(batched) == _canon(host)

    @pytest.mark.parametrize("seed", range(140, 190))
    def test_spread_parity(self, seed):
        rng = random.Random(seed)
        api, sched, pod = _fuzz_cluster(rng, spread=True,
                                        pdb=rng.random() < 0.3)
        batched, host = _run_both(sched, pod)
        assert _canon(batched) == _canon(host)

    @pytest.mark.parametrize("seed", range(190, 230))
    def test_nominated_overlay_parity(self, seed):
        rng = random.Random(seed)
        api, sched, pod = _fuzz_cluster(rng, nominate=True,
                                        pdb=rng.random() < 0.3)
        batched, host = _run_both(sched, pod)
        assert _canon(batched) == _canon(host)

    def test_priority_tie_exact_order(self):
        """Victims with equal priority reprieve in creation order; the
        kernel must reproduce the host's exact victim LIST, not just the
        set."""
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 6, "memory": "16Gi", "pods": 110}).obj())
        for i in range(3):
            p = make_pod(f"tie{i}").req({"cpu": "2", "memory": "1Gi"}) \
                .priority(5).obj()
            api.create_pod(p)
            api.bind(p, "n0")
        pod = make_pod("vip").req({"cpu": "4", "memory": "1Gi"}) \
            .priority(50).obj()
        batched, host = _run_both(sched, pod)
        assert _canon(batched) == _canon(host)
        # earliest-started tie pods are reprieved last → evicted
        assert len(batched[0][1] if isinstance(batched[0], tuple)
                   else batched[0].victims) == 2

    def test_fallback_cases_use_host_loop(self):
        """Preemptors with pod anti-affinity have no tensor form: the
        batched path must decline (return None), not guess."""
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        p = make_pod("low").req({"cpu": "4", "memory": "1Gi"}).obj()
        api.create_pod(p)
        api.bind(p, "n0")
        pod = make_pod("vip").req({"cpu": "4", "memory": "1Gi"}) \
            .priority(50).label("x", "y") \
            .pod_affinity(ZONE, {"x": "y"}, anti=True).obj()
        sched.cache.update_snapshot(sched.snapshot)
        ev = _evaluator(sched)
        nodes = sched.snapshot.node_info_list
        got = ev._dry_run_batched(pod, nodes, 10, nodes, [])
        assert got is None
        # and the full dry run still works through the host loop
        host = ev.dry_run_preemption(CycleState(), pod, nodes, 10,
                                     all_nodes=nodes)
        assert [c.node_name for c in host] == ["n0"]

    def test_end_to_end_uses_batched_path(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(3):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        for i in range(3):
            api.create_pod(make_pod(f"low{i}").req(
                {"cpu": "4", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 3
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()
        ev = _evaluator(sched)
        assert ev.batched_dry_runs == 1
        assert ev.host_dry_runs == 0
        assert api.pods["default/vip"].status.nominated_node_name != ""


class TestPDBRegression:
    """The two PDB divergences fixed to match preemption.go / the
    disruption controller."""

    def _pdb(self, name, labels, min_available=None, allowed=None):
        pdb = PodDisruptionBudget(
            metadata=ObjectMeta(name=name),
            selector=LabelSelector.of(match_labels=labels),
            min_available=min_available)
        if allowed is not None:
            pdb.disruptions_allowed = allowed
        return pdb

    def test_violating_pod_still_consumes_other_budgets(self):
        """filterPodsWithPDBViolation decrements EVERY matching PDB for
        EVERY pod: a pod violating PDB A still consumes PDB B's budget,
        so a later B-only pod is classified violating too."""
        from kubernetes_tpu.framework.preemption import Evaluator
        pdb_a = self._pdb("a", {"app": "a"}, allowed=0)
        pdb_b = self._pdb("b", {"grp": "g"}, allowed=1)
        p0 = PodInfo.of(make_pod("p0").label("app", "a")
                        .label("grp", "g").obj())
        p1 = PodInfo.of(make_pod("p1").label("grp", "g").obj())
        violating, ok = Evaluator._filter_pods_with_pdb_violation(
            [p0, p1], [pdb_a, pdb_b])
        # p0 violates A (0 → −1) and consumes B (1 → 0); p1 then pushes
        # B to −1 → violating as well. The old code reprieved p1 first.
        assert [pi.pod.name for pi in violating] == ["p0", "p1"]
        assert ok == []

    def test_min_available_percent_rounds_up(self):
        """"50%" of 3 pods protects ceil(1.5) = 2 (the disruption
        controller's GetScaledValueFromIntOrPercent roundUp=true)."""
        api = APIServer()
        api.create_node(make_node("n0").capacity(
            {"cpu": 16, "memory": "32Gi", "pods": 10}).obj())
        for i in range(3):
            p = make_pod(f"a{i}").label("app", "a").obj()
            api.create_pod(p)
            api.bind(p, "n0")
        api.create_pdb(self._pdb("pct", {"app": "a"}, min_available="50%"))
        allowed = {p.name: p.disruptions_allowed for p in api.list_pdbs()}
        assert allowed == {"pct": 1}   # floor would overstate it as 2


class TestOverlayCarryInvalidation:
    def test_nomination_change_invalidates_sig_cache(self):
        """ADVICE r5 high: a nomination arriving between two same-signature
        drains must zero the resident SigCache — otherwise the second
        drain reuses fit_ok computed WITHOUT the overlay and a pod steals
        the capacity reserved for the preemptor."""
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(2):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        api.create_pod(make_pod("a1").req({"cpu": "4", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1   # warm carry, sig cached
        # a preemptor nomination lands on the still-free node — through the
        # nominator only, which does NOT invalidate the device carry
        nom = make_pod("vip").req({"cpu": "4", "memory": "1Gi"}) \
            .priority(100).obj()
        free_node = "n1" if api.pods["default/a1"].spec.node_name == "n0" \
            else "n0"
        sched.queue.nominator.add(QueuedPodInfo(pod_info=PodInfo.of(nom)),
                                  free_node)
        # same-signature pod: with a stale SigCache it would reuse the
        # overlay-free fit_ok and bind onto the nominated node
        api.create_pod(make_pod("a2").req({"cpu": "4", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        assert api.pods["default/a2"].spec.node_name == ""

    def test_handle_failure_drains_pending_before_preemption(self):
        sched = Scheduler(APIServer(), batch_size=64)
        calls = []
        sched._drain_pending = lambda: calls.append(True)
        sched._pending.append(object())
        from kubernetes_tpu.framework.types import FitError
        qpi = QueuedPodInfo(pod_info=PodInfo.of(make_pod("x").obj()))
        sched._handle_failure(qpi, FitError(qpi.pod, 1))
        assert calls, "_handle_failure must quiesce the pipeline first"
