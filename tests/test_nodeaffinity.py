"""NodeAffinity filter/score + framework runtime schedule_pod oracle tests."""

from kubernetes_tpu.framework.interface import Code, CycleState
from kubernetes_tpu.framework.runtime import Framework, schedule_pod
from kubernetes_tpu.framework.types import FitError, NodeInfo
from kubernetes_tpu.plugins import noderesources as nr
from kubernetes_tpu.plugins.node_basics import (NodeName, NodePorts,
                                                NodeUnschedulable,
                                                TaintToleration)
from kubernetes_tpu.plugins.nodeaffinity import NodeAffinity
from kubernetes_tpu.testing.wrappers import make_node, make_pod

import pytest


def ni(node):
    return NodeInfo(node=node)


class TestNodeAffinityFilter:
    def test_node_selector_map(self):
        p = NodeAffinity()
        pod = make_pod().node_selector({"disktype": "ssd"}).obj()
        good = ni(make_node("a").label("disktype", "ssd").obj())
        bad = ni(make_node("b").label("disktype", "hdd").obj())
        assert p.filter(CycleState(), pod, good).is_success()
        assert p.filter(CycleState(), pod, bad).code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_required_affinity_in(self):
        p = NodeAffinity()
        pod = make_pod().node_affinity_in("zone", ["z1", "z2"]).obj()
        assert p.filter(CycleState(), pod, ni(make_node("a").label("zone", "z2").obj())).is_success()
        assert not p.filter(CycleState(), pod, ni(make_node("b").label("zone", "z3").obj())).is_success()

    def test_prefilter_metadata_name_shortcut(self):
        from kubernetes_tpu.api.types import (LabelSelectorRequirement,
                                              NodeSelector, NodeSelectorTerm,
                                              Affinity, NodeAffinity as NA)
        p = NodeAffinity()
        term = NodeSelectorTerm(match_fields=(
            LabelSelectorRequirement("metadata.name", "In", ("n1",)),))
        pod = make_pod().obj()
        pod.spec.affinity = Affinity(node_affinity=NA(required=NodeSelector((term,))))
        result, st = p.pre_filter(CycleState(), pod, [])
        assert st.is_success()
        assert result.node_names == {"n1"}

    def test_preferred_scoring(self):
        p = NodeAffinity()
        pod = make_pod().preferred_node_affinity_in("zone", ["z1"], 10).obj()
        cs = CycleState()
        p.pre_score(cs, pod, [])
        s1, _ = p.score(cs, pod, ni(make_node("a").label("zone", "z1").obj()))
        s2, _ = p.score(cs, pod, ni(make_node("b").label("zone", "z2").obj()))
        assert (s1, s2) == (10, 0)


def default_framework() -> Framework:
    """The default plugin set (reference v1/default_plugins.go:30-93 weights:
    TaintToleration 3, NodeAffinity 2, NodeResourcesFit 1, Balanced 1)."""
    return Framework("default-scheduler", [
        NodeUnschedulable(), NodeName(), TaintToleration(), NodeAffinity(),
        NodePorts(), nr.Fit(), nr.BalancedAllocation(),
    ], weights={"TaintToleration": 3, "NodeAffinity": 2,
                "NodeResourcesFit": 1, "NodeResourcesBalancedAllocation": 1})


class TestSchedulePod:
    def test_picks_least_allocated(self):
        fwk = default_framework()
        nodes = [ni(make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj())
                 for i in range(3)]
        from kubernetes_tpu.framework.types import PodInfo
        nodes[0].add_pod(PodInfo.of(make_pod().req({"cpu": "3"}).obj()))
        nodes[2].add_pod(PodInfo.of(make_pod().req({"cpu": "1"}).obj()))
        pod = make_pod().req({"cpu": "1", "memory": "1Gi"}).obj()
        result = schedule_pod(fwk, CycleState(), pod, nodes)
        assert result.suggested_host == "n1"  # emptiest node
        assert result.feasible_nodes == 3

    def test_fit_error_when_no_node_fits(self):
        fwk = default_framework()
        nodes = [ni(make_node("n0").capacity({"cpu": "1"}).obj())]
        pod = make_pod().req({"cpu": "8"}).obj()
        with pytest.raises(FitError) as err:
            schedule_pod(fwk, CycleState(), pod, nodes)
        assert "NodeResourcesFit" in err.value.diagnosis.unschedulable_plugins

    def test_taint_weight_dominates(self):
        fwk = default_framework()
        # n0 empty but has PreferNoSchedule taint; n1 half full.
        n0 = ni(make_node("n0").capacity({"cpu": "4", "memory": "8Gi"})
                .taint("k", "v", "PreferNoSchedule").obj())
        n1 = ni(make_node("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
        from kubernetes_tpu.framework.types import PodInfo
        n1.add_pod(PodInfo.of(make_pod().req({"cpu": "2", "memory": "4Gi"}).obj()))
        pod = make_pod().req({"cpu": "1", "memory": "2Gi"}).obj()
        result = schedule_pod(fwk, CycleState(), pod, [n0, n1])
        # TaintToleration: n0 → 0, n1 → 100, weighted ×3 dominates the
        # LeastAllocated advantage of the empty node.
        assert result.suggested_host == "n1"

    def test_single_feasible_short_circuit(self):
        fwk = default_framework()
        nodes = [ni(make_node("n0").obj()),
                 ni(make_node("n1").unschedulable().obj())]
        pod = make_pod().req({"cpu": "1"}).obj()
        result = schedule_pod(fwk, CycleState(), pod, nodes)
        assert result.suggested_host == "n0"
        assert result.feasible_nodes == 1
