"""Pod-journey tracing + telemetry timeline (ISSUE 13).

Gates this file establishes:

- the e2e SLI clock bugfix: the queue→bind SLI clock starts at the
  pod's FIRST enqueue and survives bind-error requeues and `resync()` —
  the regressions that previously restarted it (a fresh QueuedPodInfo
  minted `initial_attempt_timestamp=now`) now have standing tests;
- journey ↔ EventRecorder causality: every Scheduled event has a
  matching assign→bind_confirm journey, every FailedScheduling event a
  fit_error→requeue journey, and per-pod transitions are causally
  ordered (fuzzed over seeded workloads);
- the ISSUE 13 acceptance line: a pod fence-unwound by a stale-
  generation flush and re-bound under the new generation renders its
  FULL lifecycle — including the `fence_unwind` requeue cause — through
  `/debug/pod?uid=`, served over HTTP;
- the timeline ring: per-second buckets, horizon eviction, SLO stamping
  on close, `series()` and both exporters (streaming JSON-lines +
  `to_jsonl`), and the `/debug/timeline` + `/debug/cluster` endpoints;
- gate independence: `PodJourneyTracing=false` stops transition
  recording but the e2e clock (and the SLI fix) stay on;
- the ≤5% journey-overhead gate at 5k nodes (slow; the PR 5
  profiler-gate shape).
"""

import json
import random
import time
import urllib.request

import pytest

from kubernetes_tpu.backend.apiserver import APIServer, Conflict
from kubernetes_tpu.config import KubeSchedulerConfiguration
from kubernetes_tpu.events import REASON_FAILED_SCHEDULING, REASON_SCHEDULED
from kubernetes_tpu.ha import LeaderElector, fence_dispatcher
from kubernetes_tpu.obs.journey import CAUSES, EVENTS, SEGMENTS
from kubernetes_tpu.obs.timeline import Timeline
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.server import SchedulerServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _no_sleep(sched):
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _nodes(api, n=6, cpu=16, mem="32Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _pod_specs(n, seed, prefix="p"):
    rng = random.Random(seed)
    return [(f"{prefix}{i}", 250 * rng.randint(1, 6), 512 * rng.randint(1, 4))
            for i in range(n)]


def _create(api, specs):
    for name, cpu, mem in specs:
        api.create_pod(make_pod(name)
                       .req({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}).obj())


def _drive_to_quiescence(api, sched, clock, want_bound, max_rounds=60):
    for _ in range(max_rounds):
        sched.schedule_pending()
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if bound >= want_bound:
            return
        clock.t += 10.0
        sched.flush_queues()
    raise AssertionError(f"did not quiesce: {want_bound} wanted, "
                         f"pending={sched.pending_summary()}")


def _events_of(journey, uid):
    return [tr["event"] for tr in journey.pod(uid)["transitions"]]


def _fail_binds_once(api, n_failures=1):
    """Monkeypatch bind_all to terminally fail (Conflict) the first
    `n_failures` flushes — the deterministic bind-error → requeue path."""
    real = api.bind_all
    state = {"left": n_failures}

    def flaky(pairs, **kw):
        if state["left"] > 0:
            state["left"] -= 1
            return [(pod, Conflict("injected bind conflict"))
                    for pod, _orig in pairs]
        return real(pairs, **kw)

    api.bind_all = flaky
    return state


class TestE2EClock:
    def test_bind_error_requeue_keeps_first_enqueue_clock(self):
        """THE regression this PR fixes: a bind error mints a fresh
        QueuedPodInfo — its SLI clock must still be the pod's FIRST
        enqueue time, not the requeue time."""
        api = APIServer()
        clock = Clock(t=5.0)
        sched = _no_sleep(Scheduler(api, batch_size=32, clock=clock))
        _nodes(api, n=2)
        api.create_pod(make_pod("w0").req(
            {"cpu": "500m", "memory": "512Mi"}).obj())   # enqueued at t=5
        _fail_binds_once(api)
        clock.t = 17.0
        sched.schedule_pending()                          # bind fails at 17
        uid = next(iter(api.pods))
        assert not api.pods[uid].spec.node_name
        # the requeued QPI sits in backoff with the ORIGINAL clock
        qpi = sched.queue.backoff_q._items[uid]
        assert qpi.initial_attempt_timestamp == 5.0
        assert sched.journey.e2e_start(uid) == 5.0
        # journey renders the requeue with its cause
        requeues = [tr for tr in sched.journey.pod(uid)["transitions"]
                    if tr["event"] == "requeue"]
        assert requeues and requeues[0]["detail"].startswith("bind_error")
        clock.t = 80.0
        sched.flush_queues()
        sched.schedule_pending()                          # binds at 80
        assert api.pods[uid].spec.node_name
        # SLI observations: ~12s (first attempt) + ~75s (full span).
        # A clock restarted at the requeue would observe ~63s instead.
        total = sum(sched.metrics.sli_duration._sums.values())
        assert total >= (17.0 - 5.0) + (80.0 - 5.0) - 1e-6
        # confirm dropped the per-pod clocks
        assert sched.journey.e2e_start(uid) is None

    def test_resync_rebuild_keeps_first_enqueue_clock(self):
        """resync() rebuilds the whole queue from a LIST: known unbound
        pods keep their first-enqueue clock and get a `resync` requeue
        transition; pods first discovered BY the LIST count as fresh
        enqueues, not requeues."""
        api = APIServer()
        clock = Clock(t=5.0)
        sched = _no_sleep(Scheduler(api, batch_size=32, clock=clock))
        _nodes(api, n=2, cpu=2)
        api.create_pod(make_pod("big").req(   # 3 cpu > any node: stranded
            {"cpu": "3000m", "memory": "1Gi"}).obj())
        clock.t = 9.0
        sched.schedule_pending()
        # a watch loss swallows `late`'s add event: the pod exists in the
        # store but the scheduler first discovers it via resync's LIST
        handlers, api.pod_handlers = api.pod_handlers, []
        api.create_pod(make_pod("late").req(
            {"cpu": "250m", "memory": "256Mi"}).obj())
        api.pod_handlers = handlers
        uid_big = "default/big"
        uid_late = "default/late"
        clock.t = 30.0
        sched.resync()
        for uid, t0 in ((uid_big, 5.0), (uid_late, 30.0)):
            got = (sched.queue.active_q._items.get(uid)
                   or sched.queue.backoff_q._items.get(uid)
                   or sched.queue.unschedulable_pods.get(uid))
            assert got is not None and got.initial_attempt_timestamp == t0
        assert any(tr["event"] == "requeue" and tr["detail"] == "resync"
                   for tr in sched.journey.pod(uid_big)["transitions"])
        assert not any(tr["event"] == "requeue"
                       for tr in sched.journey.pod(uid_late)["transitions"])
        assert sched.metrics.pod_requeues.value("resync") == 1
        # capacity finally shows up: both bind, and big's SLI spans from
        # its t=5 FIRST enqueue, not the resync rebuild
        clock.t = 35.0
        api.create_node(make_node("roomy").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 80}).obj())
        _drive_to_quiescence(api, sched, clock, want_bound=2)
        assert sum(sched.metrics.sli_duration._sums.values()) >= 30.0


class TestJourneyVsEventRecorder:
    def test_causality_fuzz_against_event_recorder(self):
        """Every EventRecorder decision has a matching, causally-ordered
        journey: Scheduled ⇒ pop ≤ assign ≤ bind_enqueue ≤ bind_flush ≤
        bind_confirm; FailedScheduling ⇒ fit_error + an `unschedulable`
        requeue. Fuzzed over seeded mixed workloads with stranded pods."""
        for seed in (3, 11, 29):
            api = APIServer()
            clock = Clock(t=1.0)
            sched = _no_sleep(Scheduler(api, batch_size=16, clock=clock))
            _nodes(api, n=4, cpu=8, mem="16Gi")
            _create(api, _pod_specs(18, seed=seed))
            for i in range(4):   # oversize: can never fit → FailedScheduling
                api.create_pod(make_pod(f"huge{i}").req(
                    {"cpu": "64", "memory": "128Gi"}).obj())
            sched.schedule_pending()
            clock.t += 10.0
            sched.flush_queues()
            sched.schedule_pending()

            scheduled = sched.events.events(reason=REASON_SCHEDULED)
            failed = sched.events.events(reason=REASON_FAILED_SCHEDULING)
            assert scheduled and failed
            for ev in scheduled:
                names = _events_of(sched.journey, ev.object_ref)
                assert names[0] == "enqueue"
                for a, b in (("pop", "assign"), ("assign", "bind_enqueue"),
                             ("bind_enqueue", "bind_flush"),
                             ("bind_flush", "bind_confirm")):
                    assert names.index(a) < names.index(b), (
                        f"{ev.object_ref}: {names}")
            for ev in failed:
                j = sched.journey.pod(ev.object_ref)
                names = [tr["event"] for tr in j["transitions"]]
                assert "fit_error" in names
                causes = [tr["detail"].split(":")[0]
                          for tr in j["transitions"]
                          if tr["event"] == "requeue"]
                assert causes and set(causes) <= set(CAUSES)
            # transitions are append-ordered ⇒ per-pod timestamps are
            # monotone; every event name is a known EVENTS member
            for uid in api.pods:
                trs = sched.journey.pod(uid)["transitions"]
                ts = [tr["t"] for tr in trs]
                assert ts == sorted(ts)
                assert all(tr["event"] in EVENTS for tr in trs)
            # e2e clocks live exactly for the pods still unbound
            unbound = sum(1 for p in api.pods.values()
                          if not p.spec.node_name)
            assert sched.journey.stats()["trackedPods"] == unbound


class TestDebugPodAcceptance:
    def test_fence_unwound_rebound_pod_renders_full_lifecycle(self):
        """ISSUE 13 acceptance: a pod assumed under generation 1 whose
        delayed flush is fenced (the lease was stolen and re-acquired in
        between) unwinds with a `fence_unwind` requeue, re-binds under
        the new generation, and /debug/pod?uid= serves the whole causal
        chain over HTTP."""
        api = APIServer()
        _nodes(api)
        clock = Clock()
        sched = _no_sleep(Scheduler(api, batch_size=32, clock=clock))
        el = LeaderElector(api, "sched-a", clock=clock,
                           metrics=sched.metrics)
        fence_dispatcher(sched.dispatcher, el)
        assert el.tick() is True                    # generation 1
        sched.prime()
        _create(api, _pod_specs(6, seed=100, prefix="w"))
        # assume + enqueue WITHOUT flushing (the zombie's limbo window)
        qpis = sched.queue.drain(32)
        sched._schedule_batch(qpis)
        sched._drain_pending()
        assert len(sched.dispatcher) > 0
        # a rival steals the expired lease (gen 2), then WE re-acquire
        # (gen 3): same scheduler, two generations apart
        rival = LeaderElector(api, "sched-b", clock=clock)
        clock.t = 20.0
        assert rival.tick() is True
        clock.t = 40.0
        assert el.tick() is True
        assert el.fence_token() == 3
        # the delayed flush carries generation 1 → fenced wholesale
        sched.dispatcher.flush()
        assert api.fenced_rejections > 0
        assert all(not p.spec.node_name for p in api.pods.values())
        assert sched.metrics.pod_requeues.value("fence_unwind") == 6
        # re-bind under generation 3
        _drive_to_quiescence(api, sched, clock, want_bound=6)

        srv = SchedulerServer(sched).start()
        try:
            uid = "default/w0"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/pod?uid={uid}",
                    timeout=5) as r:
                assert r.status == 200
                out = json.loads(r.read().decode())
        finally:
            srv.stop()
        names = [tr["event"] for tr in out["transitions"]]
        assert names[0] == "enqueue"
        requeues = [tr for tr in out["transitions"]
                    if tr["event"] == "requeue"]
        assert len(requeues) == 1
        assert requeues[0]["detail"].startswith("fence_unwind")
        # the second bind attempt completes AFTER the unwind
        assert names.index("bind_confirm") > names.index("requeue")
        assert names.count("assign") == 2          # bound, unwound, re-bound
        assert set(out["segments"]) == set(SEGMENTS)
        assert out["segments"]["queue_wait"] >= 0.0

    def test_debug_pod_param_and_error_paths(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=8)
        srv = SchedulerServer(sched).start()
        try:
            def get(path):
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{srv.port}{path}",
                            timeout=5) as r:
                        return r.status, r.read().decode()
                except urllib.error.HTTPError as e:
                    return e.code, e.read().decode()

            assert get("/debug/pod")[0] == 400
            assert get("/debug/pod?uid=default/ghost")[0] == 404
            sched.journey.enabled = False
            code, body = get("/debug/pod?uid=default/ghost")
            assert code == 404 and "PodJourneyTracing" in body
        finally:
            srv.stop()


class TestTimeline:
    def test_buckets_series_and_horizon(self):
        slo_calls = []
        tl = Timeline(horizon=3, clock=lambda: 0.0,
                      slo_sample=lambda: (slo_calls.append(1) or {"s": 1}))
        tl.bump(1.2, "binds", 3)
        tl.segment(1.5, "drain", 0.5, 2)
        tl.segment(1.9, "drain", 0.3, 1)
        tl.requeue(2.1, "resync")
        tl.requeue(2.2, "resync")
        s = tl.series(seconds=60)
        assert s["segments"] == list(SEGMENTS)
        assert [b["t"] for b in s["buckets"]] == [1, 2]
        b1, b2 = s["buckets"]
        assert b1["binds"] == 3 and b1["e2e"]["drain"] == [0.8, 3]
        assert b2["requeues"] == {"resync": 2}
        # closing bucket 1 stamped an SLO sample exactly once
        assert b1["slo"] == {"s": 1} and len(slo_calls) == 1
        # horizon eviction: only the newest `horizon` buckets survive
        for sec in range(3, 9):
            tl.bump(float(sec), "pops", 1)
        assert len(tl.series(seconds=100)["buckets"]) <= 3

    def test_jsonl_exporters(self, tmp_path):
        stream = tmp_path / "stream.jsonl"
        tl = Timeline(horizon=100, clock=lambda: 0.0,
                      export_path=str(stream))
        for sec in range(4):
            tl.bump(float(sec), "binds", sec + 1)
        lines = [json.loads(ln) for ln
                 in stream.read_text().splitlines()]
        assert [b["t"] for b in lines] == [0, 1, 2]   # closed buckets only
        dump = tmp_path / "dump.jsonl"
        assert tl.to_jsonl(str(dump)) == 4
        assert len(dump.read_text().splitlines()) == 4
        # a broken sink disables the exporter instead of spinning
        tl2 = Timeline(horizon=10, clock=lambda: 0.0,
                       export_path=str(tmp_path / "no" / "dir" / "x.jsonl"))
        tl2.bump(0.0, "binds")
        tl2.bump(1.0, "binds")
        assert tl2.export_path == ""

    def test_scheduler_timeline_and_cluster_endpoints(self):
        api = APIServer()
        clock = Clock(t=1.0)
        sched = _no_sleep(Scheduler(api, batch_size=16, clock=clock))
        _nodes(api, n=3)
        _create(api, _pod_specs(8, seed=5))
        sched.schedule_pending()
        srv = SchedulerServer(sched).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/timeline?seconds=9",
                    timeout=5) as r:
                tl = json.loads(r.read().decode())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/cluster",
                    timeout=5) as r:
                cl = json.loads(r.read().decode())
        finally:
            srv.stop()
        assert tl["buckets"] and tl["causes"] == list(CAUSES)
        bucket = tl["buckets"][-1]
        assert bucket["binds"] == 8 and bucket["pops"] >= 8
        assert "queue_wait" in bucket["e2e"]
        # the probe snapshot rode the drain and resolved at commit
        assert cl["probeEnabled"] is True
        probe = cl["probe"]
        assert probe and probe["validNodes"] == 3
        assert set(probe["resources"]["cpu"]) == {
            "p50", "p90", "p99", "max", "mean", "frag", "stranded"}
        assert probe["domains"]["domains"] >= 1.0
        assert cl["journey"]["transitions"] > 0
        assert bucket["probe"] == probe


class TestFeatureGates:
    def test_journey_gate_off_keeps_e2e_clock_on(self):
        cfg = KubeSchedulerConfiguration(feature_gates={
            "PodJourneyTracing": False, "ClusterStateProbe": False,
            "TelemetryTimeline": False})
        api = APIServer()
        clock = Clock(t=5.0)
        sched = _no_sleep(Scheduler(api, batch_size=8, clock=clock,
                                    config=cfg))
        _nodes(api, n=2)
        api.create_pod(make_pod("w0").req(
            {"cpu": "500m", "memory": "512Mi"}).obj())
        _fail_binds_once(api)
        clock.t = 17.0
        sched.schedule_pending()
        uid = next(iter(api.pods))
        # no transitions recorded, no timeline buckets, no probe…
        assert sched.journey.stats()["transitions"] == 0
        assert not sched.timeline.series(seconds=60)["buckets"]
        assert sched._last_probe is None
        # …but the SLI bugfix holds regardless of the gate
        assert (sched.queue.backoff_q._items[uid]
                .initial_attempt_timestamp == 5.0)
        clock.t = 80.0
        sched.flush_queues()
        sched.schedule_pending()
        assert api.pods[uid].spec.node_name
        total = sum(sched.metrics.sli_duration._sums.values())
        assert total >= (17.0 - 5.0) + (80.0 - 5.0) - 1e-6


@pytest.mark.slow
class TestJourneyOverheadGate:
    def test_overhead_within_5_percent_at_5k_nodes(self):
        """ISSUE 13 acceptance: SchedulingBasic-shaped 5k-node drains
        with PodJourneyTracing+TelemetryTimeline+ClusterStateProbe ON
        stay within 5% of gates-OFF throughput (median of 3 measured
        passes each, warm shapes — the PR 5 profiler-gate shape)."""

        def _feed(api, n, start=0):
            api.create_pods([make_pod(f"p{start + i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj() for i in range(n)])

        def one_pass(gate_on):
            cfg = KubeSchedulerConfiguration(feature_gates={
                "PodJourneyTracing": gate_on,
                "TelemetryTimeline": gate_on,
                "ClusterStateProbe": gate_on})
            api = APIServer()
            sched = Scheduler(api, batch_size=8192, config=cfg)
            for i in range(5000):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
            sched.prime()
            t0 = time.perf_counter()
            created = 0
            while created < 10000:
                _feed(api, 512, start=created)
                created += 512
                sched.schedule_pending(wait=False)
            sched.schedule_pending()
            dt = time.perf_counter() - t0
            assert sched.scheduled_count == created
            return created / dt

        one_pass(True)    # warm every executable outside the measurement
        off = sorted(one_pass(False) for _ in range(3))[1]
        on = sorted(one_pass(True) for _ in range(3))[1]
        assert on >= 0.95 * off, (
            f"journey overhead gate: on={on:.0f} off={off:.0f} pods/s "
            f"({on / off - 1:+.1%})")
