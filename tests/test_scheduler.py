"""End-to-end Scheduler tests: API server → watch → queue → device batch /
host fallback → assume → async bind → informer confirm.

Models the reference's integration tier (test/integration/scheduler/): real
scheduler wiring, in-process API server, nodes as bare API objects."""

import numpy as np

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def mk(n_nodes=4, **kw):
    api = APIServer()
    sched = Scheduler(api, **kw)
    for i in range(n_nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 110}).zone(f"z{i % 2}")
            .label("kubernetes.io/hostname", f"n{i}").obj())
    return api, sched


class TestBatchPath:
    def test_schedules_everything(self):
        api, sched = mk()
        for i in range(20):
            api.create_pod(make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"}).obj())
        bound = sched.schedule_pending()
        assert bound == 20
        assert api.binding_count == 20
        assert all(p.spec.node_name for p in api.pods.values())
        assert sched.device_batches >= 1
        assert sched.host_scheduled == 0

    def test_balanced_spread(self):
        api, sched = mk(n_nodes=4)
        for i in range(16):
            api.create_pod(make_pod(f"p{i}").req({"cpu": "1"}).obj())
        sched.schedule_pending()
        per_node = {}
        for p in api.pods.values():
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert sorted(per_node.values()) == [4, 4, 4, 4]

    def test_unschedulable_parks_then_node_add_rescues(self):
        api, sched = mk(n_nodes=1)
        api.create_pod(make_pod("huge").req({"cpu": "64"}).obj())
        assert sched.schedule_pending() == 0
        assert len(sched.queue.unschedulable_pods) == 1
        # a big node arrives → NODE_ADD moves the pod; backoff applies
        api.create_node(make_node("big").capacity({"cpu": "128", "memory": "256Gi",
                                                   "pods": 110}).obj())
        assert len(sched.queue.unschedulable_pods) == 0
        sched.queue.clock = lambda: 1e9  # skip backoff
        assert sched.schedule_pending() == 1
        assert api.pods["default/huge"].spec.node_name == "big"

    def test_mixed_plain_and_spread_pods_stay_on_device(self):
        api, sched = mk(n_nodes=4)
        # interleaved plain + spread-constraint pods all run the device path
        # (ops/groups.py kernels); the skew constraint must hold
        for i in range(8):
            w = make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "web")
            if i % 2 == 0:
                w = w.spread_constraint(1, "topology.kubernetes.io/zone",
                                        "DoNotSchedule", {"app": "web"})
            api.create_pod(w.obj())
        bound = sched.schedule_pending()
        assert bound == 8
        assert sched.host_scheduled == 0
        zones = {}
        for p in api.pods.values():
            z = "z0" if p.spec.node_name in ("n0", "n2") else "z1"
            zones[z] = zones.get(z, 0) + 1
        assert abs(zones.get("z0", 0) - zones.get("z1", 0)) <= 1

    def test_scheduling_gates(self):
        api, sched = mk()
        api.create_pod(make_pod("gated").scheduling_gate("wait").obj())
        assert sched.schedule_pending() == 0
        gated = [q for q in sched.queue.unschedulable_pods.values() if q.gated]
        assert len(gated) == 1
        # gate removed → pod update → re-enqueued
        ungated = api.pods["default/gated"].clone()
        ungated.spec.scheduling_gates = []
        api.update_pod(ungated)
        assert sched.schedule_pending() == 1

    def test_pod_delete_frees_capacity(self):
        api, sched = mk(n_nodes=1)
        api.create_pod(make_pod("a").req({"cpu": "8"}).obj())
        assert sched.schedule_pending() == 1
        api.create_pod(make_pod("b").req({"cpu": "8"}).obj())
        assert sched.schedule_pending() == 0
        api.delete_pod("default/a")  # AssignedPodDelete → move
        sched.queue.clock = lambda: 1e9
        assert sched.schedule_pending() == 1
        assert api.pods["default/b"].spec.node_name == "n0"

    def test_priority_order_under_scarcity(self):
        api, sched = mk(n_nodes=1)
        api.create_pod(make_pod("low").priority(1).req({"cpu": "6"}).obj())
        api.create_pod(make_pod("high").priority(100).req({"cpu": "6"}).obj())
        assert sched.schedule_pending() == 1
        assert api.pods["default/high"].spec.node_name == "n0"
        assert not api.pods["default/low"].spec.node_name


class TestHostPath:
    def test_schedule_one(self):
        api, sched = mk()
        api.create_pod(make_pod("p").req({"cpu": "1"}).obj())
        assert sched.schedule_one()
        assert api.binding_count == 1

    def test_bind_error_requeues(self):
        api, sched = mk(n_nodes=1)
        api.create_pod(make_pod("p").req({"cpu": "1"}).obj())
        # sabotage: delete the node after watch registration so bind 404s,
        # but keep the cache/device view stale by bypassing the informer
        del api.nodes["n0"]
        assert sched.schedule_pending() == 0  # bind failed, forget + requeue
        assert sched.error_count == 1
        assert len(sched.queue) == 1  # pod back in a queue


class TestChurn:
    def test_steady_state_many_batches(self):
        api, sched = mk(n_nodes=8, batch_size=32)
        for wave in range(3):
            for i in range(64):
                api.create_pod(make_pod(f"w{wave}-p{i}").req(
                    {"cpu": "100m", "memory": "128Mi"}).obj())
            assert sched.schedule_pending() == 64
        assert api.binding_count == 192
        # cache and device state agree at the end; the carry stayed
        # device-resident across all batches
        assert sched._device_carry is not None
        assert sched.reconcile() == []


class TestAffinitySymmetry:
    """InterPodAffinity is symmetric (filtering.go:204-228,
    scoring.go:81-124): existing cluster pods with (anti-)affinity veto and
    score ANY incoming pod. Since round 3 this runs on DEVICE (ops/groups.py
    ipa_veto / ipa_score carried counts) — no host routing involved."""

    def test_existing_anti_affinity_blocks_incoming_plain_pod(self):
        # one node in zone z0 hosting a pod with required anti-affinity on
        # app=web; an incoming plain app=web pod must be UNSCHEDULABLE
        api, sched = mk(n_nodes=1)
        guard = (make_pod("guard").label("app", "other")
                 .pod_affinity("topology.kubernetes.io/zone", {"app": "web"},
                               anti=True)
                 .req({"cpu": "100m"}).obj())
        api.create_pod(guard)
        assert sched.schedule_pending() == 1
        incoming = make_pod("victim").label("app", "web").req({"cpu": "100m"}).obj()
        api.create_pod(incoming)
        assert sched.schedule_pending() == 0
        assert not api.pods["default/victim"].spec.node_name
        assert len(sched.queue.unschedulable_pods) == 1

    def test_existing_preferred_anti_affinity_scores_plain_pod(self):
        api, sched = mk(n_nodes=2)
        guard = (make_pod("guard").label("app", "db")
                 .preferred_pod_affinity("topology.kubernetes.io/zone",
                                         {"app": "web"}, weight=100, anti=True)
                 .req({"cpu": "100m"}).obj())
        api.create_pod(guard)
        sched.schedule_pending()
        api.create_pod(make_pod("plain").label("app", "web").req({"cpu": "100m"}).obj())
        assert sched.schedule_pending() == 1
        # the plain pod is steered AWAY from the guard's zone by the guard's
        # preferred anti-affinity (symmetric scoring), on the device path
        assert sched.host_scheduled == 0
        zone_of = {"n0": "z0", "n1": "z1"}
        assert (zone_of[api.pods["default/plain"].spec.node_name]
                != zone_of[api.pods["default/guard"].spec.node_name])

    def test_in_batch_anti_affinity_coupling(self):
        # within one drained batch: the guard's placement must steer the
        # later pod to the OTHER zone — the scan's carried counts couple them
        api, sched = mk(n_nodes=2)
        api.create_pod(make_pod("a-guard").label("app", "other")
                       .pod_affinity("topology.kubernetes.io/zone",
                                     {"app": "web"}, anti=True)
                       .req({"cpu": "100m"}).obj())
        api.create_pod(make_pod("b-web").label("app", "web").req({"cpu": "100m"}).obj())
        bound = sched.schedule_pending()
        assert bound == 2
        web = api.pods["default/b-web"]
        guard_node = api.pods["default/a-guard"].spec.node_name
        assert web.spec.node_name, "b-web must bind (one zone is free)"
        zone_of = {"n0": "z0", "n1": "z1"}
        assert zone_of[web.spec.node_name] != zone_of[guard_node]


class TestSignatureTableBounds:
    def test_table_reset_keeps_scheduling_correct(self):
        """Per-pod-unique labels mint one signature row each; past
        MAX_TABLE_ROWS the table resets instead of doubling, and scheduling
        stays correct across the reset."""
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.testing.wrappers import make_node, make_pod
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        sched.builder.dims.max_table_rows = 32   # force resets quickly
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 64, "memory": "128Gi", "pods": 200}).obj())
        total = 0
        for r in range(3):
            for i in range(40):   # 40 unique-label pods per round
                api.create_pod(make_pod(f"p{r}-{i}")
                               .req({"cpu": "250m", "memory": "256Mi"})
                               .label("pod-name", f"p{r}-{i}").obj())
            total += sched.schedule_pending()
        assert total == 120
        assert sched.builder.reset_count >= 1
        assert sched.builder.dims.table_rows <= 64
        assert sched.reconcile() == []


class TestRestartRecovery:
    def test_fresh_scheduler_resumes_live_cluster(self):
        """Scheduler restart: a NEW Scheduler against a live APIServer must
        rebuild its whole state from the informer LIST replay — bound pods
        occupy their nodes, pending pods schedule, and decisions match a
        scheduler that saw everything arrive live."""
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.testing.wrappers import make_node, make_pod
        api = APIServer()
        first = Scheduler(api, batch_size=64)
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 20}).obj())
        for i in range(10):
            api.create_pod(make_pod(f"old{i}").req(
                {"cpu": "2", "memory": "1Gi"}).obj())
        assert first.schedule_pending() == 10
        before = {p.name: p.spec.node_name for p in api.pods.values()}
        # pending work exists at the moment of the "crash"
        for i in range(6):
            api.create_pod(make_pod(f"new{i}").req(
                {"cpu": "2", "memory": "1Gi"}).obj())
        # restart: a brand-new scheduler attaches to the same API server
        second = Scheduler(api, batch_size=64)
        assert second.schedule_pending() == 6
        assert second.reconcile() == []
        after = {p.name: p.spec.node_name for p in api.pods.values()}
        # old placements untouched; new pods landed respecting old usage
        for name, node in before.items():
            assert after[name] == node
        # capacity accounting honored existing pods: 8cpu nodes with 2cpu
        # pods -> max 4 per node
        from collections import Counter
        per_node = Counter(after.values())
        assert max(per_node.values()) <= 4
        assert all(n for n in after.values())
