"""Streaming drain pipeline (ISSUE 18): parity, chaos, backpressure, SLI.

The gates this file establishes:

- deterministic parity: the SAME seeded trace through the lock-step
  `schedule_pending()` loop and through the streaming pipeline (one
  `feed(close=True)` per chunk pins identical batch boundaries) lands a
  byte-identical final assignment map, with zero shadow-oracle
  divergence at 100% sampling and a verifying drain ledger on both
  sides;
- free-running parity: the pipeline running its own adaptive batch
  closes (boundaries the test does NOT control) still byte-matches a
  replay twin driven by the recorded commit order — the
  boundary-independent invariant from tests/test_shards.py;
- kill-mid-pipeline chaos: a worker dies at each stage boundary
  (host_build / device / commit / mid-flush); the fault surfaces
  through `drain()`, a fresh scheduler over the same store recovers
  every pod, `binding_count` stays exact (zero double-binds), and the
  replay twin still matches;
- explicit backpressure: dispatch depth caps ingest and commit backlog
  caps dispatch, each stall counted on the STALLED stage's label;
- observability: /debug/pipeline serves the occupancy block, the
  scheduler_pipeline_* families mirror the pipeline's counters, and the
  feature gate off means no pipeline at all;
- the requeue-safe SLI clock attributes commit_backlog waits per pod
  even when commits complete out of phase with dispatches (ISSUE 18
  satellite).
"""

import json
import random
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.config import KubeSchedulerConfiguration
from kubernetes_tpu.metrics import SchedulerMetrics
from kubernetes_tpu.obs.journey import JourneyLedger
from kubernetes_tpu.pipeline import STAGES, PipelineStopped, StreamingPipeline
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.server import SchedulerServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod

SEED = 1813


class Killed(Exception):
    """Simulated process death inside a pipeline worker."""


def _nodes(api, n=8, cpu=64, mem="128Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _specs(n, seed, prefix="p"):
    rng = random.Random(seed)
    return [(f"{prefix}{i}", "default", 250 * rng.randint(1, 6),
             512 * rng.randint(1, 4)) for i in range(n)]


def _pods(specs, raw=None):
    out = []
    for name, ns, cpu, mem in specs:
        pod = make_pod(name, namespace=ns).req(
            {"cpu": f"{cpu}m", "memory": f"{mem}Mi"}).obj()
        if raw is not None:
            raw[pod.uid] = (name, ns, cpu, mem)
        out.append(pod)
    return out


def _assignments(api):
    return {uid: p.spec.node_name for uid, p in api.pods.items()}


def _audited(sched):
    assert sched.audit is not None, "ShadowOracleAudit gate must be on"
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    return sched


def _no_sleep(sched):
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _sched(client, batch_size=64):
    return _audited(_no_sleep(Scheduler(client, batch_size=batch_size)))


def _divergence(sched):
    m = sched.metrics
    return sum(int(m.oracle_divergence.value(kind))
               for kind in ("assignment", "reason", "verdict"))


def _bound(api):
    return sum(1 for p in api.pods.values() if p.spec.node_name)


class BindRecorder:
    """Record every committed (uid, node) chunk in commit order — the
    replay twin's script (tests/test_shards.py pattern). Installed on
    the INNER store so killer facades route through it."""

    def __init__(self, api):
        self.chunks = []
        self._real_all, self._real_one = api.bind_all, api.bind
        api.bind_all = self._bind_all
        api.bind = self._bind

    def _bind_all(self, pairs, fence_token=None):
        failures = self._real_all(pairs, fence_token=fence_token)
        failed = {p.uid for p, _e in failures}
        chunk = [(a.uid, a.spec.node_name) for a, _o in pairs
                 if a.uid not in failed]
        if chunk:
            self.chunks.append(chunk)
        return failures

    def _bind(self, pod, node_name, fence_token=None):
        out = self._real_one(pod, node_name, fence_token=fence_token)
        self.chunks.append([(pod.uid, node_name)])
        return out


def _replay_twin(raw, chunks, n_nodes=8, cpu=64, mem="128Gi"):
    """Feed the recorded commit order, chunk by chunk, to ONE fresh
    lock-step scheduler on a fresh store: if the pipeline changed
    nothing but WHEN work happened, the twin's final assignment map is
    byte-identical."""
    api = APIServer()
    _nodes(api, n=n_nodes, cpu=cpu, mem=mem)
    sched = _sched(api)
    want = 0
    for chunk in chunks:
        for uid, _node in chunk:
            name, ns, pcpu, pmem = raw[uid]
            api.create_pod(make_pod(name, namespace=ns).req(
                {"cpu": f"{pcpu}m", "memory": f"{pmem}Mi"}).obj())
        want += len(chunk)
        for _ in range(60):
            sched.schedule_pending()
            if _bound(api) >= want:
                break
            sched.flush_queues()
    assert sched.reconcile() == []
    return _assignments(api)


class MidFlushKiller:
    """Victim-only client facade: when armed, the next bulk bind commits
    its first half and then the 'process' dies (tests/test_shards.py)."""

    def __init__(self, inner):
        self.inner = inner
        self.armed = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def bind_all(self, pairs, fence_token=None):
        if self.armed and len(pairs) > 1:
            self.armed = False
            self.inner.bind_all(pairs[:len(pairs) // 2],
                                fence_token=fence_token)
            raise Killed("died mid-flush")
        return self.inner.bind_all(pairs, fence_token=fence_token)


def _arm_kill(sched, phase, client=None):
    """Wire the simulated death into the chosen pipeline stage."""
    if phase == "host_build":
        orig = sched.builder.build

        def die(*a, **k):
            sched.builder.build = orig
            raise Killed("died in host build")
        sched.builder.build = die
    elif phase == "device":
        def die(*a, **k):
            raise Killed("died before commit")
        sched._commit_next = die
    elif phase == "commit":
        orig_flush = sched.dispatcher.flush

        def die_flush(*a, **k):
            if len(sched.dispatcher):
                raise Killed("died before the API flush")
            return orig_flush(*a, **k)
        sched.dispatcher.flush = die_flush
    elif phase == "mid_flush":
        client.armed = True
    else:                            # pragma: no cover
        raise AssertionError(phase)


# -- feature gate --------------------------------------------------------------


def test_gate_off_means_no_pipeline():
    api = APIServer()
    sched = Scheduler(api, config=KubeSchedulerConfiguration(
        feature_gates={"StreamingDrainPipeline": False}))
    with pytest.raises(RuntimeError, match="StreamingDrainPipeline"):
        StreamingPipeline(sched)


def test_feed_after_stop_raises():
    api = APIServer()
    _nodes(api, 2)
    sched = _sched(api)
    sched.prime()
    pipe = StreamingPipeline(sched).start()
    pipe.stop()
    with pytest.raises(PipelineStopped):
        pipe.feed(_pods(_specs(1, SEED)))


# -- parity gates --------------------------------------------------------------


@pytest.mark.parametrize("seed", [SEED, SEED + 1])
def test_streaming_matches_lockstep_bind_for_bind(seed):
    """Same seeded trace, same chunk boundaries, through both paths:
    byte-identical assignment maps, zero shadow-oracle divergence at
    100% sampling, verifying ledgers on both sides."""
    specs = _specs(192, seed)
    chunks = [specs[i:i + 32] for i in range(0, len(specs), 32)]

    # lock-step twin: one schedule_pending() per chunk
    api_l = APIServer()
    _nodes(api_l)
    lock = _sched(api_l)
    lock.prime()
    for chunk in chunks:
        for pod in _pods(chunk):
            api_l.create_pod(pod)
        lock.schedule_pending()
    assert _bound(api_l) == len(specs)

    # streaming path: one feed(close=True) per chunk pins the SAME
    # batch boundaries; commits ride the async commit worker
    api_s = APIServer()
    _nodes(api_s)
    stream = _sched(api_s)
    stream.prime()
    pipe = StreamingPipeline(stream)
    pipe.start()
    try:
        for chunk in chunks:
            pipe.feed(_pods(chunk), close=True)
        pipe.drain(timeout=60.0)
    finally:
        pipe.stop()
    assert not pipe.errors
    assert _bound(api_s) == len(specs)

    assert _assignments(api_s) == _assignments(api_l)
    assert _divergence(stream) == 0 and _divergence(lock) == 0
    assert stream.audit.ledger.verify() and lock.audit.ledger.verify()
    assert api_s.binding_count == len(specs)


def test_free_running_pipeline_replay_twin_parity():
    """The pipeline choosing its OWN adaptive batch boundaries still
    byte-matches a lock-step replay twin of the recorded commit order,
    with the ledger verifying and zero divergence — plus the satellite
    SLI gate: every bound pod gets exactly one commit_backlog segment
    sample even though commits land out of phase with dispatches."""
    rng = random.Random(SEED)
    specs = _specs(224, SEED + 2)
    raw = {}
    api = APIServer()
    _nodes(api)
    rec = BindRecorder(api)
    sched = _sched(api)
    sched.prime()
    pipe = StreamingPipeline(sched, latency_budget_s=0.002)
    pipe.start()
    try:
        for i in range(0, len(specs), 16):
            pipe.feed(_pods(specs[i:i + 16], raw=raw))
            time.sleep(rng.uniform(0.0, 0.003))
        pipe.drain(timeout=60.0)
    finally:
        pipe.stop()
    assert not pipe.errors
    assert _bound(api) == len(specs)
    assert api.binding_count == len(specs)
    assert _divergence(sched) == 0
    assert sched.audit.ledger.verify()
    assert _replay_twin(raw, rec.chunks) == _assignments(api)
    # requeue-safe SLI clock, out-of-phase commits: one commit_backlog
    # sample per bound pod, none lost, none double-counted
    assert sched.metrics.e2e_segment.count("commit_backlog") == len(specs)


# -- kill-mid-pipeline chaos ---------------------------------------------------


@pytest.mark.parametrize("phase",
                         ["host_build", "device", "commit", "mid_flush"])
def test_kill_mid_pipeline_no_double_binds(phase):
    """A worker dies at each stage boundary: the fault surfaces through
    drain(), a fresh scheduler over the same store recovers every pod,
    binding_count stays exact and the replay twin still matches."""
    specs = _specs(160, SEED + 3)
    raw = {}
    api = APIServer()
    _nodes(api)
    rec = BindRecorder(api)
    victim_client = MidFlushKiller(api) if phase == "mid_flush" else api
    sched = _sched(victim_client)
    sched.prime()
    pipe = StreamingPipeline(sched, latency_budget_s=0.001)
    pipe.start()
    chunks = [specs[i:i + 32] for i in range(0, len(specs), 32)]
    killed = False
    try:
        pipe.feed(_pods(chunks[0], raw=raw))   # healthy prologue
        time.sleep(0.02)
        _arm_kill(sched, phase, client=victim_client)
        for chunk in chunks[1:]:
            pipe.feed(_pods(chunk, raw=raw))
            time.sleep(0.002)
        pipe.drain(timeout=30.0)
    except Killed:
        killed = True
    finally:
        pipe.stop()
    assert killed, f"{phase} kill never fired"
    assert any(isinstance(e, Killed) for _stage, e in pipe.errors)

    # the fault fails feeds fast, so only a prefix of the trace reached
    # the store — recovery owes exactly those pods, nothing less
    total = len(api.pods)
    assert total >= len(chunks[0]), "prologue never landed"

    # 'process restart': a fresh scheduler over the same store LISTs the
    # survivors and finishes the job
    sched2 = _sched(api)
    sched2.prime()
    for _ in range(60):
        sched2.schedule_pending()
        if _bound(api) >= total:
            break
        sched2.flush_queues()
    assert _bound(api) == total
    assert api.binding_count == total            # zero double-binds
    assert _divergence(sched2) == 0
    assert sched2.audit.ledger.verify()
    assert _replay_twin(raw, rec.chunks) == _assignments(api)


# -- backpressure --------------------------------------------------------------


def _await(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return False


def test_dispatch_depth_caps_ingest():
    """With commits stalled and dispatch_depth=1, a second close must
    stall INGEST (the stalled stage carries the label) until the commit
    worker catches up."""
    api = APIServer()
    _nodes(api)
    sched = _sched(api)
    sched.prime()
    real_commit = sched.commit_ready
    sched.commit_ready = lambda limit=0: 0      # commits stall
    pipe = StreamingPipeline(sched, dispatch_depth=1)
    pipe.start()
    try:
        pipe.feed(_pods(_specs(16, SEED + 4)), close=True)
        blocked = threading.Thread(
            target=pipe.feed,
            args=(_pods(_specs(16, SEED + 5, prefix="q")),),
            kwargs={"close": True})
        blocked.start()
        assert _await(lambda: pipe._backpressure["ingest"] > 0), \
            "ingest never saw backpressure"
        sched.commit_ready = real_commit        # commits resume
        blocked.join(timeout=20.0)
        assert not blocked.is_alive()
        pipe.drain(timeout=30.0)
    finally:
        sched.commit_ready = real_commit
        pipe.stop()
    assert not pipe.errors
    assert pipe.stats()["backpressure"]["ingest"] >= 1
    assert _bound(api) == 32


def test_commit_backlog_caps_dispatch():
    """With the bind-echo flush stalled and a 1-pod commit backlog cap,
    the next dispatch must stall on the DEVICE label (commit backlog
    caps dispatch) until the flush drains."""
    api = APIServer()
    _nodes(api)
    sched = _sched(api)
    sched.prime()
    real_flush = sched.dispatcher.flush
    sched.dispatcher.flush = lambda *a, **k: 0  # echo stalls, backlog grows
    pipe = StreamingPipeline(sched, commit_backlog_pods=1)
    pipe.start()
    try:
        pipe.feed(_pods(_specs(16, SEED + 6)), close=True)
        assert _await(lambda: len(sched.dispatcher) > 0), \
            "commit backlog never formed"
        blocked = threading.Thread(
            target=pipe.feed,
            args=(_pods(_specs(16, SEED + 7, prefix="q")),),
            kwargs={"close": True})
        blocked.start()
        assert _await(lambda: pipe._backpressure["device"] > 0), \
            "dispatch never saw commit-backlog backpressure"
        sched.dispatcher.flush = real_flush     # the echo drains
        blocked.join(timeout=20.0)
        assert not blocked.is_alive()
        pipe.drain(timeout=30.0)
    finally:
        sched.dispatcher.flush = real_flush
        pipe.stop()
    assert not pipe.errors
    assert pipe.stats()["backpressure"]["device"] >= 1
    assert _bound(api) == 32


# -- observability -------------------------------------------------------------


def test_stats_metrics_and_debug_endpoint():
    """stats() reports occupancy and depths; the scheduler_pipeline_*
    families mirror the pipeline's counters; /debug/pipeline serves the
    occupancy block (404 with no pipeline attached)."""
    api = APIServer()
    _nodes(api)
    sched = _sched(api)
    sched.prime()

    # no pipeline attached yet: 404
    srv = SchedulerServer(sched).start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/pipeline", timeout=5)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404

        pipe = StreamingPipeline(sched)
        pipe.start()
        try:
            for i in range(0, 96, 16):
                pipe.feed(_pods(_specs(16, SEED + 8 + i,
                                       prefix=f"w{i}-")))
                time.sleep(0.002)
            pipe.drain(timeout=60.0)
        finally:
            pipe.stop()
        st = pipe.stats()
        assert st["running"] is False
        assert st["batches"] >= 1 and st["commits"] >= 1
        assert st["busySeconds"]["ingest"] > 0
        assert st["busySeconds"]["commit"] > 0
        assert st["depths"] == {"queue": 0, "dispatch": 0,
                                "commitBacklog": 0}
        assert set(st["batchClose"]) >= {"full", "idle", "budget", "feed"}
        # the metric families mirror the pipeline's own counters exactly
        m = sched.metrics
        for stage in STAGES:
            # stats() rounds for display; the raw counter is the truth
            assert m.pipeline_stage_busy.value(stage) == pytest.approx(
                st["busySeconds"][stage], abs=1e-6)
            assert m.pipeline_backpressure.value(stage) == float(
                st["backpressure"][stage])

        # the pipeline stays reachable at /debug after stop()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/pipeline",
                timeout=5) as r:
            assert r.status == 200
            out = json.loads(r.read().decode())
        assert out["batches"] == st["batches"]
        assert set(out["busySeconds"]) == set(STAGES)
        assert set(out["backpressure"]) == set(STAGES)
    finally:
        srv.stop()


# -- the requeue-safe SLI clock under out-of-phase commits ---------------------


def test_sli_commit_backlog_attribution_out_of_phase():
    """ISSUE 18 satellite: commit_backlog waits are attributed per pod
    from each pod's OWN dispatcher-enqueue clock, even when bind echoes
    land out of phase with dispatch order (drain N+1 confirming before
    drain N) and across a bind-error re-enqueue."""
    led = JourneyLedger(enabled=True)
    led.bind_enqueued(["default/a", "default/b"], now=100.0)   # drain N
    led.bind_enqueued(["default/c"], now=101.0)                # drain N+1
    # out of phase: drain N+1's echo lands FIRST — its wait must use
    # its own enqueue clock, not drain N's
    assert led.bind_confirmed(["default/c"], now=101.5) == [0.5]
    assert led.bind_confirmed(["default/a", "default/b"],
                              now=104.0) == [4.0, 4.0]
    # a bind-error re-enqueue restarts the commit_backlog clock (the
    # e2e clock elsewhere keeps first_seen; this segment is per attempt)
    led.bind_enqueued(["default/a"], now=110.0)
    assert led.bind_confirmed(["default/a"], now=110.25) == [0.25]
    # an echo with no recorded enqueue contributes no wait sample
    assert led.bind_confirmed(["default/ghost"], now=120.0) == []
    # clocks are dropped at confirm: a second echo is idempotent
    assert led.bind_confirmed(["default/c"], now=130.0) == []


# -- tools/check.py pipeline_stages gate ---------------------------------------


def _load_check():
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_tpu_tools_check_pipeline", os.path.join(repo, "tools", "check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_pipeline_stages_check_repo_is_clean():
    """The shipped pipeline.py reaches the device only through the
    Scheduler seams — the check must pass on the real tree."""
    assert _load_check().pipeline_stage_gaps() == []


def test_pipeline_stages_check_catches_bypasses():
    """Every bypass class is caught: kernel-module imports (absolute and
    relative), direct JIT entry calls, and raw measured_call()."""
    chk = _load_check()
    gaps = chk.pipeline_stage_gaps(source=(
        "import jax\n"
        "from kubernetes_tpu.ops.program import run_batch\n"
        "from .parallel import sharding\n"
        "def stage(cfg, na, carry, pods):\n"
        "    out = run_batch(cfg, na, carry, pods)\n"
        "    return LEDGER.measured_call('run_batch', fn, cfg)\n"))
    kinds = "\n".join(gaps)
    assert len(gaps) == 5
    assert "import jax" in kinds
    assert "kubernetes_tpu.ops.program" in kinds
    assert ".parallel" in kinds
    assert "run_batch()" in kinds
    assert "measured_call()" in kinds
    # and the sanctioned seams are NOT flagged
    assert chk.pipeline_stage_gaps(source=(
        "def loop(sched):\n"
        "    sched.dispatch_once()\n"
        "    sched.commit_ready()\n"
        "    sched.flush_queues()\n")) == []
