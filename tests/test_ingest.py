"""Columnar ingest & commit engine parity suites (ISSUE 9).

Three contracts, each fuzzed against the serial reference paths that
stay in the tree as oracles:

1. columnar tensorize — `BatchBuilder.build` (chunked interning +
   ingest/columns.py fill_rows) vs a per-pod `_lookup`/`_fill_row` build:
   bit-for-bit PodTable equality (affinity term tables included), plus
   identical sig/tidx/valid/fallback vectors and commit-facts columns.
2. generation-diff snapshot upload — `ClusterState.device_arrays`'s
   scatter_rows path vs a full re-tensorize, across seeded assume /
   forget / node-flap / cordon sequences.
3. batched commit — the CommitEngine + bulk bind-echo (`ColumnarIngest`
   on) vs the serial `_fast_commit` / per-pod informer path (gate off):
   identical assignments, cache content, dispatcher traffic and events.

Plus the columnar node-row writers (ingest/noderows.py) and the
vectorized group seeding (ingest/groupcols.py) against brute-force
per-node references.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.state.batch import BatchBuilder, PodBatch, PodTable
from kubernetes_tpu.state.tensorize import ClusterState, pow2_at_least
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


# ---------------------------------------------------------------------------
# fuzz pod generator


def _fuzz_pod(rng: random.Random, i: int):
    w = make_pod(f"pod-{i}").req(
        {"cpu": f"{rng.choice([100, 250, 500, 900])}m",
         "memory": f"{rng.choice([256, 512, 1024])}Mi"})
    if rng.random() < 0.3:
        w = w.label("app", rng.choice(["web", "db", "cache"]))
    if rng.random() < 0.25:
        w = w.node_selector({ZONE: f"zone-{rng.randrange(4)}"})
    if rng.random() < 0.2:
        w = w.toleration(key="dedicated", operator="Equal",
                         value=rng.choice(["gpu", "infra"]),
                         effect="NoSchedule")
    if rng.random() < 0.2:
        w = w.node_affinity_in(ZONE,
                               [f"zone-{z}" for z in range(rng.randrange(1, 4))])
    if rng.random() < 0.15:
        w = w.preferred_node_affinity_in(ZONE, ["zone-0", "zone-1"],
                                         weight=rng.randrange(1, 50))
    if rng.random() < 0.15:
        w = w.spread_constraint(rng.randrange(1, 3), ZONE, "DoNotSchedule",
                                {"app": "web"})
    if rng.random() < 0.1:
        w = w.pod_affinity(ZONE, {"app": "db"}, anti=True)
    if rng.random() < 0.1:
        w = w.host_port(8000 + rng.randrange(16))
    if rng.random() < 0.1:
        w = w.container({"cpu": "50m"}, image=f"img-{rng.randrange(4)}:v1")
    if rng.random() < 0.08:
        w = w.pvc(f"claim-{i}")        # host-fallback path
    if rng.random() < 0.06:
        # overflow a padded dim (tolerations) → capacity fallback
        for t in range(9):
            w = w.toleration(key=f"k{t}", operator="Exists")
    return w.obj()


def _serial_build(builder: BatchBuilder, pods, pad_to: int = 0) -> PodBatch:
    """The pre-columnar per-pod build loop, verbatim — the oracle."""
    B = pow2_at_least(max(len(pods), pad_to))
    if builder.table_used >= builder.dims.max_table_rows:
        builder._reset_table()
    if builder.table.req.shape[1] != builder.state.dims.resources:
        builder._reset_table()
    valid = np.zeros((B,), bool)
    fallback = np.zeros((B,), bool)
    sig = np.zeros((B,), np.int32)
    tidx = np.zeros((B,), np.int32)
    last = -1
    for i, pod in enumerate(pods):
        ent = builder._lookup(pod)
        if ent[0] == "fallback":
            fallback[i] = True
        else:
            valid[i] = True
            sig[i] = ent[1]
            tidx[i] = ent[2]
            last = i
    if last >= 0 and len(pods) < B:
        sig[len(pods):] = sig[last]
        tidx[len(pods):] = tidx[last]
    return PodBatch(valid=valid, host_fallback=fallback, sig=sig,
                    tidx=tidx, table=builder.table,
                    table_version=builder.table_version)


class TestColumnarTensorizeParity:
    def test_fuzz_bit_for_bit_table_parity(self):
        for seed in range(20):
            rng_a = random.Random(seed)
            rng_b = random.Random(seed)
            state_a, state_b = ClusterState(), ClusterState()
            ba = BatchBuilder(state_a)
            bb = BatchBuilder(state_b)
            # several chunks against the same builders: exercises the
            # ident/sig caches, growth and cross-chunk interning
            off = 0
            for chunk in range(3):
                n = rng_a.randrange(1, 40)
                rng_b.randrange(1, 40)
                pods_a = [_fuzz_pod(rng_a, off + i) for i in range(n)]
                pods_b = [_fuzz_pod(rng_b, off + i) for i in range(n)]
                off += n
                got = ba.build(pods_a, pad_to=16)
                want = _serial_build(bb, pods_b, pad_to=16)
                np.testing.assert_array_equal(got.valid, want.valid)
                np.testing.assert_array_equal(got.host_fallback,
                                              want.host_fallback)
                np.testing.assert_array_equal(got.sig, want.sig)
                np.testing.assert_array_equal(got.tidx, want.tidx)
                assert ba.table_used == bb.table_used
                for name in PodTable._fields:
                    np.testing.assert_array_equal(
                        getattr(ba.table, name), getattr(bb.table, name),
                        err_msg=f"PodTable.{name} diverged (seed {seed}, "
                                f"chunk {chunk})")
                # the commit-facts column is aligned and identical
                assert len(ba.row_facts) == ba.table_used
                assert ba.row_facts == bb.row_facts

    def test_single_signature_chunk_fast_path(self):
        state = ClusterState()
        b = BatchBuilder(state)
        proto = make_pod("p0").req({"cpu": "500m"}).obj()
        pods = [proto] + [_clone_shared(proto, f"p{i}") for i in range(1, 64)]
        batch = b.build(pods)
        assert batch.valid[:64].all()
        assert (batch.sig[:64] == batch.sig[0]).all()
        assert b.table_used == 1
        assert len(b.row_facts) == 1

    def test_facts_match_commit_predicates(self):
        """CommitFacts flags mirror NodeInfo.add_pod's membership
        predicates for every fuzzed signature row."""
        from kubernetes_tpu.framework.types import NodeInfo, PodInfo
        rng = random.Random(7)
        state = ClusterState()
        b = BatchBuilder(state)
        pods = [_fuzz_pod(rng, i) for i in range(60)]
        batch = b.build(pods)
        node = make_node("n0").capacity({"cpu": 64, "memory": "64Gi",
                                         "pods": 110}).obj()
        for i, pod in enumerate(pods):
            if not batch.valid[i]:
                continue
            f = b.row_facts[int(batch.tidx[i])]
            pi = PodInfo.of(pod.with_node_name("n0"))
            info = NodeInfo(node=node)
            info.add_pod(pi)
            assert f.has_affinity == bool(info.pods_with_affinity)
            assert f.has_anti_affinity == bool(
                info.pods_with_required_anti_affinity)
            assert dict(f.req_items) == pi.requests
            assert (f.cpu_nz, f.mem_nz) == (pi.cpu_nonzero, pi.mem_nonzero)
            assert f.has_ports == bool(info.used_ports.ports)


def _clone_shared(proto, name):
    """Stamp a pod sharing spec/labels objects (the PodFactory shape)."""
    from kubernetes_tpu.api.types import PodStatus, _shallow
    from kubernetes_tpu.testing.wrappers import _counter
    p = _shallow(proto)
    m = _shallow(proto.metadata)
    m.name = name
    m.uid = f"{m.namespace}/{name}"
    m.creation_index = next(_counter)
    p.metadata = m
    p.status = PodStatus()
    return p


# ---------------------------------------------------------------------------
# generation-diff device scatter


def _fresh_device(state: ClusterState):
    import jax.numpy as jnp
    return [np.asarray(jnp.asarray(x)) for x in state.arrays]


class TestGenerationDiffScatter:
    def _cluster(self, n_nodes=24, seed=0):
        rng = random.Random(seed)
        cache = Cache()
        snapshot = Snapshot()
        nodes = []
        for i in range(n_nodes):
            w = make_node(f"node-{i}").capacity(
                {"cpu": 16, "memory": "32Gi", "pods": 110}).zone(
                f"z{i % 4}").label(HOSTNAME, f"node-{i}")
            if rng.random() < 0.2:
                w = w.taint("dedicated", "infra", "NoSchedule")
            nodes.append(w.obj())
            cache.add_node(nodes[-1])
        state = ClusterState()
        cache.update_snapshot(snapshot)
        state.apply_snapshot(snapshot)
        return rng, cache, snapshot, state, nodes

    def test_scatter_equals_full_upload_across_mutations(self):
        rng, cache, snapshot, state, nodes = self._cluster()
        base = state.device_arrays()      # full upload (first build)
        assert state.full_uploads_total == 1
        pods = []
        for step in range(30):
            op = rng.random()
            if op < 0.5 or not pods:
                pod = make_pod(f"p{len(pods)}").req(
                    {"cpu": "250m", "memory": "256Mi"}).obj()
                pod = pod.with_node_name(
                    f"node-{rng.randrange(len(nodes))}")
                try:
                    cache.assume_pod(pod)
                    pods.append(pod)
                except KeyError:
                    pass
            elif op < 0.75:
                pod = pods.pop(rng.randrange(len(pods)))
                try:
                    cache.forget_pod(pod)
                except (KeyError, ValueError):
                    pass
            elif op < 0.9:
                # node flap: remove + re-add (fresh generation)
                i = rng.randrange(len(nodes))
                cache.remove_node(nodes[i])
                cache.add_node(nodes[i])
            else:
                # cordon/uncordon (spec change → full row rewrite)
                i = rng.randrange(len(nodes))
                import dataclasses
                old = nodes[i]
                new_spec = dataclasses.replace(
                    old.spec, unschedulable=not old.spec.unschedulable)
                new = dataclasses.replace(old, spec=new_spec)
                cache.update_node(old, new)
                nodes[i] = new
            cache.update_snapshot(snapshot)
            state.apply_snapshot(snapshot)
            dev = state.device_arrays()   # scatter or full, its call
            full = _fresh_device(state)
            for got, want, name in zip(dev, full, type(dev)._fields):
                np.testing.assert_array_equal(
                    np.asarray(got), want,
                    err_msg=f"device field {name} diverged at step {step}")
        assert state.rows_scattered_total > 0, \
            "the sequence never exercised the scatter path"

    def test_node_removal_reaches_device(self):
        """A node removal with no other writes must clear the device
        row's valid bit (the stale-valid fix)."""
        _rng, cache, snapshot, state, nodes = self._cluster(n_nodes=8)
        state.device_arrays()
        idx = state.node_index[nodes[3].name]
        cache.remove_node(nodes[3])
        cache.update_snapshot(snapshot)
        state.apply_snapshot(snapshot)
        dev = state.device_arrays()
        assert not bool(np.asarray(dev.valid)[idx])

    def test_large_dirty_set_takes_full_upload(self):
        # 40 dirty rows > max(N >> 3, 32) at a 64-row bucket → full path
        _rng, cache, snapshot, state, nodes = self._cluster(n_nodes=40)
        state.device_arrays()
        before = state.full_uploads_total
        for i, node in enumerate(nodes):
            pod = make_pod(f"bulk-{i}").req({"cpu": "100m"}).obj()
            cache.assume_pod(pod.with_node_name(node.name))
        cache.update_snapshot(snapshot)
        state.apply_snapshot(snapshot)
        state.device_arrays()
        assert state.full_uploads_total == before + 1

    def test_scatter_rows_entry_pads_and_duplicates(self):
        from kubernetes_tpu.ops.program import scatter_rows
        from kubernetes_tpu.state.tensorize import NodeArrays, _zero_arrays
        state = ClusterState()
        state.ensure_arrays()
        import jax.numpy as jnp
        dev = NodeArrays(*(jnp.asarray(x) for x in state.arrays))
        a = _zero_arrays(state.dims)
        a.cap[2, 0] = 99
        idx = np.array([2, 2, 2, 2], np.int32)   # duplicates, identical rows
        rows = NodeArrays(*(x[idx] for x in a))
        out = scatter_rows(dev, idx, rows)
        assert int(np.asarray(out.cap)[2, 0]) == 99


# ---------------------------------------------------------------------------
# columnar node-row writers


class TestNodeRowWriters:
    def test_write_rows_bit_for_bit(self):
        from kubernetes_tpu.ingest.noderows import write_rows
        for seed in range(6):
            rng = random.Random(seed)
            cache = Cache()
            for i in range(40):
                w = make_node(f"n-{i}").capacity(
                    {"cpu": 8 + rng.randrange(8), "memory": "16Gi",
                     "pods": 110}).zone(f"z{i % 3}").label(
                    HOSTNAME, f"n-{i}").label("idx", str(i))
                if rng.random() < 0.3:
                    w = w.taint("t", f"v{rng.randrange(3)}",
                                rng.choice(["NoSchedule",
                                            "PreferNoSchedule"]))
                if rng.random() < 0.3:
                    w = w.unschedulable()
                cache.add_node(w.obj())
            snapshot = Snapshot()
            cache.update_snapshot(snapshot)
            # serial reference
            ref = ClusterState()
            ref.ensure_arrays()
            ref_items = []
            for ni in snapshot.node_info_list:
                ref_items.append((ref._slot(ni.name), ni))
            # pre-size: both states go through _slot the same way
            col = ClusterState()
            col.ensure_arrays()
            col_items = [(col._slot(ni.name), ni)
                         for ni in snapshot.node_info_list]
            for idx, ni in ref_items:
                ref._write_row(idx, ni)
            assert write_rows(col, col_items)
            for name in type(ref.arrays)._fields:
                np.testing.assert_array_equal(
                    getattr(ref.arrays, name), getattr(col.arrays, name),
                    err_msg=f"NodeArrays.{name} diverged (seed {seed})")

    def test_aggregate_rows_bit_for_bit(self):
        from kubernetes_tpu.ingest.noderows import write_aggregate_rows
        cache = Cache()
        nodes = [make_node(f"m-{i}").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 110}).obj()
            for i in range(12)]
        for node in nodes:
            cache.add_node(node)
        snapshot = Snapshot()
        cache.update_snapshot(snapshot)
        ref, col = ClusterState(), ClusterState()
        ref.apply_snapshot(snapshot)
        col.apply_snapshot(snapshot)
        for i, node in enumerate(nodes):
            cache.assume_pod(make_pod(f"q{i}").req(
                {"cpu": "300m", "memory": "1Gi"}).obj()
                .with_node_name(node.name))
        cache.update_snapshot(snapshot)
        items_ref = [(ref.node_index[ni.name], ni)
                     for ni in snapshot.node_info_list]
        items_col = [(col.node_index[ni.name], ni)
                     for ni in snapshot.node_info_list]
        for idx, ni in items_ref:
            ref._write_row_aggregates(idx, ni)
        assert write_aggregate_rows(col, items_col)
        for name in ("used", "nonzero_used", "npods", "ports"):
            np.testing.assert_array_equal(
                getattr(ref.arrays, name), getattr(col.arrays, name))


# ---------------------------------------------------------------------------
# vectorized group seeding


class TestGroupSeedParity:
    def test_gather_ids_matches_dict_probe(self):
        from kubernetes_tpu.ingest.groupcols import gather_ids
        rng = random.Random(3)
        for _ in range(50):
            n = rng.randrange(1, 200)
            tv = np.array([rng.randrange(0, 12) for _ in range(n)],
                          np.int32)
            table = {k: rng.randrange(1, 100)
                     for k in rng.sample(range(1, 12),
                                         rng.randrange(0, 8))}
            want = np.array([table.get(int(t), 0) for t in tv], np.int64)
            np.testing.assert_array_equal(gather_ids(tv, table), want)

    def test_seed_counts_against_brute_force(self):
        """Vectorized seed_counts vs a per-node dict-probe reference over
        a live cluster with spread + inter-pod affinity load."""
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(24):
            api.create_node(make_node(f"node-{i}").capacity(
                {"cpu": 16, "memory": "32Gi", "pods": 110}).zone(
                f"zone-{i % 4}").label(HOSTNAME, f"node-{i}").obj())
        # existing pods feeding the symmetric counts
        for i in range(12):
            api.create_pod(make_pod(f"old-{i}").req({"cpu": "100m"})
                           .label("app", "web" if i % 2 else "db")
                           .node(f"node-{i % 24}").obj())
        sched.prime()
        pods = [
            make_pod("s0").req({"cpu": "200m"}).label("app", "web")
            .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "web"})
            .obj(),
            make_pod("s1").req({"cpu": "200m"}).label("app", "db")
            .spread_constraint(2, ZONE, "ScheduleAnyway", {"app": "db"})
            .obj(),
            make_pod("s2").req({"cpu": "200m"}).label("app", "web")
            .pod_affinity(ZONE, {"app": "db"}).obj(),
            make_pod("s3").req({"cpu": "200m"}).label("app", "db")
            .pod_affinity(ZONE, {"app": "web"}, anti=True).obj(),
        ]
        sched.builder.build(pods)
        g = sched.builder.groups
        rows = range(len(g.rows))
        nis = g._node_rows(sched.snapshot)
        out = g.seed_counts(sched.snapshot, rows, nis=nis)
        # brute force: per-node label dict probes (the pre-columnar walk)
        from kubernetes_tpu.framework.interface import CycleState
        from kubernetes_tpu.plugins import interpodaffinity as ipa_mod
        from kubernetes_tpu.plugins import podtopologyspread as pts_mod
        node_list = sched.snapshot.node_info_list
        for r, u in enumerate(rows):
            info = g.rows[u]
            if info is None:
                continue
            pod = info.pod
            if info.f_constraints:
                cs = CycleState()
                g.pts.pre_filter(cs, pod, node_list)
                s = cs.read_or_none(pts_mod._PRE_FILTER_KEY)
                for j, c in enumerate(s.constraints):
                    cnts = s.tp_value_to_match_num[j]
                    for idx, ni in nis:
                        v = ni.node.metadata.labels.get(c.topology_key)
                        want = cnts.get(v, 0) if v is not None else 0
                        assert out["spr_f_cnt"][r, j, idx] == want
            cs = CycleState()
            g.ipa.pre_filter(cs, pod, node_list)
            s = cs.read_or_none(ipa_mod._PRE_FILTER_KEY)
            if s is not None and s.existing_anti_affinity_counts:
                for idx, ni in nis:
                    want = sum(
                        s.existing_anti_affinity_counts.get(kv, 0)
                        for kv in ni.node.metadata.labels.items())
                    assert out["ipa_veto"][r, idx] == want
            cs = CycleState()
            g.ipa.pre_score(cs, pod, node_list, all_nodes=node_list)
            ps = cs.read_or_none(ipa_mod._PRE_SCORE_KEY)
            if ps is not None and ps.topology_score:
                for idx, ni in nis:
                    labels = ni.node.metadata.labels
                    want = sum(tv_scores.get(labels.get(tk), 0)
                               for tk, tv_scores
                               in ps.topology_score.items()
                               if labels.get(tk) is not None)
                    assert out["ipa_score"][r, idx] == want

    def test_label_columns_invalidate_on_statics_gen(self):
        from kubernetes_tpu.ingest.groupcols import NodeLabelColumns
        cache = Cache()
        node = make_node("n0").capacity({"cpu": 8, "memory": "16Gi",
                                         "pods": 110}).zone("za").obj()
        cache.add_node(node)
        snapshot = Snapshot()
        cache.update_snapshot(snapshot)
        state = ClusterState()
        state.apply_snapshot(snapshot)
        cols = NodeLabelColumns(state)
        nis = [(state.node_index[ni.name], ni)
               for ni in snapshot.node_info_list]
        cols.sync(nis)
        tv1 = cols.tv(ZONE)
        assert tv1[0] != 0
        # relabel the node → full row rewrite → statics bump → fresh cols
        import dataclasses
        meta = dataclasses.replace(
            node.metadata, labels={**node.metadata.labels, ZONE: "zb"})
        new = dataclasses.replace(node, metadata=meta)
        cache.update_node(node, new)
        cache.update_snapshot(snapshot)
        state.apply_snapshot(snapshot)
        nis = [(state.node_index[ni.name], ni)
               for ni in snapshot.node_info_list]
        cols.sync(nis)
        tv2 = cols.tv(ZONE)
        assert tv2[0] != tv1[0]


# ---------------------------------------------------------------------------
# batched commit vs serial end-state parity


def _run_workload(columnar: bool, seed: int, chaos_fail: bool = False):
    api = APIServer()
    sched = Scheduler(api, batch_size=256)
    sched.feature_gates.set("ColumnarIngest", columnar)
    # re-wire the gate-dependent plumbing the ctor derived
    sched.columnar_ingest = columnar
    if not columnar:
        sched.commit_engine = None
        # rebuild handlers without the bulk echo
        api.pod_handlers.clear()
        api.node_handlers.clear()
        for attr in ("pvc_handlers", "pv_handlers", "pdb_handlers",
                     "workload_handlers"):
            if hasattr(api, attr):
                getattr(api, attr).clear()
        sched._register_event_handlers()
    rng = random.Random(seed)
    for i in range(24):
        api.create_node(make_node(f"node-{i}").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 110}).zone(
            f"zone-{i % 4}").label(HOSTNAME, f"node-{i}").obj())
    sched.prime()
    pods = []
    for i in range(120):
        w = make_pod(f"pod-{i}")
        if chaos_fail and i % 9 == 0:
            w = w.req({"cpu": "100"})      # infeasible: failure path
        else:
            w = w.req({"cpu": f"{rng.choice([250, 500])}m",
                       "memory": "512Mi"})
        if i % 7 == 0:
            w = w.label("app", "web").spread_constraint(
                5, ZONE, "ScheduleAnyway", {"app": "web"})
        pods.append(w.obj())
    for start in range(0, len(pods), 40):
        api.create_pods(pods[start:start + 40])
        sched.schedule_pending(wait=False)
    sched.schedule_pending()
    assignments = {uid: p.spec.node_name for uid, p in api.pods.items()}
    cache_dump = sched.cache.dump()
    return {
        "assignments": assignments,
        "scheduled": sched.scheduled_count,
        "unschedulable": sched.unschedulable_count,
        # NodeInfo generations are a process-global monotonic counter —
        # normalize them out before comparing two in-process runs
        "cache_nodes": {n: {k: v for k, v in d.items()
                            if k != "generation"}
                        for n, d in cache_dump["nodes"].items()},
        "assumed": cache_dump["assumed_pods"],
        "pod_count": cache_dump["pod_count"],
        "dispatcher_executed": sched.dispatcher.executed,
        "dispatcher_errors": sched.dispatcher.errors,
        "events": dict(sched.events.counts),
        "queue_len": len(sched.queue),
    }


class TestBatchedCommitParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_end_state_parity(self, seed):
        a = _run_workload(columnar=True, seed=seed)
        b = _run_workload(columnar=False, seed=seed)
        assert a == b

    def test_end_state_parity_with_failures(self):
        a = _run_workload(columnar=True, seed=5, chaos_fail=True)
        b = _run_workload(columnar=False, seed=5, chaos_fail=True)
        assert a == b

    def test_resync_parity(self):
        """resync()'s columnar re-ingest reaches the same cache/queue
        state under both gates."""
        outs = []
        for columnar in (True, False):
            api = APIServer()
            sched = Scheduler(api, batch_size=64)
            if not columnar:
                sched.columnar_ingest = False
                sched.commit_engine = None
            for i in range(12):
                api.create_node(make_node(f"n-{i}").capacity(
                    {"cpu": 8, "memory": "16Gi", "pods": 110}).obj())
            sched.prime()
            api.create_pods([make_pod(f"p-{i}").req(
                {"cpu": "500m"}).obj() for i in range(40)])
            sched.schedule_pending()
            # some pending pods that never scheduled (queue re-ingest)
            api.create_pods([make_pod(f"late-{i}").req(
                {"cpu": "100"}).obj() for i in range(5)])
            sched.resync()
            dump = sched.cache.dump()
            outs.append({
                "cache_nodes": {n: {k: v for k, v in d.items()
                                    if k != "generation"}
                                for n, d in dump["nodes"].items()},
                "assumed": dump["assumed_pods"],
                "pod_count": dump["pod_count"],
                "queue": len(sched.queue),
                "active": len(sched.queue.active_q),
            })
        assert outs[0] == outs[1]
