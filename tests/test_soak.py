"""Soak: sustained mixed churn with invariant checks every cycle.

The aux-subsystem analog of the reference's race/leak detection: drive the
scheduler through waves of creation, deletion, cordoning, preemption, gang
arrivals, and volume binds, and after EVERY wave assert the cross-layer
invariants that silent state corruption would break:

- reconcile() clean (device carry == host cache == snapshot)
- no assumed pod outlives its bind (cache.assumed_pods drains)
- no waiting pod leaks past its gang's resolution
- every bound pod's node exists and its uid appears exactly once
- scheduler counters stay consistent with the API server's bindings
"""

import random

from kubernetes_tpu.api.types import ObjectMeta, PodGroup, Workload
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _invariants(api, sched):
    assert sched.reconcile() == [], "carry/cache divergence"
    # assumed pods must all be confirmed after flush
    assert not sched.cache.assumed_pods, sched.cache.assumed_pods
    for uid, rec in sched._waiting_pods.items():
        assert uid in api.pods, f"waiting pod {uid} deleted but parked"
    bound_nodes = [p.spec.node_name for p in api.pods.values()
                   if p.spec.node_name]
    for n in bound_nodes:
        assert n in api.nodes, f"pod bound to missing node {n}"
    # cache pod view matches the API server's bound set
    cache_pods = {uid for uid, ps in sched.cache.pod_states.items()}
    api_bound = {p.uid for p in api.pods.values() if p.spec.node_name}
    assert api_bound <= cache_pods | set(sched._waiting_pods)


def test_mixed_soak():
    rng = random.Random(1234)
    api = APIServer()
    clock = Clock()
    sched = Scheduler(api, batch_size=64, clock=clock)
    for i in range(10):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": 16, "memory": "32Gi", "pods": 60})
                        .zone(f"z{i % 3}").obj())
    api.create_workload(Workload(metadata=ObjectMeta(name="gang"),
                                 pod_groups=[PodGroup(name="w", min_count=4)]))
    seq = 0
    live: list[str] = []
    for wave in range(25):
        action = rng.random()
        if action < 0.5:
            # create a mixed batch
            for _ in range(rng.randint(3, 10)):
                kind = rng.random()
                w = make_pod(f"s{seq}").req(
                    {"cpu": f"{rng.randint(1, 6) * 250}m",
                     "memory": f"{rng.randint(1, 4) * 512}Mi"})
                if kind < 0.2:
                    w = w.label("app", "x").spread_constraint(
                        2, ZONE, "DoNotSchedule", {"app": "x"})
                elif kind < 0.3:
                    w = w.priority(rng.randint(50, 100))
                elif kind < 0.4:
                    w = w.workload("gang")
                p = w.obj()
                api.create_pod(p)
                live.append(p.uid)
                seq += 1
        elif action < 0.7 and live:
            # delete a few random pods (bound or pending)
            for _ in range(rng.randint(1, 4)):
                if not live:
                    break
                uid = live.pop(rng.randrange(len(live)))
                if uid in api.pods:
                    api.delete_pod(uid)
        elif action < 0.85:
            # cordon / uncordon a node
            i = rng.randrange(10)
            node = api.nodes[f"n{i}"]
            w = make_node(f"n{i}").capacity(
                {"cpu": 16, "memory": "32Gi", "pods": 60}).zone(f"z{i % 3}")
            if not node.spec.unschedulable:
                w = w.unschedulable()
            api.update_node(w.obj())
        else:
            # time passes: backoffs expire, gang deadlines approach
            clock.t += rng.choice([5.0, 40.0, 400.0])
            sched.flush_queues()
        sched.schedule_pending()
        _invariants(api, sched)
    # drain everything outstanding
    for _ in range(6):
        clock.t += 60.0
        sched.flush_queues()
        sched.schedule_pending()
        _invariants(api, sched)
    assert api.binding_count == sched.metrics.api_dispatcher_calls.value(
        "pod_binding", "success")
