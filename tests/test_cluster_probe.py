"""`cluster_probe` (ISSUE 13): the thirteenth kernel's bit-parity gate.

The probe's contract (ops/program.py) is bit-reproducibility: every
cross-node reduction is exact int64 arithmetic, floats appear only in
elementwise division/compare, sort and gather — all deterministic
between XLA and numpy. This file holds that contract with a full numpy
oracle at 5k nodes (EXACT equality, not allclose), pins the edge cases
(empty cluster, absent resource, saturated cluster, single domain), and
proves the kernel's rails discipline: warm re-calls fit a zero retrace
budget and the whole probe runs under `jax.transfer_guard("disallow")`
on pre-staged device inputs — zero h2d beyond the resident carry.
"""

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.analysis.rails import GLOBAL as RAILS
from kubernetes_tpu.ops.program import (PROBE_STATS, PROBE_TIGHT, Carry,
                                        _PROBE_QS, cluster_probe,
                                        initial_carry)
from kubernetes_tpu.state.tensorize import NodeArrays


def _device_state(cap, used, valid, npods):
    """A minimal NodeArrays + carry pair: the probe only reads cap /
    valid / carry.used / carry.npods; every other column is a stub."""
    n = cap.shape[0]
    z32 = jnp.zeros((n, 1), jnp.int32)
    na = NodeArrays(
        cap=jnp.asarray(cap, jnp.int64),
        used=jnp.asarray(used, jnp.int64),
        nonzero_used=jnp.zeros((n, 2), jnp.int64),
        npods=jnp.asarray(npods, jnp.int32),
        allowed_pods=jnp.full((n,), 110, jnp.int32),
        valid=jnp.asarray(valid, bool),
        unschedulable=jnp.zeros((n,), bool),
        name_id=jnp.zeros((n,), jnp.int32),
        taint_key=z32, taint_val=z32, taint_eff=z32,
        label_key=z32, label_kv=z32,
        label_num=jnp.zeros((n, 1), jnp.int64),
        ports=z32, image_id=z32,
        image_size=jnp.zeros((n, 1), jnp.int64),
    )
    return na, initial_carry(na)


def _oracle(cap, used, valid, npods, dom, ndom):
    """The numpy twin of _cluster_probe_jit — same dtypes, same op
    order, so every output element must match bit-for-bit."""
    f32 = np.float32
    cap = np.asarray(cap, np.int64)
    used = np.asarray(used, np.int64)
    valid = np.asarray(valid, bool)
    part = valid[:, None] & (cap > 0)
    used_m = np.where(part, used, 0).astype(np.int64)
    cap_m = np.where(part, cap, 0).astype(np.int64)
    util = np.where(part,
                    used_m.astype(f32) / np.maximum(cap_m, 1).astype(f32),
                    f32(-1.0)).astype(f32)
    m = part.sum(axis=0).astype(np.int32)
    n_total, n_res = util.shape

    srt = np.sort(util, axis=0)
    mf = m.astype(np.float64)
    cols = []
    for q in _PROBE_QS + (1.0,):
        idx = np.floor(q * (mf - 1.0) + 0.5).astype(np.int32)
        at = np.clip(n_total - m + idx, 0, n_total - 1)
        col = srt[at, np.arange(n_res)]
        cols.append(np.where(m > 0, col, f32(0.0)).astype(f32))

    sum_used = used_m.sum(axis=0, dtype=np.int64)
    sum_cap = cap_m.sum(axis=0, dtype=np.int64)
    mean = np.where(sum_cap > 0,
                    sum_used.astype(f32) / np.maximum(sum_cap, 1).astype(f32),
                    f32(0.0)).astype(f32)

    free = cap_m - used_m
    tot_free = free.sum(axis=0, dtype=np.int64)
    max_free = free.max(axis=0)
    frag = np.where(tot_free > 0,
                    f32(1.0) - max_free.astype(f32)
                    / np.maximum(tot_free, 1).astype(f32),
                    f32(0.0)).astype(f32)

    bottleneck = np.max(np.where(part, util, f32(0.0)), axis=1)
    tight = valid & (bottleneck >= f32(PROBE_TIGHT))
    stranded_free = np.where(tight[:, None], free, 0).sum(axis=0,
                                                          dtype=np.int64)
    stranded = np.where(tot_free > 0,
                        stranded_free.astype(f32)
                        / np.maximum(tot_free, 1).astype(f32),
                        f32(0.0)).astype(f32)

    per_res = np.stack(cols + [mean, frag, stranded], axis=1).astype(f32)

    dclip = np.clip(np.asarray(dom, np.int32), 0, ndom - 1)
    dom_pods = np.zeros((ndom,), np.int64)
    np.add.at(dom_pods, dclip, np.where(valid, npods, 0).astype(np.int64))
    dom_nodes = np.zeros((ndom,), np.int64)
    np.add.at(dom_nodes, dclip, valid.astype(np.int64))
    has = dom_nodes > 0
    load = np.where(has,
                    dom_pods.astype(f32) / np.maximum(dom_nodes, 1).astype(f32),
                    f32(0.0))
    if has.any():
        dmax, dmin = load[has].max(), load[has].min()
        dom_stats = np.array([has.sum(), dmax, dmin, dmax - dmin], f32)
    else:
        dom_stats = np.zeros((4,), f32)
    return per_res, dom_stats, np.int32(valid.sum())


def _random_cluster(rng, n, r, ndom):
    """Adversarial mix: zero-capacity cells, a resource nobody
    advertises, invalid nodes, a band of saturated (tight) nodes."""
    cap = rng.integers(0, 200, size=(n, r), dtype=np.int64)
    cap[:, r - 1] = 0                       # resource with m == 0
    cap[rng.random(n) < 0.1] = 0            # nodes advertising nothing
    frac = rng.random((n, r))
    used = np.minimum((cap * frac).astype(np.int64), cap)
    tight_rows = rng.random(n) < 0.15       # saturate the bottleneck
    used[tight_rows, 0] = cap[tight_rows, 0]
    valid = rng.random(n) < 0.9
    npods = rng.integers(0, 50, size=(n,), dtype=np.int32)
    dom = rng.integers(0, ndom, size=(n,), dtype=np.int32)
    return cap, used, valid, npods, dom


def _assert_probe_matches(cap, used, valid, npods, dom, ndom):
    na, carry = _device_state(cap, used, valid, npods)
    per_res, dom_stats, count = cluster_probe(
        na, carry, jnp.asarray(dom, jnp.int32), ndom)
    o_per, o_dom, o_count = _oracle(cap, used, valid, npods, dom, ndom)
    got_per = np.asarray(per_res)
    got_dom = np.asarray(dom_stats)
    assert got_per.dtype == np.float32 and got_per.shape == (cap.shape[1], 7)
    assert np.array_equal(got_per, o_per), (
        f"per-res divergence:\nxla={got_per}\noracle={o_per}")
    assert np.array_equal(got_dom, o_dom)
    assert int(count) == int(o_count)
    return got_per


class TestClusterProbeParity:
    def test_bit_parity_vs_numpy_oracle_5k_nodes(self):
        rng = np.random.default_rng(13)
        cap, used, valid, npods, dom = _random_cluster(rng, 5000, 16, 9)
        per = _assert_probe_matches(cap, used, valid, npods, dom, 9)
        # the adversarial mix must actually exercise every stat column
        stats = dict(zip(PROBE_STATS, per.T))
        assert stats["max"].max() > 0 and stats["mean"].max() > 0
        assert stats["frag"].max() > 0 and stats["stranded"].max() > 0

    def test_bit_parity_fuzz_small_shapes(self):
        for seed in range(5):
            rng = np.random.default_rng(100 + seed)
            n = int(rng.integers(1, 64))
            ndom = int(rng.integers(1, 5))
            cap, used, valid, npods, dom = _random_cluster(rng, n, 6, ndom)
            _assert_probe_matches(cap, used, valid, npods, dom, ndom)

    def test_empty_cluster_all_invalid(self):
        n, r = 16, 4
        cap = np.full((n, r), 10, np.int64)
        used = np.zeros((n, r), np.int64)
        valid = np.zeros((n,), bool)
        per = _assert_probe_matches(cap, used, valid,
                                    np.zeros((n,), np.int32),
                                    np.zeros((n,), np.int32), 1)
        assert not per.any()

    def test_saturated_cluster_stranded_is_total(self):
        """Every node tight with free memory left: ALL free capacity is
        stranded, fragmentation matches the oracle, p50==p90==p99."""
        n = 32
        cap = np.tile(np.array([[100, 400]], np.int64), (n, 1))
        used = np.tile(np.array([[100, 100]], np.int64), (n, 1))
        valid = np.ones((n,), bool)
        per = _assert_probe_matches(cap, used, valid,
                                    np.full((n,), 5, np.int32),
                                    np.zeros((n,), np.int32), 1)
        stats = dict(zip(PROBE_STATS, per.T))
        assert stats["stranded"][1] == np.float32(1.0)
        assert stats["p50"][0] == stats["p99"][0] == np.float32(1.0)


class TestClusterProbeRails:
    def test_warm_recall_fits_zero_retrace_budget(self):
        """Same shapes + same static ndom ⇒ no fresh XLA compile — the
        per-drain sampling loop never pays a retrace inside rails
        windows after warm-up."""
        rng = np.random.default_rng(5)
        cap, used, valid, npods, dom = _random_cluster(rng, 256, 8, 3)
        na, carry = _device_state(cap, used, valid, npods)
        dom_dev = jnp.asarray(dom, jnp.int32)
        cluster_probe(na, carry, dom_dev, 3)[0].block_until_ready()  # warm
        RAILS.enable(True)
        try:
            with RAILS.retrace_budget(0, kernels=("cluster_probe",)):
                cap2, used2, valid2, npods2, dom2 = _random_cluster(
                    np.random.default_rng(6), 256, 8, 3)
                na2, carry2 = _device_state(cap2, used2, valid2, npods2)
                out = cluster_probe(na2, carry2,
                                    jnp.asarray(dom2, jnp.int32), 3)
                out[0].block_until_ready()
        finally:
            RAILS.enable(False)

    def test_probe_runs_under_transfer_guard_disallow(self):
        """Pre-staged device inputs: the probe itself moves zero bytes
        host↔device (the 'zero extra h2d' acceptance line)."""
        rng = np.random.default_rng(11)
        cap, used, valid, npods, dom = _random_cluster(rng, 128, 8, 4)
        na, carry = _device_state(cap, used, valid, npods)
        dom_dev = jnp.asarray(dom, jnp.int32)
        cluster_probe(na, carry, dom_dev, 4)[0].block_until_ready()  # warm
        with jax.transfer_guard("disallow"):
            per_res, dom_stats, count = cluster_probe(na, carry, dom_dev, 4)
            per_res.block_until_ready()
        o_per, o_dom, o_count = _oracle(cap, used, valid, npods, dom, 4)
        assert np.array_equal(np.asarray(per_res), o_per)
        assert np.array_equal(np.asarray(dom_stats), o_dom)
        assert int(count) == int(o_count)


class TestProbeRegistration:
    def test_all_kernels_ledgered_and_sanitized(self):
        from kubernetes_tpu.analysis.jaxsan import ENTRY_POINTS
        from kubernetes_tpu.perf.ledger import KERNELS
        assert "cluster_probe" in KERNELS and len(KERNELS) == 18
        assert "cluster_probe_sharded" in KERNELS
        assert "cluster_probe" in ENTRY_POINTS["kubernetes_tpu.ops.program"]
