"""jaxsan self-test + the tier-1 repo lint gate.

Two halves:

- the FIXTURE tests seed a synthetic package with one violation per rule
  class and assert every class is detected (and that a cleaned copy of
  the same package passes) — the linter's own regression harness, so a
  precision "fix" that silently lobotomizes a rule is a test failure;
- the REPO test runs the full analysis over this repository exactly like
  `tools/check.py` and fails on any unwaived finding — the CI gate the
  ISSUE ships (every existing violation fixed or explicitly waived).
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kubernetes_tpu.analysis.findings import RULES  # noqa: E402
from kubernetes_tpu.analysis.jaxsan import (JaxsanAnalyzer,  # noqa: E402
                                            analyze_tree)
from kubernetes_tpu.analysis.findings import (is_waived,  # noqa: E402
                                              parse_waivers)
from kubernetes_tpu.analysis.locks import LockChecker  # noqa: E402


# ---------------------------------------------------------------------------
# fixture package: one violation per rule class

# device-path violations, all reachable from the jit root `enter`
_DEVICE_BAD = '''
import functools
import numpy as np
import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def enter(x, sel, k):
    if x[0] > 0:                      # traced-branch
        x = x * 2
    bad = np.abs(x)                   # np-in-jit
    y = jnp.zeros(x[0])               # dynamic-shape
    return helper(x, sel, k) + bad + y.sum()


SINK = []


def helper(x, sel, k):
    SINK.append(x)                    # tracer-leak (outer container)
    acc = x
    for tag in {"a", "b", "c"}:       # nondeterministic-iteration
        acc = acc + sel
    n = int(x.sum())                  # traced-branch (host cast)
    return acc * k + n
'''

# host-side violations: donated-buffer read + set feeding tensors
_HOST_BAD = '''
import numpy as np

from .device import enter


def run_batch(cfg, na, carry, pods):
    return carry


def dispatch(cfg, na, carry, pods):
    out = run_batch(cfg, na, carry, pods)
    return np.asarray(carry)          # donation-after-use


def seed(items):
    rows = [np.array(v) for v in set(items)]   # nondeterministic-iteration
    return rows
'''

# lock-discipline violations: unguarded access + opposite nesting orders
_LOCKS_BAD = '''
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._ring = []   # guarded_by: _lock

    def push(self, v):
        self._ring.append(v)          # unguarded-shared-state

    def ok(self, v):
        with self._lock:
            self._ring.append(v)

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ba(self):                     # lock-order-cycle with ab()
        with self._b:
            with self._a:
                return 2
'''

# the same package, violations repaired — the clean tree must pass
_DEVICE_CLEAN = '''
import functools
import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def enter(x, sel, k):
    x = jnp.where(x[0] > 0, x * 2, x)
    bad = jnp.abs(x)
    y = jnp.zeros(x.shape[0])
    return helper(x, sel, k) + bad + y.sum()


def helper(x, sel, k):
    acc = x
    for tag in ("a", "b", "c"):
        acc = acc + sel
    return acc * k
'''

_HOST_CLEAN = '''
import numpy as np

from .device import enter


def run_batch(cfg, na, carry, pods):
    return carry


def dispatch(cfg, na, carry, pods):
    carry = run_batch(cfg, na, carry, pods)
    return np.asarray(carry)


def seed(items):
    rows = [np.array(v) for v in sorted(set(items))]
    return rows
'''

_LOCKS_CLEAN = '''
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._ring = []   # guarded_by: _lock

    def push(self, v):
        with self._lock:
            self._ring.append(v)

    def _push_locked(self, v):        # jaxsan: holds _lock
        self._ring.append(v)

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ab2(self):
        with self._a:
            with self._b:
                return 2
'''

ENTRIES = {"fixturepkg.device": ("enter",)}


def _write_pkg(root, device, host, locks):
    pkg = os.path.join(str(root), "fixturepkg")
    os.makedirs(pkg, exist_ok=True)
    for name, src in (("__init__.py", ""), ("device.py", device),
                      ("host.py", host), ("locks.py", locks)):
        with open(os.path.join(pkg, name), "w") as f:
            f.write(textwrap.dedent(src))
    return str(root)


@pytest.fixture()
def bad_tree(tmp_path):
    return _write_pkg(tmp_path, _DEVICE_BAD, _HOST_BAD, _LOCKS_BAD)


@pytest.fixture()
def clean_tree(tmp_path):
    return _write_pkg(tmp_path, _DEVICE_CLEAN, _HOST_CLEAN, _LOCKS_CLEAN)


class TestFixtureDetection:
    def test_all_rule_classes_detected(self, bad_tree):
        findings = analyze_tree(bad_tree, package="fixturepkg",
                                entry_points=ENTRIES)
        live = [f for f in findings if not f.waived]
        rules = {f.rule for f in live}
        expected = {"traced-branch", "np-in-jit", "dynamic-shape",
                    "tracer-leak", "donation-after-use",
                    "nondeterministic-iteration",
                    "unguarded-shared-state", "lock-order-cycle"}
        assert expected <= rules, f"missed: {expected - rules}"
        # the acceptance bar: >= 8 distinct rule classes from one seeded
        # violation each
        assert len(rules & expected) >= 8
        # every rule in the registry has a fixture violation — adding a
        # rule without a fixture is itself a failure
        assert set(RULES) <= rules

    def test_findings_carry_location_and_hint(self, bad_tree):
        findings = [f for f in analyze_tree(bad_tree, package="fixturepkg",
                                            entry_points=ENTRIES)
                    if not f.waived]
        for f in findings:
            assert f.path.startswith("fixturepkg")
            assert f.line >= 1
            assert f.hint, f"no fix-it hint for {f.rule}"
        # file:line formatting (the editor-clickable contract)
        text = findings[0].format(fix_hints=True)
        assert ":" in text and "fix:" in text

    def test_clean_tree_passes(self, clean_tree):
        findings = analyze_tree(clean_tree, package="fixturepkg",
                                entry_points=ENTRIES)
        live = [f for f in findings if not f.waived]
        assert live == [], [f.format() for f in live]

    def test_static_param_branch_is_not_flagged(self, tmp_path):
        # branching on a STATIC argname is the intended kernel-trimming
        # idiom — the discrimination the whole analyzer exists for
        root = _write_pkg(tmp_path, '''
import functools
import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("flag",))
def enter(x, flag):
    if flag:
        return x * 2
    return x
''', "", "")
        findings = [f for f in analyze_tree(root, package="fixturepkg",
                                            entry_points=ENTRIES)
                    if not f.waived and f.rule == "traced-branch"]
        assert findings == [], [f.format() for f in findings]

    def test_entry_coverage_lost_is_reported(self, bad_tree):
        an = JaxsanAnalyzer(bad_tree, package="fixturepkg",
                            entry_points={"fixturepkg.device": ("enter",),
                                          "fixturepkg.host": ("gone",)})
        an.load()
        an.run()
        missing = an.check_entry_coverage()
        assert "fixturepkg.host.gone" in missing
        assert "fixturepkg.device.enter" not in missing


class TestWaivers:
    def test_waiver_suppresses_named_rule(self, tmp_path):
        device = _DEVICE_BAD.replace(
            "bad = np.abs(x)                   # np-in-jit",
            "bad = np.abs(x)  # jaxsan: waive[np-in-jit] fixture baseline")
        root = _write_pkg(tmp_path, device, _HOST_BAD, _LOCKS_BAD)
        findings = analyze_tree(root, package="fixturepkg",
                                entry_points=ENTRIES)
        np_findings = [f for f in findings if f.rule == "np-in-jit"]
        assert np_findings and all(f.waived for f in np_findings)
        # other rules on other lines stay live
        assert any(not f.waived and f.rule == "traced-branch"
                   for f in findings)

    def test_waiver_star_and_line_above(self):
        w = parse_waivers("x = 1  # jaxsan: waive[*]\n"
                          "y = foo()\n"
                          "z = 2  # jaxsan: waive[a, b]\n")
        assert is_waived(w, 1, "anything")
        assert is_waived(w, 2, "anything")      # covers the line below
        assert is_waived(w, 3, "a") and is_waived(w, 3, "b")
        assert not is_waived(w, 3, "c")
        assert not is_waived(w, 5, "a")

    def test_holds_annotation_treats_body_as_guarded(self, tmp_path):
        locks = _LOCKS_CLEAN + '''

class Uses:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0   # guarded_by: _lock

    def bump(self):   # jaxsan: holds _lock
        self._n += 1
'''
        root = _write_pkg(tmp_path, _DEVICE_CLEAN, _HOST_CLEAN, locks)
        findings = [f for f in analyze_tree(root, package="fixturepkg",
                                            entry_points=ENTRIES)
                    if not f.waived]
        assert findings == [], [f.format() for f in findings]


class TestCheckCli:
    """tools/check.py exit-code contract, driven on the small fixture
    tree (subprocess — the exact CI invocation)."""

    def _run(self, root, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check.py"),
             "--root", root, "--package", "fixturepkg",
             "--entries", "fixturepkg.device:enter", *args],
            capture_output=True, text=True)

    def test_dirty_tree_exits_1_with_findings(self, bad_tree):
        r = self._run(bad_tree)
        assert r.returncode == 1
        assert "np-in-jit" in r.stdout
        assert "fixturepkg" in r.stdout

    def test_clean_tree_exits_0(self, clean_tree):
        r = self._run(clean_tree)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_fix_hints_flag(self, bad_tree):
        r = self._run(bad_tree, "--fix-hints")
        assert "fix:" in r.stdout

    def test_json_output(self, bad_tree):
        import json
        r = self._run(bad_tree, "--json")
        payload = json.loads(r.stdout)
        assert payload["findings"]
        assert {"rule", "path", "line", "message", "hint"} <= set(
            payload["findings"][0])


class TestRepoGate:
    """The tier-1 gate: this repository must lint clean."""

    def test_repo_has_zero_unwaived_findings(self):
        findings = analyze_tree(REPO)
        live = [f for f in findings if not f.waived]
        assert live == [], "\n" + "\n".join(f.format() for f in live)

    def test_all_declared_entries_have_jit_coverage(self):
        an = JaxsanAnalyzer(REPO).load()
        an.run()
        assert an.check_entry_coverage() == []
        # the declared entry set is exactly the ledger's kernel surface
        names = {n for mod, ns in an.entry_points.items() for n in ns}
        assert names == {"run_batch", "run_uniform", "run_wave",
                         "run_wave_scan", "run_plan", "wave_statics",
                         "diagnose_row", "dry_run_select_victims",
                         "run_batch_sharded", "run_uniform_sharded",
                         "run_plan_sharded", "run_gang_sharded",
                         "scatter_rows_sharded", "cluster_probe_sharded",
                         "run_gang", "scatter_rows",
                         "explain_row", "cluster_probe"}

    def test_threaded_subsystems_are_annotated(self):
        """The lock checker's input contract: the shared rings/queues of
        the threaded subsystems declare their lock."""
        import ast
        an = JaxsanAnalyzer(REPO).load()
        ck = LockChecker(an.modules)
        declared = {}
        for mi in an.modules.values():
            lines = mi.source.splitlines()
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.ClassDef):
                    info = ck._collect(node, lines, mi.path)
                    if info.guarded:
                        declared[f"{mi.name}.{node.name}"] = set(info.guarded)
        assert "_events" in declared["kubernetes_tpu.events.EventRecorder"]
        assert "ring" in declared["kubernetes_tpu.events.FlightRecorder"]
        assert "_queue" in declared[
            "kubernetes_tpu.backend.dispatcher.APIDispatcher"]
        assert "_ring" in declared[
            "kubernetes_tpu.perf.profiler.HostProfiler"]
