"""End-to-end cross-path parity fuzz: mixed constraints, fast vs scan.

The scheduler has three execution tiers — closed-form uniform runs, the
sequential device scan, and the host oracle. The per-kernel suites prove
pairwise parity; this fuzz drives the FULL scheduler over randomized mixed
workloads (resources, taints/tolerations, node affinity, spread, inter-pod
(anti-)affinity, images, priorities) twice — fast paths enabled vs scan
forced — and requires bit-identical bind maps plus a clean reconcile. Any
routing bug (signature runs, group-family gating, profile caching,
fallback ordering) shows up as a divergent placement here.
"""

import random

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

MB = 1024 * 1024
ZONE = "topology.kubernetes.io/zone"


def _build_cluster(api, rng):
    n_nodes = rng.randint(6, 20)
    for i in range(n_nodes):
        w = (make_node(f"n{i}")
             .capacity({"cpu": rng.randint(4, 32),
                        "memory": f"{rng.randint(8, 64)}Gi",
                        "pods": rng.randint(8, 40)})
             .zone(f"z{i % 3}")
             .label("kubernetes.io/hostname", f"n{i}"))
        if i % 4 == 0:
            w = w.label("disk", "ssd")
        if i % 5 == 1:
            w = w.taint("dedicated", "infra", "NoSchedule")
        if i % 6 == 2:
            w = w.image("app:v1", rng.randint(100, 900) * MB)
        api.create_node(w.obj())
    return n_nodes


def _make_workload(rng, count):
    pods = []
    for i in range(count):
        kind = rng.random()
        w = make_pod(f"p{i}").req({"cpu": f"{rng.randint(1, 6) * 250}m",
                                   "memory": f"{rng.randint(1, 6) * 256}Mi"})
        if kind < 0.35:
            pass                                   # plain (uniform runs)
        elif kind < 0.5:
            w = w.label("app", "web").spread_constraint(
                rng.randint(1, 3), ZONE, "DoNotSchedule", {"app": "web"})
        elif kind < 0.6:
            w = (w.label("tier", "db")
                 .pod_affinity(ZONE, {"tier": "db"}, anti=True))
        elif kind < 0.7:
            w = w.node_affinity_in("disk", ["ssd"])
        elif kind < 0.8:
            w = w.toleration(key="dedicated", value="infra")
        elif kind < 0.9:
            p = w.obj()
            p.spec.containers[0].image = "app:v1"
            p.spec.priority = rng.randint(0, 5)
            pods.append(p)
            continue
        else:
            w = w.node_selector({ZONE: f"z{rng.randint(0, 2)}"})
        p = w.obj()
        p.spec.priority = rng.randint(0, 5)
        pods.append(p)
    return pods


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _run(seed, fast):
    # deterministic clock: retry/backoff timing must not depend on how
    # fast each execution tier happens to run on the test machine
    rng = random.Random(seed)
    api = APIServer()
    clock = _Clock()
    sched = Scheduler(api, batch_size=128, clock=clock)
    if not fast:
        sched.UNIFORM_RUN_MIN = 10 ** 9     # force the sequential scan
    _build_cluster(api, rng)
    pods = _make_workload(rng, rng.randint(40, 90))
    # arrive in waves so runs, carries, group reseeds, and backoff-driven
    # retries all exercise
    for lo in range(0, len(pods), 30):
        for p in pods[lo:lo + 30]:
            api.create_pod(p)
        sched.schedule_pending()
        clock.t += 30.0
        sched.flush_queues()
        sched.schedule_pending()
    assert sched.reconcile() == []
    return ({p.name: p.spec.node_name for p in api.pods.values()},
            sched.scheduled_count)


@pytest.mark.parametrize("seed", range(8))
def test_mixed_workload_fast_equals_scan(seed):
    fast_map, fast_bound = _run(seed, fast=True)
    scan_map, scan_bound = _run(seed, fast=False)
    assert fast_bound == scan_bound
    assert fast_map == scan_map
