"""Continuous host profiling + compile ledger (ISSUE 5 tentpole).

Covers perf/profiler.py (sampling, phase attribution, exports), the
compile ledger (perf/ledger.py: per-kernel compiles, warm-run stability,
h2d accounting), the scheduler wiring (drain ids across logs/spans/
flight/events, hot frames on slow drains, dispatcher_inflight), the
/debug/hostprofile + /debug/compileledger endpoints, and the slow-marked
profiler overhead gate.
"""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.config import KubeSchedulerConfiguration
from kubernetes_tpu.perf.ledger import GLOBAL as LEDGER
from kubernetes_tpu.perf.ledger import CompileLedger
from kubernetes_tpu.perf.profiler import HostProfiler, _pow2_bucket
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.server import SchedulerServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.utils.tracing import PhaseTrack


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


def _cluster(nodes=8, config=None, batch_size=128):
    api = APIServer()
    sched = Scheduler(api, batch_size=batch_size, config=config)
    for i in range(nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
    return api, sched


def _feed(api, n, start=0, cpu="100m"):
    api.create_pods([make_pod(f"p{start + i}").req(
        {"cpu": cpu, "memory": "64Mi"}).obj() for i in range(n)])


class TestPhaseTrack:
    def test_stack_semantics(self):
        t = PhaseTrack()
        assert t.current() == ""
        t.push("host_build")
        with t.scope("host_tensorize"):
            assert t.current() == "host_tensorize"
        assert t.current() == "host_build"
        t.pop()
        assert t.current() == ""
        t.pop()   # over-pop is a no-op, never raises

    def test_scope_pops_on_exception(self):
        t = PhaseTrack()
        with pytest.raises(RuntimeError):
            with t.scope("commit"):
                raise RuntimeError("boom")
        assert t.current() == ""


class TestLogContext:
    def test_context_appended_and_restored(self):
        import logging

        from kubernetes_tpu.utils.logging import klog, log_context
        records = []
        h = logging.Handler()
        h.emit = lambda rec: records.append(rec.getMessage())
        logger = logging.getLogger("kubernetes_tpu")
        old_level = logger.level
        logger.setLevel(logging.INFO)
        logger.addHandler(h)
        try:
            with log_context(drain=17):
                klog.info("batch committed", pods=3)
                with log_context(drain=18):
                    klog.info("nested")
            klog.info("outside")
        finally:
            logger.removeHandler(h)
            logger.setLevel(old_level)
        assert records[0] == "batch committed pods=3 drain=17"
        assert records[1] == "nested drain=18"
        assert records[2] == "outside"

    def test_explicit_kv_wins_over_context(self):
        from kubernetes_tpu.utils.logging import _fmt, log_context
        with log_context(drain=1):
            assert _fmt("m", {"drain": 9}) == "m drain=9"


class TestHostProfiler:
    def _profiled(self, phases):
        """Deterministic samples: inject the current frame under each
        phase a known number of times."""
        import sys
        track = PhaseTrack()
        prof = HostProfiler(hz=100, phase_fn=track.current)
        for phase, count in phases:
            with track.scope(phase):
                for _ in range(count):
                    assert prof.sample_once(frame=sys._getframe())
        return prof

    def test_counts_and_phase_shares(self):
        prof = self._profiled([("host_tensorize", 30), ("commit", 10)])
        assert prof.sample_count == 40
        shares = prof.phase_shares()
        assert shares["host_tensorize"] == pytest.approx(0.75)
        assert shares["commit"] == pytest.approx(0.25)

    def test_collapsed_format(self):
        prof = self._profiled([("commit", 3)])
        text = prof.collapsed()
        lines = [ln for ln in text.splitlines() if ln]
        assert lines
        for ln in lines:
            stack, _, count = ln.rpartition(" ")
            assert int(count) > 0
            assert stack.split(";")[0].startswith("commit")
        # this very function is on the sampled stack
        assert "test_collapsed_format" in text

    def test_frame_table_and_top_frames(self):
        prof = self._profiled([("commit", 5)])
        table = prof.frame_table()
        assert table
        leaf = table[0]
        assert leaf["self"] >= 1 and leaf["cum"] >= leaf["self"]
        # cum of the root frame covers every sample
        assert any(row["cum"] == 5 for row in table) or \
            sum(r["self"] for r in table) == 5
        top = prof.top_frames(2)
        assert len(top) <= 2 and all("/" in t for t in top)

    def test_speedscope_shape(self):
        prof = self._profiled([("device", 4)])
        doc = prof.speedscope()
        assert doc["profiles"][0]["type"] == "sampled"
        assert len(doc["profiles"][0]["samples"]) == \
            len(doc["profiles"][0]["weights"])
        nframes = len(doc["shared"]["frames"])
        for sample in doc["profiles"][0]["samples"]:
            assert all(0 <= i < nframes for i in sample)
        assert sum(doc["profiles"][0]["weights"]) == 4

    def test_seconds_window(self):
        import sys
        prof = HostProfiler(hz=100)
        prof.sample_once(frame=sys._getframe())
        # a sample stamped "now" is inside any recent window ...
        assert prof.aggregate(seconds=5).total == 1
        # ... and outside a window that ended in the past
        assert prof.aggregate(seconds=-5).total == 0

    def test_pow2_bucket(self):
        assert [_pow2_bucket(n) for n in (0, 1, 2, 3, 4, 5, 9)] == \
            [0, 1, 2, 4, 4, 8, 16]

    def test_bucket_tagging(self):
        import sys
        cell = [3]
        prof = HostProfiler(hz=100, bucket_fn=lambda: cell[0])
        prof.sample_once(frame=sys._getframe())
        ((phase, bucket, _stack), n), = prof.aggregate().counts.items()
        assert (phase, bucket, n) == ("other", 4, 1)

    def test_phase_shares_agree_with_wall_clock(self):
        """ISSUE 5 satellite: per-phase sample shares track the phases'
        wall-clock shares on a synthetic two-phase workload (2:1)."""
        track = PhaseTrack()
        prof = HostProfiler(hz=200, phase_fn=track.current)
        prof.ensure_running()
        wall = {}
        try:
            for phase, dur in (("host_tensorize", 0.5), ("commit", 0.25)):
                t0 = time.perf_counter()
                with track.scope(phase):
                    while time.perf_counter() - t0 < dur:
                        sum(range(500))   # busy: hold a real stack
                wall[phase] = time.perf_counter() - t0
        finally:
            prof.stop()
        shares = prof.phase_shares()
        got = shares.get("host_tensorize", 0.0)
        other = shares.get("commit", 0.0)
        assert got + other > 0, "sampler collected nothing"
        sampled_ratio = got / (got + other)
        wall_ratio = wall["host_tensorize"] / (wall["host_tensorize"]
                                               + wall["commit"])
        assert abs(sampled_ratio - wall_ratio) < 0.2

    def test_thread_lifecycle(self):
        prof = HostProfiler(hz=500)
        prof.ensure_running()
        assert prof.running
        time.sleep(0.05)
        prof.stop()
        assert not prof.running
        assert prof.sample_count > 0


class TestCompileLedger:
    class _FakeJit:
        """Callable with jax's _cache_size surface: 'compiles' on first
        call per distinct arg."""

        def __init__(self):
            self.seen = set()

        def __call__(self, x):
            self.seen.add(x)
            return x

        def _cache_size(self):
            return len(self.seen)

    def test_compiles_and_retraces(self):
        led = CompileLedger()
        fn = self._FakeJit()
        led.measured_call("k", fn, "shape-a")
        led.measured_call("k", fn, "shape-a")   # cached: no compile
        led.measured_call("k", fn, "shape-b")   # retrace
        rec = led.kernels["k"]
        assert rec.calls == 3
        assert rec.compiles == 2
        assert rec.retraces == 1
        assert rec.compile_seconds >= 0.0
        snap = led.snapshot()
        assert snap["kernels"]["k"]["retraces"] == 1
        assert snap["totalCompiles"] == 2

    def test_donation_miss_probe(self):
        led = CompileLedger()
        fn = self._FakeJit()

        class Arr:
            def __init__(self, deleted):
                self._d = deleted

            def is_deleted(self):
                return self._d

        led.measured_call("k", fn, "a", donated=Arr(True))    # consumed
        led.measured_call("k", fn, "b", donated=Arr(False))   # miss
        led.measured_call("k", fn, "c", donated=None)
        assert led.kernels["k"].donation_misses == 1

    def test_h2d_accounting(self):
        import numpy as np
        led = CompileLedger()
        led.note_h2d("host_cache", 100)
        led.note_h2d("host_cache", 20)
        led.note_h2d_tree("host_snapshot",
                          (np.zeros(4, np.int64), np.zeros(2, np.int32)))
        assert led.h2d == {"host_cache": 120, "host_snapshot": 40}


class TestSchedulerProfiling:
    def _run_until_sampled(self, api, sched, deadline_s=20.0):
        """Schedule batches until the profiler holds phase-tagged samples
        (the sampler is asynchronous; more drains = more chances)."""
        start = time.time()
        base = 0
        while time.time() - start < deadline_s:
            _feed(api, 256, start=base)
            base += 256
            sched.schedule_pending()
            shares = sched.profiler.phase_shares()
            if any(p != "other" for p in shares):
                return shares
        raise AssertionError("no phase-tagged samples within deadline")

    def test_profiler_on_by_default_and_samples_drains(self):
        api, sched = _cluster(nodes=32)
        assert sched.profiler is not None
        assert not sched.profiler.running   # lazy: starts on first drain
        shares = self._run_until_sampled(api, sched)
        assert sched.profiler.running
        # phase names come from the drain pipeline's PhaseTrack marks
        known = {"host_build", "host_snapshot", "host_tensorize",
                 "host_group_seed", "host_cache", "device", "commit",
                 "other"}
        assert set(shares) <= known

    def test_gate_off_disables(self):
        cfg = KubeSchedulerConfiguration(
            feature_gates={"ContinuousHostProfiling": False})
        api, sched = _cluster(config=cfg)
        assert sched.profiler is None
        _feed(api, 8)
        assert sched.schedule_pending() == 8

    def test_hz_zero_disables(self):
        cfg = KubeSchedulerConfiguration(host_profiler_hz=0)
        api, sched = _cluster(config=cfg)
        assert sched.profiler is None

    def test_hz_knob_round_trip_and_validation(self):
        cfg = KubeSchedulerConfiguration(host_profiler_hz=97.0)
        cfg.validate()
        again = KubeSchedulerConfiguration.from_dict(cfg.to_dict())
        assert again.host_profiler_hz == 97.0
        assert KubeSchedulerConfiguration().to_dict()["hostProfilerHz"] \
            == 200.0
        with pytest.raises(ValueError, match="hostProfilerHz"):
            KubeSchedulerConfiguration(host_profiler_hz=-1).validate()
        api, sched = _cluster(config=cfg)
        assert sched.profiler.hz == 97.0

    def test_hostprofile_and_compileledger_endpoints(self):
        api, sched = _cluster(nodes=32)
        self._run_until_sampled(api, sched)
        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/hostprofile")
            assert code == 200 and body.strip()
            line = body.strip().splitlines()[0]
            stack, _, count = line.rpartition(" ")
            assert int(count) > 0 and ";" in stack

            code, body = _get(srv.port,
                              "/debug/hostprofile?format=speedscope"
                              "&seconds=300")
            doc = json.loads(body)
            assert doc["profiles"][0]["samples"]

            code, body = _get(srv.port, "/debug/compileledger")
            led = json.loads(body)
            assert "run_uniform" in led["kernels"] \
                or "run_batch" in led["kernels"]
            assert led["h2dBytes"].get("host_snapshot", 0) > 0
        finally:
            srv.stop()

    def test_hostprofile_endpoint_404_when_off(self):
        cfg = KubeSchedulerConfiguration(
            feature_gates={"ContinuousHostProfiling": False})
        api, sched = _cluster(config=cfg)
        srv = SchedulerServer(sched).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, "/debug/hostprofile")
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_compile_ledger_stable_across_warm_rerun(self):
        """ISSUE 5 satellite: identical shapes on a fresh scheduler must
        mint ZERO new executables (no hidden retraces)."""

        def run():
            api, sched = _cluster(nodes=16, batch_size=128)
            _feed(api, 256)
            assert sched.schedule_pending() == 256

        run()   # possibly-cold pass (this process may already be warm)
        before = {k: r.compiles for k, r in LEDGER.kernels.items()}
        run()   # warm re-run: identical node bucket / batch bucket / L,K,J
        after = {k: r.compiles for k, r in LEDGER.kernels.items()}
        assert after == before

    def test_drain_ids_across_flight_and_events(self):
        api, sched = _cluster(nodes=8)
        _feed(api, 64)
        api.create_pod(make_pod("huge").req(
            {"cpu": "500", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        records = sched.flight.dump()
        ids = [r["drainId"] for r in records]
        assert ids and ids == sorted(ids) and ids[0] >= 1
        dump = sched.events.dump()
        sched_ids = {e["drainId"] for e in dump["events"]
                     if e["reason"] == "Scheduled"}
        fail_ids = {e["drainId"] for e in dump["events"]
                    if e["reason"] == "FailedScheduling"}
        assert sched_ids and sched_ids <= set(ids)
        assert fail_ids and fail_ids <= set(ids)
        # span attribution: drain id rides the host_build span attrs
        from kubernetes_tpu.utils.tracing import Tracer
        tr = Tracer(slow_threshold_s=float("inf"), keep_recent=64)
        sched.tracer = tr
        _feed(api, 32, start=100000)
        sched.schedule_pending()
        hb = next(sp for root in tr.recent
                  for sp in [root.find("host_build")] if sp is not None)
        assert hb.attributes["drain"] in [r["drainId"]
                                          for r in sched.flight.dump()]

    def test_hot_frames_attached_to_slow_drains(self):
        api, sched = _cluster(nodes=16)
        self._run_until_sampled(api, sched)
        sched.profiler.slow_drain_s = 0.0   # every drain counts as slow
        _feed(api, 256, start=200000)
        sched.schedule_pending()
        rec = sched.flight.dump()[-1]
        assert isinstance(rec["hotFrames"], list)
        assert rec["hotFrames"], "no hot frames despite live sampler"
        assert all("/" in f for f in rec["hotFrames"])

    def test_dispatcher_inflight_gauge(self):
        api, sched = _cluster(nodes=8)
        _feed(api, 16)
        sched.schedule_pending()
        text = sched.metrics.exposition()
        assert 'scheduler_dispatcher_inflight{kind="api_calls"} 0' in text
        assert 'scheduler_dispatcher_inflight{kind="drains"} 0' in text
        # live depth while calls are queued
        from kubernetes_tpu.backend.dispatcher import APICall, CallType
        sched.dispatcher.add(APICall(
            CallType.STATUS_PATCH, make_pod("x").obj(), condition={}))
        assert sched._inflight_depths()[("api_calls",)] == 1.0
        sched.dispatcher.flush()

    def test_xla_and_h2d_series_in_exposition(self):
        api, sched = _cluster(nodes=8)
        _feed(api, 64)
        sched.schedule_pending()
        text = sched.metrics.exposition()
        assert 'scheduler_xla_compiles_total{kernel="run_uniform"}' in text
        assert 'scheduler_xla_compile_seconds{kernel="run_uniform"}' in text
        assert 'scheduler_h2d_bytes_total{phase="host_snapshot"}' in text
        # the ledger mirror carries real observations, not just seeds
        snap = LEDGER.snapshot()
        assert snap["h2dBytes"].get("host_snapshot", 0) > 0


@pytest.mark.slow
class TestProfilerOverheadGate:
    def test_overhead_within_5_percent_at_5k_nodes(self):
        """ISSUE 5 acceptance: a SchedulingBasic-shaped 5k-node drain with
        the profiler ON stays within 5% of profiler-OFF throughput
        (median of 3 measured passes each, warm shapes)."""

        def one_pass(gate_on):
            cfg = KubeSchedulerConfiguration(feature_gates={
                "ContinuousHostProfiling": gate_on})
            api = APIServer()
            sched = Scheduler(api, batch_size=8192, config=cfg)
            for i in range(5000):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
            sched.prime()
            t0 = time.perf_counter()
            created = 0
            while created < 10000:
                _feed(api, 512, start=created)
                created += 512
                sched.schedule_pending(wait=False)
            sched.schedule_pending()
            dt = time.perf_counter() - t0
            assert sched.scheduled_count == created
            return created / dt

        one_pass(False)   # warm every executable outside the measurement
        off = sorted(one_pass(False) for _ in range(3))[1]
        on = sorted(one_pass(True) for _ in range(3))[1]
        assert on >= 0.95 * off, (
            f"profiler overhead gate: on={on:.0f} off={off:.0f} pods/s "
            f"({on / off - 1:+.1%})")
